"""Benchmark suite: the reference's asv workloads on this framework.

Parity target: BASELINE.md / the reference's asv_bench —
``time_reduce`` (reduce.py:12-117), ``time_reduce_bare`` (reduce.py:88-104),
``time_quantile`` (reduce.py:146-161), cohort-detection timing and
graph-size-style metrics (cohorts.py:40-81), and the synthetic workloads
(ERA5 day-of-year, PerfectMonthly, OISST, NWM county zonal stats,
RandomBigArray).

Run: ``python benchmarks.py [--scale small|full] [--engine jax|numpy]``.
Prints one JSON line per benchmark:
``{"bench": ..., "value": ..., "unit": ...}``.
``bench.py`` remains the single-line headline benchmark for the driver.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _timeit(fn, reps=3):
    fn()  # warm (compile)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times)


def _block(x):
    if hasattr(x, "block_until_ready"):
        x.block_until_ready()
    return x


def bench_reduce(engine: str):
    """time_reduce parity: N=3000, 1-D and 2-D, core func sweep."""
    from flox_tpu import groupby_reduce

    n = 3000
    rng = np.random.default_rng(0)
    labels = np.repeat(np.arange(5), n // 5)
    out = []
    for shape_name, vals in [("1d", rng.normal(size=n)), ("2d", rng.normal(size=(5, n)))]:
        for func in ["sum", "nansum", "mean", "nanmean", "max", "nanmax", "count"]:
            t = _timeit(lambda: _block(groupby_reduce(vals, labels, func=func, engine=engine)[0]))
            out.append({"bench": f"time_reduce[{shape_name}-{func}-{engine}]", "value": round(t * 1e3, 3), "unit": "ms"})
    return out


def bench_reduce_bare(engine: str):
    """time_reduce_bare parity: the engine kernel alone."""
    from flox_tpu.aggregations import generic_aggregate

    n = 3000
    rng = np.random.default_rng(0)
    labels = np.repeat(np.arange(5), n // 5)
    vals = rng.normal(size=n)
    out = []
    for func in ["nansum", "nanmean", "nanmax", "nanlen"]:
        t = _timeit(
            lambda: _block(
                generic_aggregate(labels, vals, engine=engine, func=func, size=5, fill_value=0)
            )
        )
        out.append({"bench": f"time_reduce_bare[{func}-{engine}]", "value": round(t * 1e3, 3), "unit": "ms"})
    return out


def bench_quantile(engine: str, scale: str):
    """time_quantile parity: q=0.9 yearly resample of a (T, 25, 25) array."""
    from flox_tpu import groupby_reduce

    nt = 31411 if scale == "full" else 4000
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(25, 25, nt))
    years = (np.arange(nt) // 365).astype(np.int64)
    t = _timeit(
        lambda: _block(
            groupby_reduce(vals, years, func="quantile", engine=engine, finalize_kwargs={"q": 0.9})[0]
        )
    )
    return [{"bench": f"time_quantile[{engine}]", "value": round(t * 1e3, 2), "unit": "ms"}]


def _era5_labels(scale: str):
    nt = 26304 if scale == "full" else 8760
    day = ((np.arange(nt) // 24) % 365).astype(np.int64)
    return nt, day


def bench_era5_dayofyear(engine: str, scale: str):
    """ERA5 day-of-year climatology (scaled spatial grid)."""
    from flox_tpu import groupby_reduce

    nt, day = _era5_labels(scale)
    nspace = 72 * 144 if scale == "full" else 24 * 48
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(nspace, nt)).astype(np.float32)
    t = _timeit(lambda: _block(groupby_reduce(vals, day, func="nanmean", engine=engine)[0]))
    gbps = vals.nbytes / t / 1e9
    return [{"bench": f"era5_dayofyear[{engine}]", "value": round(gbps, 2), "unit": "GB/s"}]


def bench_era5_resampling(engine: str, scale: str):
    """ERA5 hourly->daily resampling (reference cohorts.py:119-132): many
    output groups (365/y), each spanning exactly 24 consecutive steps."""
    from flox_tpu import groupby_reduce

    nyears = 5 if scale == "full" else 1
    nt = nyears * 365 * 24
    nspace = 37 * 72 if scale == "full" else 24 * 24
    day = (np.arange(nt) // 24).astype(np.int64)
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(nspace, nt)).astype(np.float32)
    t = _timeit(lambda: _block(groupby_reduce(vals, day, func="mean", engine=engine)[0]))
    gbps = vals.nbytes / t / 1e9
    return [{"bench": f"era5_resampling[{engine}]", "value": round(gbps, 2), "unit": "GB/s"}]


def bench_nwm_zonal(engine: str, scale: str):
    """NWM county zonal stats: 2-D labels, ~900 groups (cohorts.py:84-97)."""
    from flox_tpu import groupby_reduce

    side = 1500 if scale == "full" else 400
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 900, size=(side, side))
    vals = rng.normal(size=(side, side)).astype(np.float32)
    t = _timeit(lambda: _block(groupby_reduce(vals, labels, func="nanmean", engine=engine)[0]))
    return [{"bench": f"nwm_zonal_stats[{engine}]", "value": round(t * 1e3, 2), "unit": "ms"}]


def bench_random_big(engine: str, scale: str):
    """RandomBigArray map-reduce stress (scaled; cohorts.py:242-248)."""
    from flox_tpu import groupby_reduce

    nt = 100_000 if scale == "full" else 20_000
    nspace = 2000 if scale == "full" else 200
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 5000, size=nt)
    vals = rng.normal(size=(nspace, nt)).astype(np.float32)
    t = _timeit(lambda: _block(groupby_reduce(vals, labels, func="nansum", engine=engine)[0]))
    gbps = vals.nbytes / t / 1e9
    return [{"bench": f"random_big_array[{engine}]", "value": round(gbps, 2), "unit": "GB/s"}]


def bench_fused(engine: str, scale: str):
    """fused_sweep_gbps: groupby_aggregate_many's one-pass multi-statistic
    dispatch vs N sequential groupby_reduce passes on the climatology
    family set (impl_sweep_gbps style — GB/s against ONE logical read of
    the bytes for both, so the sequential row shows the bytes-touched
    penalty directly). The measurements feed the "fused" autotune family."""
    from flox_tpu import groupby_aggregate_many, groupby_reduce

    funcs = ("mean", "var", "min", "max")
    nt = 8760 if scale == "full" else 2000
    rows = 64 if scale == "full" else 16
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(rows, nt)).astype(np.float32)
    labels = (np.arange(nt) // 31) % 12
    nbytes = vals.nbytes

    def run_fused():
        outs, _ = groupby_aggregate_many(vals, labels, funcs=funcs, engine=engine)
        for v in outs.values():
            _block(v)

    def run_seq():
        for f in funcs:
            _block(groupby_reduce(vals, labels, func=f, engine=engine)[0])

    t_fused = _timeit(run_fused)
    t_seq = _timeit(run_seq)
    out = [
        {"bench": f"fused_sweep_gbps[fused-{engine}]",
         "value": round(nbytes / t_fused / 1e9, 3), "unit": "GB/s"},
        {"bench": f"fused_sweep_gbps[sequential-{engine}]",
         "value": round(nbytes / t_seq / 1e9, 3), "unit": "GB/s"},
        {"bench": f"fused_speedup[{engine}]",
         "value": round(t_seq / t_fused, 2), "unit": "x"},
    ]
    if engine == "jax":
        # only device-path measurements feed the dispatch family: the
        # store keys carry no engine axis, and host-numpy ratios say
        # nothing about the jax fused-vs-sequential decision
        try:
            from flox_tpu import autotune

            for cand, t in (("fused", t_fused), ("sequential", t_seq)):
                autotune.record(
                    "fused", cand, nbytes / t / 1e9, dtype=str(vals.dtype),
                    ngroups=12, nelems=vals.size, source="bench",
                )
        except Exception:  # noqa: BLE001 — recording is best-effort
            pass
    return out


def bench_mesh_methods(scale: str):
    """Mesh execution-method comparison (the analogue of the reference's
    time_combine: _simple_combine vs _grouped_combine, combine.py:27-77 —
    here the combine strategies are whole SPMD programs)."""
    from flox_tpu import groupby_reduce
    from flox_tpu.parallel import make_mesh

    mesh = make_mesh()
    n = 500_000 if scale == "full" else 100_000
    rng = np.random.default_rng(0)
    labels = np.tile(np.arange(366), n // 366 + 1)[:n]
    vals = rng.normal(size=(8, n)).astype(np.float32)
    out = []
    for method in ["map-reduce", "cohorts"]:
        t = _timeit(
            lambda: _block(
                groupby_reduce(vals, labels, func="nanmean", method=method, mesh=mesh)[0]
            )
        )
        out.append({"bench": f"time_mesh_combine[{method}]", "value": round(t * 1e3, 2), "unit": "ms"})
    # distributed order statistics (radix-select counting passes psum'd):
    # the capability row the reference cannot run at all
    t = _timeit(
        lambda: _block(
            groupby_reduce(vals, labels, func="nanmedian", method="map-reduce", mesh=mesh)[0]
        )
    )
    out.append({"bench": "time_mesh_quantile[nanmedian-mapreduce]", "value": round(t * 1e3, 2), "unit": "ms"})
    return out


def bench_streaming(scale: str):
    """Out-of-core streaming throughput (the role the reference's dask/cubed
    chunked runtimes play) — ERA5-month shape streamed in bounded slabs."""
    from flox_tpu.streaming import streaming_groupby_reduce

    nt = 26304 if scale == "full" else 8760
    nspace = 72 * 144 if scale == "full" else 24 * 48
    rng = np.random.default_rng(0)
    month = ((np.arange(nt) // (24 * 30.44)).astype(np.int64)) % 12
    data = rng.normal(size=(nspace, nt)).astype(np.float32)
    streaming_groupby_reduce(data, month, func="nanmean", batch_bytes=64 * 2**20)  # warm
    t0 = time.perf_counter()
    streaming_groupby_reduce(data, month, func="nanmean", batch_bytes=64 * 2**20)
    t = time.perf_counter() - t0
    out = [
        {"bench": "time_streaming[era5-nanmean]", "value": round(t * 1e3, 1), "unit": "ms"},
        {"bench": "streaming_throughput[era5-nanmean]",
         "value": round(data.nbytes / t / 1e9, 2), "unit": "GB/s"},
    ]
    # round-5 additions: out-of-core exact median (nbits+1 passes) and the
    # carry-based streaming scan. batch_len forces >= 4 slabs at every
    # scale so the row measures the MULTI-SLAB paths it is named for (the
    # per-slab count accumulation / cross-slab carry), not a degenerate
    # one-slab run; one warm call excludes trace+compile like the row above
    from flox_tpu.streaming import streaming_groupby_scan

    sub = data[: max(1, nspace // 8)]
    blen = nt // 4

    def run_q():
        # block: the 33 bit-pass dispatches are async — unsynced timing
        # would stop the clock at dispatch, not completion
        _block(streaming_groupby_reduce(sub, month, func="nanmedian", batch_len=blen)[0])

    run_q()  # warm (compile)
    t0 = time.perf_counter()
    run_q()
    tq = time.perf_counter() - t0
    out.append({"bench": "time_streaming[era5-nanmedian-33pass]",
                "value": round(tq * 1e3, 1), "unit": "ms"})
    # throughput against ONE logical read: the 33-pass cost shows up as a
    # visibly lower GB/s than the nanmean row's single pass
    out.append({"bench": "streaming_throughput[era5-nanmedian-33pass]",
                "value": round(sub.nbytes / tq / 1e9, 3), "unit": "GB/s"})

    def run_s():
        streaming_groupby_scan(sub[0], month, func="nancumsum", batch_len=blen)

    run_s()  # warm (compile)
    t0 = time.perf_counter()
    run_s()
    ts = time.perf_counter() - t0
    out.append({"bench": "time_streaming[era5-scan-nancumsum]",
                "value": round(ts * 1e3, 1), "unit": "ms"})

    # -- prefetch pipeline vs synchronous staging under simulated IO latency
    # (pipeline.py): the loader sleeps like a zarr/S3 chunk read, so the
    # win is measurable on CPU CI — sleep releases the GIL while the
    # staging pool loads the next slabs. Same staged bytes either way
    # (results are bit-identical); only the overlap differs.
    import flox_tpu
    from flox_tpu import profiling

    latency_s = 0.010  # ~an object-store range-read RTT (>= the 5 ms floor)
    blen_p = max(1, nt // 16)
    # the row isolates the IO-overlap win, so keep per-slab compute small
    # next to the simulated latency (sub: 1/8 of the spatial rows) — the
    # compute-bound regime is already covered by the rows above
    psub = sub

    def sim_loader(s, e):
        time.sleep(latency_s)
        return psub[:, s:e]

    def run_p(depth):
        with flox_tpu.set_options(stream_prefetch=depth):
            with profiling.stream_monitor() as reports:
                _block(streaming_groupby_reduce(
                    sim_loader, month, func="nanmean", batch_len=blen_p
                )[0])
        return reports[0]

    # the prefetch row measures the configured depth (or 2 if the session
    # disabled prefetch — the row exists to show the pipeline delta)
    configured = flox_tpu.options.OPTIONS["stream_prefetch"] or 2
    run_p(0)
    run_p(configured)  # warm BOTH modes (compile + thread-pool first-spin)
    times = {}
    for d, tag in ((0, "sync"), (configured, "prefetch")):
        best, rep = None, None
        for _ in range(3):  # best-of-3: a noisy rep must not fake (or
            t0 = time.perf_counter()  # hide) the overlap win
            r = run_p(d)
            dt = time.perf_counter() - t0
            if best is None or dt < best:
                best, rep = dt, r
        times[tag] = best
        out.append({"bench": f"streaming_throughput[era5-nanmean-simio-{tag}]",
                    "value": round(psub.nbytes / times[tag] / 1e9, 3), "unit": "GB/s"})
        out.append({"bench": f"streaming_overlap[era5-nanmean-simio-{tag}]",
                    "value": round(rep.overlap_fraction, 3), "unit": "fraction"})
    out.append({"bench": "streaming_prefetch_speedup[era5-nanmean-simio]",
                "value": round(times["sync"] / times["prefetch"], 2), "unit": "x"})
    return out


def bench_scan(engine: str, scale: str):
    """Grouped-scan timing (reference tracks scans through its asv suite)."""
    from flox_tpu import groupby_scan

    n = 500_000 if scale == "full" else 100_000
    rng = np.random.default_rng(0)
    labels = np.tile(np.arange(12), n // 12 + 1)[:n]
    vals = rng.normal(size=n)
    out = []
    for func in ["cumsum", "ffill"]:
        t = _timeit(lambda: _block(groupby_scan(vals, labels, func=func, engine=engine)))
        out.append({"bench": f"time_scan[{func}-{engine}]", "value": round(t * 1e3, 2), "unit": "ms"})
    return out


def bench_scan_blelloch(scale: str):
    """Distributed Blelloch scan over the mesh (jax backend; once per run)."""
    from flox_tpu import groupby_scan

    n = 500_000 if scale == "full" else 100_000
    rng = np.random.default_rng(0)
    labels = np.tile(np.arange(12), n // 12 + 1)[:n]
    vals = rng.normal(size=n)
    t = _timeit(lambda: _block(groupby_scan(vals, labels, func="cumsum", method="blelloch")))
    return [{"bench": "time_scan[cumsum-blelloch]", "value": round(t * 1e3, 2), "unit": "ms"}]


def bench_telemetry(scale: str):
    """ISSUE 4: one instrumented pass of the ERA5 day-of-year headline so
    every benchmark round records its compile counts, retrace counts, and
    span-phase breakdown — the after-the-fact diagnosis BENCH rounds 1-5
    lacked whenever the accelerator probe fell back to CPU."""
    from flox_tpu import cache, groupby_reduce, telemetry

    nt, day = _era5_labels(scale)
    nspace = 72 * 144 if scale == "full" else 24 * 48
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(nspace, nt)).astype(np.float32)
    cache.clear_all()  # fresh caches: the profile records REAL compile work
    try:
        profile = telemetry.profile_call(
            lambda: _block(groupby_reduce(vals, day, func="nanmean", engine="jax")[0])
        )
    except Exception as exc:  # noqa: BLE001 — diagnostics must not kill the sweep
        profile = {"error": f"{type(exc).__name__}: {exc}"}
    return [{"bench": "telemetry[era5-nanmean]", "value": profile, "unit": "profile"}]


def bench_highcard(engine: str, scale: str):
    """Dense vs the sort (present-groups) engine on a sparse-presence
    high-cardinality workload — the ``highcard_gbps[...]`` rows the
    dense-vs-sort crossover (docs/engines.md) is recorded from."""
    from flox_tpu import groupby_reduce

    size = 1 << (20 if scale == "full" else 17)
    n = 1 << (16 if scale == "full" else 14)
    present = max(64, size >> 8)
    rng = np.random.default_rng(11)
    ids = rng.choice(size, present, replace=False)
    codes = ids[rng.integers(0, present, n)]
    vals = rng.normal(size=n)
    eg = np.arange(size)
    out = []
    for eng, label in ((engine, "dense"), ("sort", "sort")):
        t = _timeit(lambda e=eng: _block(groupby_reduce(
            vals, codes, func="nanmean", expected_groups=eg, engine=e,
        )[0]))
        out.append({
            "bench": f"highcard_gbps[{label}-{size}g-{engine}]",
            "value": round(vals.nbytes / t / 1e9, 3), "unit": "GB/s",
        })
    return out


def bench_costmodel(scale: str):
    """Analytical-cards sweep (ISSUE 14): run the ERA5 nanmean with the
    cost-model plane on and emit each program's card next to the drift
    verdict — every benchmarks.py round carries the predicted-vs-observed
    join, so a program that silently got slower shows up in the committed
    artifact, not just in a live scrape."""
    import flox_tpu
    from flox_tpu import cache, costmodel, groupby_reduce

    nt, day = _era5_labels(scale)
    nspace = 72 * 144 if scale == "full" else 24 * 48
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(nspace, nt)).astype(np.float32)
    cache.clear_all()
    try:
        with flox_tpu.set_options(telemetry=True, costmodel=True):
            _block(groupby_reduce(vals, day, func="nanmean", engine="jax")[0])
            drift = costmodel.drift_report()
            # keyed by digest (the registry identity) — one label can hold
            # several cards, one per input signature
            record = {
                "cards": {
                    digest: {
                        "label": card["label"],
                        "flops": card["flops"],
                        "bytes_accessed": card["bytes_accessed"],
                        "predicted_ms": card["predicted_ms"],
                        "analysis": card["analysis"],
                    }
                    for digest, card in costmodel.cards().items()
                },
                "drift_flagged": drift["flagged"],
            }
    except Exception as exc:  # noqa: BLE001 — diagnostics must not kill the sweep
        record = {"error": f"{type(exc).__name__}: {exc}"}
    finally:
        cache.clear_all()
    return [{"bench": "costmodel[era5-nanmean]", "value": record, "unit": "cards"}]


def bench_cohort_detection(scale: str):
    """time_find_group_cohorts + track_num_cohorts parity."""
    from flox_tpu import cache
    from flox_tpu.cohorts import chunks_from_shards, find_group_cohorts

    nt, day = _era5_labels(scale)
    chunks = chunks_from_shards(nt, nt // 48)

    def run():
        cache.clear_all()  # the reference's asv clears flox.cache the same way
        return find_group_cohorts(day, chunks, expected_groups=range(365))

    t = _timeit(run)
    method, mapping = run()
    return [
        {"bench": "time_find_group_cohorts[era5]", "value": round(t * 1e3, 2), "unit": "ms"},
        {"bench": "track_num_cohorts[era5]", "value": len(mapping), "unit": "cohorts"},
        {"bench": "track_method[era5]", "value": method, "unit": "method"},
    ]


def sentinel_row(rows: list, platform: str) -> dict:
    """Regression sentinel over this sweep's GB/s rows (ISSUE 6): diff each
    throughput row against the newest committed ``BENCH_HISTORY/r*_cpu.jsonl``
    round with a matching row, flagging drops past the autotune threshold.
    Report-only by construction — the verdict is a row, never an exit code."""
    import glob
    import os
    import re

    from flox_tpu.autotune import _REGRESSION_THRESHOLD, compare_families

    current = {
        r["bench"]: r["value"]
        for r in rows
        if r.get("unit") == "GB/s" and isinstance(r.get("value"), (int, float))
    }
    previous: dict = {}
    compared = None
    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for path in glob.glob(os.path.join(here, "BENCH_HISTORY", "r*_cpu.jsonl")):
        m = re.match(r"r(\d+)_cpu\.jsonl$", os.path.basename(path))
        if m:
            rounds.append((int(m.group(1)), path))
    # newest round first, by the parsed round NUMBER: lexicographic order
    # inverts r99/r100 (and any unpadded name) the moment digits grow
    for _, path in sorted(rounds, reverse=True):
        try:
            with open(path) as f:
                lines = [json.loads(line) for line in f if line.strip()]
        except (OSError, ValueError):
            continue
        plat = next((r["value"] for r in lines if r.get("bench") == "platform"), None)
        if plat != platform:
            continue
        previous = {
            r["bench"]: r["value"]
            for r in lines
            if r.get("unit") == "GB/s" and isinstance(r.get("value"), (int, float))
        }
        compared = os.path.basename(path)
        break
    families, regressed = compare_families(current, previous)
    return {
        "bench": "regression_sentinel",
        "value": {
            "status": "regression" if regressed else "ok",
            "platform": platform,
            "threshold": _REGRESSION_THRESHOLD,
            "compared_against": compared,
            "regressed": regressed,
            "families": families,
        },
        "unit": "verdict",
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=["small", "full"], default="small")
    ap.add_argument("--engine", choices=["jax", "numpy", "both"], default="jax")
    ap.add_argument(
        "--platform", choices=["default", "cpu"], default="default",
        help="cpu forces the CPU backend before any device init (the "
        "environment's sitecustomize otherwise selects the accelerator, "
        "which hangs when the TPU tunnel is down)",
    )
    ap.add_argument(
        "--sweeps", type=int, default=3,
        help="run the whole battery N times and record the per-row MEDIAN "
        "(VERDICT r4 #3: back-to-back reps share transient host load; "
        "sweeps minutes apart sample the session's noise distribution). "
        "1 = a quick single sweep.",
    )
    args = ap.parse_args()

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    # report the backend that ACTUALLY initialized, not the CLI arg — the
    # capture tooling uses this row as evidence a sweep ran on hardware
    print(json.dumps(
        {"bench": "platform", "value": jax.default_backend(), "unit": "config"}
    ))

    engines = ["jax", "numpy"] if args.engine == "both" else [args.engine]

    def one_sweep():
        results = []
        for engine in engines:
            results += bench_reduce(engine)
            results += bench_reduce_bare(engine)
            results += bench_quantile(engine, args.scale)
            results += bench_era5_dayofyear(engine, args.scale)
            results += bench_era5_resampling(engine, args.scale)
            results += bench_nwm_zonal(engine, args.scale)
            results += bench_random_big(engine, args.scale)
            results += bench_fused(engine, args.scale)
            results += bench_highcard(engine, args.scale)
            results += bench_scan(engine, args.scale)
        if "jax" in engines:
            # mesh benchmarks need a working jax backend; keep --engine numpy
            # runnable on hosts without one
            results += bench_mesh_methods(args.scale)
            results += bench_scan_blelloch(args.scale)
            results += bench_streaming(args.scale)
            results += bench_telemetry(args.scale)
            results += bench_costmodel(args.scale)
        results += bench_cohort_detection(args.scale)
        return results

    sweeps = [one_sweep() for _ in range(max(1, args.sweeps))]
    print(json.dumps({
        "bench": "timer", "value": f"median-of-{len(sweeps)}-sweeps",
        "unit": "config",
    }))
    # per-row median across sweeps; non-numeric rows pass through from the
    # first sweep (config rows are sweep-invariant)
    by_name: dict = {}
    for sweep in sweeps:
        for r in sweep:
            by_name.setdefault(r["bench"], []).append(r)
    medians = []
    for name, rows in by_name.items():
        vals = sorted(r["value"] for r in rows if isinstance(r["value"], (int, float)))
        if vals:
            k = len(vals)
            med = vals[k // 2] if k % 2 else round((vals[k // 2 - 1] + vals[k // 2]) / 2, 6)
            out = dict(rows[0], value=med)
        else:
            out = rows[0]
        medians.append(out)
        print(json.dumps(out))
    # report-only regression sentinel over the medians (ISSUE 6)
    print(json.dumps(sentinel_row(medians, jax.default_backend())))


if __name__ == "__main__":
    main()
