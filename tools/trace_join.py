"""Join per-process telemetry jsonl exports into ONE Perfetto trace.

A fleet (router + N replicas) or a multi-host ``jax.distributed`` mesh
produces one jsonl export per process — each with its own monotonic span
clock, its own pids/tids, and (with trace propagation, PR 13) shared trace
ids linking the hops of one request. This tool merges them::

    python -m tools.trace_join fleet.json replica-a.jsonl replica-b.jsonl

into a single Chrome trace-event file (ui.perfetto.dev-loadable) where:

* every input file becomes its OWN process track, named from the file's
  replica stamp (``process_name`` metadata events; ``process_sort_index``
  follows the recorded ``jax.distributed`` process index, so mesh tracks
  order deterministically);
* per-process monotonic timestamps are aligned onto one shared timeline
  from each file's clock anchor — the freshest ``clock-anchor`` event
  (``telemetry.anchor_event()``) when present, else the export tail's
  ``anchor`` pair, else the import-time ``wall0`` — normalized so the
  earliest process starts at 0;
* records sharing a trace id across processes get Perfetto flow arrows
  (``ph: s/f``) from the root span of the process that saw the trace
  first (the router/client hop) to each other process's root span for it
  — with ``trace_parent`` stamps (a propagated W3C ``traceparent``)
  naming the exact remote parent span.

Counters lines ride along under ``floxTpuFleet`` (one entry per input
file: replica, host, pid, process index, counter snapshot), so the merged
file still answers "how many compiles did replica b pay".
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

__all__ = ["join_traces", "load_jsonl", "main"]


def load_jsonl(path: str) -> tuple[list[dict], dict]:
    """(records, tail) for one per-process export: every span/event record
    plus the final ``counters`` record (the identity/anchor stamp). A
    malformed line is an error naming ``file:line`` — a torn export must
    fail the join, not silently drop a process's spans."""
    records: list[dict] = []
    tail: dict = {}
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                raise ValueError(f"{path}:{lineno}: malformed record ({exc})") from exc
            if not isinstance(rec, dict):
                raise ValueError(
                    f"{path}:{lineno}: expected an object, got {type(rec).__name__}"
                )
            if rec.get("type") == "counters":
                tail = rec  # later snapshots supersede (append-mode files)
            else:
                records.append(rec)
    return records, tail


def _wall_offset_us(records: list[dict], tail: dict) -> float:
    """Microseconds to ADD to this file's ``ts_us`` values to land them on
    the wall clock: from the freshest ``clock-anchor`` event (both clocks
    read at one instant), else the export tail's ``anchor`` pair, else the
    import-time ``wall0`` (where ``ts_us`` 0 == ``wall0`` by
    construction)."""
    anchor: tuple[float, float] | None = None  # (wall_s, ts_us)
    for rec in records:
        if rec.get("name") == "clock-anchor":
            wall = (rec.get("attrs") or {}).get("wall")
            if wall is not None:
                anchor = (float(wall), float(rec.get("ts_us", 0.0)))
    if anchor is None and isinstance(tail.get("anchor"), dict):
        pair = tail["anchor"]
        if "wall" in pair and "ts_us" in pair:
            anchor = (float(pair["wall"]), float(pair["ts_us"]))
    if anchor is None and "wall0" in tail:
        anchor = (float(tail["wall0"]), 0.0)
    if anchor is None:
        return 0.0
    wall_s, ts_us = anchor
    return wall_s * 1e6 - ts_us


def join_traces(inputs: list[tuple[str, list[dict], dict]]) -> dict:
    """Merge per-process (label, records, tail) triples into one Chrome
    trace-event object with a distinct, named process track per input and
    cross-process flow arrows for shared trace ids."""
    if not inputs:
        raise ValueError("no input files to join")
    labels = [label for label, _, _ in inputs]
    if len(set(labels)) != len(labels):
        raise ValueError(
            f"duplicate input labels {sorted(labels)} — labels key the "
            "per-file clock offsets, so they must be distinct"
        )
    offsets = {
        label: _wall_offset_us(records, tail)
        for label, records, tail in inputs
    }
    # normalize: the earliest process's first record lands at ts 0 (Perfetto
    # renders absolute microseconds; epoch-scale values are unwieldy)
    starts = []
    for label, records, tail in inputs:
        for rec in records:
            if "ts_us" in rec:
                starts.append(rec["ts_us"] + offsets[label])
                break
    base = min(starts) if starts else 0.0

    events: list[dict] = []
    fleet_meta: list[dict] = []
    #: (trace id, pid) -> {"ts": earliest aligned ts, "tid": its thread,
    #: "parent": any trace_parent stamp seen} — the per-process sighting
    #: the flow arrows connect. Earliest by TIMESTAMP, not file order:
    #: spans emit at exit, so inner spans precede their parents in the
    #: file, and the parent stamp rides only root-level records.
    sightings: dict[tuple[str, int], dict] = {}
    for pid, (label, records, tail) in enumerate(inputs, start=1):
        replica = tail.get("replica") or label
        sort_index = int(tail.get("process_index", pid - 1))
        events.append(
            {
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"{replica} ({label})"},
            }
        )
        events.append(
            {
                "name": "process_sort_index", "ph": "M", "pid": pid, "tid": 0,
                "args": {"sort_index": sort_index},
            }
        )
        offset = offsets[label] - base
        for rec in records:
            ts = float(rec.get("ts_us", 0.0)) + offset
            args = dict(rec.get("attrs") or {})
            if rec.get("trace") is not None:
                args["trace_id"] = rec["trace"]
            if rec.get("trace_parent") is not None:
                args["trace_parent"] = rec["trace_parent"]
            if rec.get("replica") is not None:
                args["replica"] = rec["replica"]
            tid = rec.get("tid", 0)
            if rec.get("type") == "span":
                events.append(
                    {
                        "name": rec.get("name", "?"), "ph": "X", "ts": ts,
                        "dur": rec.get("dur_us", 0.0), "pid": pid, "tid": tid,
                        "args": args,
                    }
                )
            elif rec.get("type") == "event":
                events.append(
                    {
                        "name": rec.get("name", "?"), "ph": "i", "s": "t",
                        "ts": ts, "pid": pid, "tid": tid, "args": args,
                    }
                )
            else:
                continue
            trace_id = rec.get("trace")
            if trace_id is not None:
                slot = sightings.setdefault(
                    (trace_id, pid), {"ts": ts, "tid": tid, "parent": None}
                )
                if ts < slot["ts"]:
                    slot["ts"], slot["tid"] = ts, tid
                if rec.get("trace_parent") is not None:
                    slot["parent"] = rec["trace_parent"]
        fleet_meta.append(
            {
                "file": label,
                "pid": pid,
                "replica": replica,
                "host": tail.get("host"),
                "source_pid": tail.get("pid"),
                "process_index": tail.get("process_index"),
                "clock_offset_us": round(offset, 1),
                "counters": tail.get("counters", {}),
            }
        )
    # flow arrows: a trace id seen in >1 process flows from its earliest
    # sighting (the hop that opened the trace) to every later process's
    # first record for it — Perfetto draws the router→replica arrow
    by_trace: dict[str, list[tuple[float, int, Any, Any]]] = {}
    for (trace_id, pid), slot in sightings.items():
        by_trace.setdefault(trace_id, []).append(
            (slot["ts"], pid, slot["tid"], slot["parent"])
        )
    flow_id = 0
    for trace_id, rows in sorted(by_trace.items()):
        if len(rows) < 2:
            continue
        rows.sort()
        t0, pid0, tid0, _ = rows[0]
        flow_id += 1
        events.append(
            {
                "name": f"trace:{trace_id}", "ph": "s", "id": flow_id,
                "ts": t0, "pid": pid0, "tid": tid0, "cat": "trace",
            }
        )
        for ts, pid, tid, parent in rows[1:]:
            events.append(
                {
                    "name": f"trace:{trace_id}", "ph": "f", "bp": "e",
                    "id": flow_id, "ts": ts, "pid": pid, "tid": tid,
                    "cat": "trace",
                    "args": {"trace_parent": parent} if parent else {},
                }
            )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "floxTpuFleet": fleet_meta,
    }


def _unique_labels(paths: list[str]) -> list[str]:
    """Short display labels for the input files, guaranteed distinct.

    Labels key the per-file clock offsets inside :func:`join_traces`, so
    two files that share a basename (``replica-a/export.jsonl`` and
    ``replica-b/export.jsonl``) must NOT collapse to one label — that
    would silently apply one file's clock offset to the other's track.
    Basenames when unique, full paths where they collide."""
    bases = [os.path.basename(p) for p in paths]
    return [
        path if bases.count(base) > 1 else base
        for base, path in zip(bases, paths)
    ]


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.trace_join",
        description="Merge per-process flox_tpu telemetry jsonl exports "
        "into one Perfetto-loadable trace with a track per process and "
        "flow arrows joining propagated trace ids.",
    )
    parser.add_argument("output", help="merged Chrome-trace .json to write")
    parser.add_argument(
        "inputs", nargs="+",
        help="per-process .jsonl telemetry exports (one track each)",
    )
    args = parser.parse_args(argv)
    try:
        labels = _unique_labels(args.inputs)
        loaded = [
            (label, *load_jsonl(path))
            for label, path in zip(labels, args.inputs)
        ]
        payload = join_traces(loaded)
    except (OSError, ValueError) as exc:
        parser.error(str(exc))
    tmp = args.output + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, args.output)
    tracks = len(loaded)
    flows = sum(1 for ev in payload["traceEvents"] if ev.get("ph") == "s")
    print(
        f"{args.output}: {len(payload['traceEvents'])} events across "
        f"{tracks} process track(s), {flows} cross-process trace flow(s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
