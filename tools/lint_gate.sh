#!/usr/bin/env bash
# Lint gate for flox_tpu: floxlint (mandatory) + ruff + mypy (best-effort —
# skipped with a notice when the tool is not installed, so the gate runs in
# minimal containers that only carry the jax toolchain).
#
# Usage: tools/lint_gate.sh  (from the repo root; CI runs it before tier-1 pytest)
set -u

cd "$(dirname "$0")/.."
rc=0

echo "== floxlint =="
# the full tree (fixtures auto-pruned), checked against the suppression
# baseline: new findings fail, and so do stale baseline entries (drift —
# a fixed hazard whose suppression was never deleted). The project index
# is cached on disk and shared with CI's SARIF step.
python -m tools.floxlint flox_tpu/ tools/ tests_tpu/ \
    --baseline tools/floxlint/baseline.json \
    --index-cache .floxlint-index.pickle || rc=1

echo
echo "== contract artifact =="
# the static contract compiler: schema-validated before writing, byte-
# deterministic, diffable between commits. CI uploads it next to the
# SARIF; the runtime conformance leg (tests/test_contract.py) replays it
# against a live replica.
python -m tools.floxlint --contract contract.json flox_tpu/ || rc=1

echo
echo "== ruff =="
if python -c "import ruff" >/dev/null 2>&1; then
    python -m ruff check flox_tpu/ tools/floxlint/ tests/test_floxlint.py || rc=1
elif command -v ruff >/dev/null 2>&1; then
    ruff check flox_tpu/ tools/floxlint/ tests/test_floxlint.py || rc=1
else
    echo "ruff not installed — skipping (config lives in [tool.ruff] in pyproject.toml)"
fi

echo
echo "== mypy =="
if python -c "import mypy" >/dev/null 2>&1; then
    python -m mypy --config-file pyproject.toml || rc=1
else
    echo "mypy not installed — skipping (config lives in [tool.mypy] in pyproject.toml)"
fi

echo
if [ "$rc" -eq 0 ]; then
    echo "lint gate: PASS"
else
    echo "lint gate: FAIL"
fi
exit "$rc"
