"""Developer tooling for the flox_tpu repo (not shipped with the package)."""
