"""Interprocedural concurrency model for FLX013–FLX016.

Composes the per-function effect summaries (:mod:`.effects`) over an
extended call graph into the whole-program facts the concurrency rules
need:

* **entry points** — thread entries (``threading.Thread(target=…)`` /
  ``Timer``, ``executor.submit``, ``asyncio.to_thread``,
  ``loop.run_in_executor``) and signal handlers (``signal.signal``), with
  the spawn *target* resolved through import aliases, ``self`` methods,
  and ``functools.partial`` wrappers;
* **extended call edges** — on top of the plain-function edges the base
  :class:`~tools.floxlint.callgraph.CallGraph` resolves, this adds
  ``self.method()`` receivers and locals bound to ``functools.partial``.
  Spawn sites are deliberately *not* call edges: work handed to a thread
  leaves the spawning context (an ``asyncio.to_thread`` boundary ends
  FLX015's event-loop reachability, and a handler that only spawns a
  daemon thread is signal-safe for FLX016);
* **held-at-entry** — for each function, the lock set held on *every*
  resolved call path into it (a meet-over-callers fixpoint), so a helper
  whose callers all hold the registry lock counts as protected;
* **thread reachability** — the closure of spawn targets under call (and
  further spawn) edges;
* the **lock-order graph** — an edge ``A -> B`` wherever B is acquired
  while A is held, locally or through any chain of calls. Cycles are
  FLX014 findings, and ``--lock-graph`` emits the graph as a JSON/dot
  review artifact.
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Iterable

from . import effects as fx
from .rules.common import dotted_name

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .core import ProjectContext
    from .index import FunctionInfo, ModuleInfo, ProjectIndex

#: spawn kinds
THREAD, EXECUTOR, TO_THREAD, TIMER, SIGNAL = (
    "thread", "executor", "to_thread", "timer", "signal",
)

_MAX_DEPTH = 8  #: reachability bound for the rule traversals


@dataclass(frozen=True)
class SpawnSite:
    caller: str  #: qualname of the spawning function
    target: str  #: qualname of the entry-point function
    kind: str  #: THREAD / EXECUTOR / TO_THREAD / TIMER / SIGNAL
    lineno: int
    col: int


@dataclass(frozen=True)
class CallContext:
    caller: str
    callee: str
    held: tuple[str, ...]  #: locks held locally at the call site
    lineno: int
    col: int


@dataclass
class LockOrderGraph:
    """Directed acquisition-order graph over canonical lock ids."""

    #: lock id -> kind (effects.LOCK / RLOCK / ASYNC_LOCK)
    nodes: dict[str, str] = field(default_factory=dict)
    #: (src, dst) -> "path:line" provenance of the first edge witness
    edges: dict[tuple[str, str], str] = field(default_factory=dict)

    def add_edge(self, src: str, dst: str, site: str) -> None:
        if src == dst and self.nodes.get(dst) == fx.RLOCK:
            return  # re-entering an RLock is its design contract
        self.edges.setdefault((src, dst), site)

    def successors(self, lock: str) -> list[str]:
        return [d for (s, d) in self.edges if s == lock]

    def cycles(self) -> list[list[str]]:
        """Every elementary inconsistency: self-loops plus one cycle per
        strongly-connected component with more than one node."""
        out: list[list[str]] = []
        for (s, d) in sorted(self.edges):
            if s == d:
                out.append([s])
        for scc in self._sccs():
            if len(scc) > 1:
                out.append(sorted(scc))
        return out

    def _sccs(self) -> list[list[str]]:
        """Tarjan over the edge set (iterative — fixture graphs are tiny but
        the real one spans the package)."""
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        counter = [0]
        sccs: list[list[str]] = []
        adj: dict[str, list[str]] = {}
        for (s, d) in self.edges:
            adj.setdefault(s, []).append(d)
            adj.setdefault(d, [])

        def strongconnect(v: str) -> None:
            work = [(v, 0)]
            while work:
                node, pi = work.pop()
                if pi == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                for i in range(pi, len(adj[node])):
                    w = adj[node][i]
                    if w not in index:
                        work.append((node, i + 1))
                        work.append((w, 0))
                        recurse = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if recurse:
                    continue
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    sccs.append(scc)
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        return sccs

    def to_json(self) -> dict:
        return {
            "version": 1,
            "nodes": [
                {"id": n, "kind": k} for n, k in sorted(self.nodes.items())
            ],
            "edges": [
                {"from": s, "to": d, "site": site}
                for (s, d), site in sorted(self.edges.items())
            ],
            "cycles": self.cycles(),
        }

    def to_dot(self) -> str:
        lines = ["digraph lock_order {"]
        for n, k in sorted(self.nodes.items()):
            shape = "box" if k == fx.RLOCK else "ellipse"
            lines.append(f'  "{n}" [shape={shape}, label="{n}\\n({k})"];')
        for (s, d), site in sorted(self.edges.items()):
            lines.append(f'  "{s}" -> "{d}" [label="{site}"];')
        lines.append("}")
        return "\n".join(lines) + "\n"


class ConcurrencyModel:
    """All interprocedural concurrency facts for one project index."""

    def __init__(self, index: "ProjectIndex") -> None:
        self.index = index
        self.effects = fx.compute_effects(index)
        self.lock_table = fx.lock_defs(index)
        #: caller -> resolved direct-call callees (extended resolution)
        self.edges: dict[str, set[str]] = {}
        self.call_contexts: list[CallContext] = []
        self.spawns: list[SpawnSite] = []
        self._build_edges_and_spawns()
        self.thread_entries: set[str] = {
            s.target for s in self.spawns if s.kind != SIGNAL
        }
        self.signal_entries: set[str] = {
            s.target for s in self.spawns if s.kind == SIGNAL
        }
        self.spawn_kind: dict[str, str] = {}
        for s in self.spawns:
            self.spawn_kind.setdefault(s.target, s.kind)
        self.thread_reachable: set[str] = self._reach(self.thread_entries)
        self.signal_reachable: set[str] = self._reach(self.signal_entries)
        self.held_at_entry: dict[str, frozenset[str]] = self._held_fixpoint()
        self.lock_graph: LockOrderGraph = self._build_lock_graph()

    # -- construction --------------------------------------------------------

    def _build_edges_and_spawns(self) -> None:
        for mod in self.index.modules.values():
            for fi in mod.functions.values():
                eff = self.effects[fi.qualname]
                self.edges.setdefault(fi.qualname, set())
                partials = self._local_partials(mod, fi)
                for rec in eff.calls:
                    callee = self._resolve_callable(
                        mod, fi, rec.call.func, partials
                    )
                    if callee is not None:
                        self.edges[fi.qualname].add(callee)
                        self.call_contexts.append(
                            CallContext(
                                caller=fi.qualname,
                                callee=callee,
                                held=rec.held,
                                lineno=rec.call.lineno,
                                col=rec.call.col_offset,
                            )
                        )
                    self._detect_spawn(mod, fi, rec.call, partials, eff)

    def _local_partials(self, mod: "ModuleInfo", fi: "FunctionInfo") -> dict[str, str]:
        """Local name -> qualname for ``g = functools.partial(f, …)``."""
        out: dict[str, str] = {}
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                continue
            target = self._unwrap_partial(mod, fi, node.value, out)
            if target is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = target
        return out

    def _unwrap_partial(
        self,
        mod: "ModuleInfo",
        fi: "FunctionInfo",
        call: ast.Call,
        partials: dict[str, str],
    ) -> str | None:
        resolved = mod.imports.resolve(call.func)
        if resolved not in ("functools.partial", "partial") or not call.args:
            return None
        return self._resolve_callable(mod, fi, call.args[0], partials)

    def _resolve_callable(
        self,
        mod: "ModuleInfo",
        fi: "FunctionInfo",
        expr: ast.AST,
        partials: dict[str, str],
    ) -> str | None:
        """Qualname of the project function ``expr`` denotes: a dotted name
        (through aliases/re-exports), a ``self.method``, a local bound to a
        ``functools.partial``, or an inline partial call."""
        if isinstance(expr, ast.Call):
            return self._unwrap_partial(mod, fi, expr, partials)
        name = dotted_name(expr)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        if head == "self" and rest and "." not in rest:
            prefix = fi.qualname.rsplit(".", 1)[0]
            while prefix and prefix != mod.name:
                cand = f"{prefix}.{rest}"
                if self.index.function(cand) is not None:
                    return cand
                prefix = prefix.rsplit(".", 1)[0] if "." in prefix else ""
            return None
        if not rest and head in partials:
            return partials[head]
        resolved = self.index.resolve_symbol(mod.name, name)
        if resolved is not None and self.index.function(resolved) is not None:
            return resolved
        return None

    def _detect_spawn(
        self,
        mod: "ModuleInfo",
        fi: "FunctionInfo",
        call: ast.Call,
        partials: dict[str, str],
        eff: fx.FunctionEffects,
    ) -> None:
        resolved = mod.imports.resolve(call.func)

        def spawn(target_expr: ast.AST, kind: str) -> None:
            target = self._resolve_callable(mod, fi, target_expr, partials)
            if target is not None:
                self.spawns.append(
                    SpawnSite(
                        caller=fi.qualname,
                        target=target,
                        kind=kind,
                        lineno=call.lineno,
                        col=call.col_offset,
                    )
                )

        if resolved in ("threading.Thread", "Thread"):
            for kw in call.keywords:
                if kw.arg == "target":
                    spawn(kw.value, THREAD)
            return
        if resolved in ("threading.Timer", "Timer"):
            if len(call.args) >= 2:
                spawn(call.args[1], TIMER)
            return
        if resolved == "asyncio.to_thread":
            if call.args:
                spawn(call.args[0], TO_THREAD)
            return
        if resolved == "signal.signal":
            if len(call.args) >= 2:
                spawn(call.args[1], SIGNAL)
            return
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            receiver = dotted_name(call.func.value) or ""
            rhead = receiver.partition(".")[0]
            rtype = eff.local_types.get(rhead)
            looks_executor = (
                rtype == "executor"
                or "executor" in receiver.lower()
                or "pool" in receiver.lower()
            )
            if attr == "submit" and looks_executor and call.args:
                spawn(call.args[0], EXECUTOR)
            elif attr == "run_in_executor" and len(call.args) >= 2:
                spawn(call.args[1], EXECUTOR)

    # -- reachability / held-at-entry ----------------------------------------

    def _reach(self, roots: Iterable[str]) -> set[str]:
        """Closure of ``roots`` under call edges AND further spawns (a thread
        that spawns another thread taints that target too)."""
        spawn_map: dict[str, set[str]] = {}
        for s in self.spawns:
            if s.kind != SIGNAL:
                spawn_map.setdefault(s.caller, set()).add(s.target)
        out: set[str] = set(roots)
        queue: deque[str] = deque(out)
        while queue:
            fn = queue.popleft()
            for nxt in self.edges.get(fn, ()) | spawn_map.get(fn, set()):
                if nxt not in out:
                    out.add(nxt)
                    queue.append(nxt)
        return out

    def _held_fixpoint(self) -> dict[str, frozenset[str]]:
        """held_at_entry(f) = ∩ over resolved call sites of
        (held_at_entry(caller) ∪ locks held at the site). Entry points
        (spawn/signal targets, async defs, uncalled functions) start — and
        stay — at ∅; the meet converges monotonically from TOP."""
        in_sites: dict[str, list[CallContext]] = {}
        for cc in self.call_contexts:
            in_sites.setdefault(cc.callee, []).append(cc)
        roots = set(self.thread_entries) | set(self.signal_entries)
        for q, eff in self.effects.items():
            if eff.is_async or q not in in_sites:
                roots.add(q)
        TOP = None
        held: dict[str, frozenset[str] | None] = {
            q: (frozenset() if q in roots else TOP) for q in self.effects
        }
        for _ in range(len(self.effects) + 1):
            changed = False
            for q in self.effects:
                if q in roots:
                    continue
                vals = [
                    held[cc.caller] | frozenset(cc.held)
                    for cc in in_sites.get(q, ())
                    if held.get(cc.caller) is not TOP
                ]
                new = frozenset.intersection(*vals) if vals else TOP
                if new != held[q]:
                    held[q] = new
                    changed = True
            if not changed:
                break
        return {q: (v if v is not TOP else frozenset()) for q, v in held.items()}

    # -- lock-order graph ----------------------------------------------------

    def acquires_closure(self, qualname: str) -> set[str]:
        """Locks acquired by ``qualname`` or anything reachable from it
        through call edges (memoized, cycle-safe)."""
        cache = self._closure_cache
        if qualname in cache:
            return cache[qualname]
        out: set[str] = set()
        cache[qualname] = out  # pre-seed: cycles contribute nothing extra
        seen = {qualname}
        queue: deque[tuple[str, int]] = deque([(qualname, 0)])
        while queue:
            fn, depth = queue.popleft()
            eff = self.effects.get(fn)
            if eff is not None:
                out.update(a.lock for a in eff.acquisitions)
            if depth >= _MAX_DEPTH:
                continue
            for nxt in self.edges.get(fn, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    queue.append((nxt, depth + 1))
        return out

    def _build_lock_graph(self) -> LockOrderGraph:
        self._closure_cache: dict[str, set[str]] = {}
        graph = LockOrderGraph()
        for lock, ld in self.lock_table.items():
            graph.nodes[lock] = ld.kind

        def kind_of(lock: str) -> str:
            return self.lock_table[lock].kind if lock in self.lock_table else fx.LOCK

        def site_str(qualname: str, lineno: int) -> str:
            fi = self.index.function(qualname)
            path = str(fi.path) if fi is not None else qualname
            return f"{path}:{lineno}"

        # intra-function nesting: every held lock orders before the new one
        for q, eff in self.effects.items():
            for acq in eff.acquisitions:
                graph.nodes.setdefault(acq.lock, acq.kind)
                for h in acq.held_before:
                    graph.nodes.setdefault(h, kind_of(h))
                    graph.add_edge(h, acq.lock, site_str(q, acq.lineno))
        # interprocedural: calling into code that acquires B while holding A
        for cc in self.call_contexts:
            if not cc.held:
                continue
            for lock in self.acquires_closure(cc.callee):
                graph.nodes.setdefault(lock, kind_of(lock))
                for h in cc.held:
                    if h == lock and kind_of(lock) == fx.RLOCK:
                        continue
                    graph.nodes.setdefault(h, kind_of(h))
                    graph.add_edge(h, lock, site_str(cc.caller, cc.lineno))
        return graph

    # -- traversal helpers for the rules -------------------------------------

    def reachable_calls(self, root: str, max_depth: int = _MAX_DEPTH) -> set[str]:
        """Functions reachable from ``root`` through call edges only —
        spawn boundaries (to_thread / executor / Thread) end the walk."""
        out: set[str] = set()
        queue: deque[tuple[str, int]] = deque([(root, 0)])
        while queue:
            fn, depth = queue.popleft()
            if depth >= max_depth:
                continue
            for nxt in self.edges.get(fn, ()):
                if nxt not in out and nxt != root:
                    out.add(nxt)
                    queue.append((nxt, depth + 1))
        return out


def model_for(pctx: "ProjectContext") -> ConcurrencyModel:
    """The (cached) concurrency model for one project context — FLX013–016
    all share a single build per lint root."""
    model = getattr(pctx, "_concurrency_model", None)
    if model is None:
        model = ConcurrencyModel(pctx.index)
        pctx._concurrency_model = model
    return model


def lock_graph_for_paths(paths: Iterable[str]) -> LockOrderGraph:
    """Standalone lock-order graph over a file set (the ``--lock-graph``
    artifact path, shared with the runtime stress harness)."""
    from .core import iter_python_files
    from .index import ProjectIndex

    groups: dict = {}
    for f, root in iter_python_files(list(paths)):
        groups.setdefault(root, []).append(f)
    merged = LockOrderGraph()
    for root, files in sorted(groups.items()):
        index = ProjectIndex.build(files, root)
        graph = ConcurrencyModel(index).lock_graph
        merged.nodes.update(graph.nodes)
        for (s, d), site in graph.edges.items():
            merged.edges.setdefault((s, d), site)
    return merged
