"""Fixture: FLX019 response-shape drift; implicit reduce op undocumented."""  # expect: FLX017

_REQUEST_FIELDS = {"func", "array"}


async def _amain(msg: dict) -> dict | None:
    op = msg.get("op")
    if op == "lookup":  # expect: FLX019
        return {"op": "lookup", "ok": True, "value": 1}
    return _handle_line(msg)


def _handle_line(msg: dict) -> dict:
    payload = {k: msg[k] for k in _REQUEST_FIELDS if k in msg}
    return {"id": msg.get("id"), "ok": True, "result": payload}


def _fail_untyped(rid: str) -> dict:
    return {"id": rid, "ok": False, "error": "boom"}  # expect: FLX019


def _fail_typed(rid: str) -> dict:
    return {"id": rid, "ok": False, "error": "boom", "code": "f19_bad"}


def _fail_subscript(rid: str) -> dict:
    out = {"id": rid, "ok": False, "error": "boom"}
    out["code"] = "f19_bad"
    return out


def _error_response(exc: Exception) -> dict:
    return {"ok": False, "error": type(exc).__name__, "code": "f19_env"}


def _fail_spread(rid: str, exc: Exception) -> dict:
    return {"id": rid, **_error_response(exc)}
