"""FLX003 fixture: dtype-policy violations (narrow-float accumulators and
ungated float64)."""

import jax.numpy as jnp
import numpy as np


def bf16_accumulator(x, size):
    acc = jnp.zeros((size,), dtype=jnp.bfloat16)  # expect: FLX003
    return acc + x


def narrow_cast_by_string(x):
    return x.astype("float16")  # expect: FLX003


def narrow_cast_by_attr(partials):
    combined = partials.sum(axis=0)
    return combined.astype(jnp.bfloat16)  # expect: FLX003


def ungated_f64(x):
    return x.astype(jnp.float64)  # expect: FLX003


def gated_f64(x, x64_enabled):
    # the sanctioned spelling: every f64 choice branches on the x64 gate
    return x.astype(jnp.float64 if x64_enabled() else jnp.float32)


def host_f64_is_fine(x):
    # numpy (host) float64 is not device policy — engine_numpy uses this
    return np.asarray(x).astype(np.float64)


def f32_is_fine(x, size):
    return jnp.zeros((size,), dtype=jnp.float32) + x
