"""FLX001 fixture: host-sync hazards inside traced code.

Each seeded violation carries a trailing ``# expect: FLXnnn`` marker;
tests/test_floxlint.py parses the markers and asserts the rule reports
exactly these (rule, line) pairs and nothing else.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def jitted_mean(x):
    total = jnp.sum(x)
    return float(total) / x.size  # expect: FLX001


@functools.partial(jax.jit, static_argnums=(1,))
def jitted_threshold(x, cutoff):
    mask = x > cutoff
    if bool(jnp.any(mask)):  # expect: FLX001
        return x
    return jnp.zeros_like(x)


def _kernel_body(codes, array):
    partial_sum = jnp.sum(array)
    host_value = partial_sum.item()  # expect: FLX001
    rounded = np.round(array)  # expect: FLX001
    return host_value, rounded


compiled_kernel = jax.jit(_kernel_body)


def host_side_is_fine(values):
    # NOT traced: plain helper, never jitted — float()/np.* here is legit
    arr = np.asarray(values)
    return float(arr.mean())


@jax.jit
def metadata_access_is_fine(x):
    # shape/dtype reads are static under trace — no finding
    scale = 1.0 / x.shape[-1]
    return jnp.sum(x) * scale


def _build_streaming_step(size):
    # the streaming-executor shape: a step closure built by a factory and
    # handed to jax.jit with a donated carry. Debugging donation ("is the
    # accumulator still alive?") tends to introduce exactly these
    # host-syncs INSIDE the traced closure — a device->host pull per slab.
    def step(state, slab, codes):
        total = jnp.sum(slab)
        if float(total) == 0.0:  # expect: FLX001
            state = jnp.zeros((size,), slab.dtype)
        snapshot = np.asarray(state)  # expect: FLX001
        return state + total, snapshot

    return jax.jit(step, donate_argnums=(0,))
