"""Suppression fixture: every hazard here carries a disable comment, so the
expected finding set for this file is EMPTY."""

import jax
import jax.numpy as jnp


@jax.jit
def deliberate_host_pull(x):
    total = jnp.sum(x)
    return float(total)  # floxlint: disable=FLX001


def deliberate_narrow_cast(x):
    return x.astype(jnp.bfloat16)  # floxlint: disable=FLX003


def deliberate_compat_probe():
    return jax.shard_map  # floxlint: disable=FLX004
