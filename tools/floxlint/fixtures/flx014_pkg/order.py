"""FLX014 fixture: an A->B / B->A inversion across the call graph, a
plain-Lock self-deadlock, and the clean RLock re-entry shape."""

import threading

_A = threading.Lock()
_B = threading.Lock()
_R = threading.RLock()
_SELF = threading.Lock()


def ab() -> None:
    with _A:
        with _B:  # expect: FLX014
            pass


def ba() -> None:
    with _B:
        _use_a()


def _use_a() -> None:
    with _A:
        pass


def self_deadlock() -> None:
    with _SELF:
        _inner()  # expect: FLX014


def _inner() -> None:
    with _SELF:
        pass


def reenter() -> None:
    with _R:
        _again()


def _again() -> None:
    with _R:  # clean: re-entering an RLock is its contract
        pass
