"""Fixture: the endpoint surface for the FLX017 contract-endpoints diff."""


def do_GET(self):
    path = self.path
    if path == "/healthz":
        return self._send(200)
    if path == "/metrics":  # expect: FLX017
        return self._send(200)
    return self._send(404)
