"""Fixture: FLX017 contract-docs drift — doc-side findings anchor here."""  # expect: FLX017

_REQUEST_FIELDS = {"func", "array", "by"}


class ServeError(Exception):
    code = "serve_error"


class ShedGate(ServeError):
    code = "f17_shed"


class DrainGate(ServeError):  # expect: FLX017
    code = "f17_drain"


async def _amain(msg: dict) -> dict | None:
    op = msg.get("op")
    if op == "stats":
        return {"op": "stats", "ok": True}
    if op == "profile":  # expect: FLX017
        return {"op": "profile", "ok": True, "dir": msg.get("dir")}
    return None
