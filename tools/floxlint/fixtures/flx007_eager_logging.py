"""Seeded FLX007 violations: eager-formatted logging in library code.

Every violating line carries the corpus's trailing expect-marker; the clean
shapes below pin the rule's negative space (lazy %-args, constant messages,
non-logger .debug attributes). Every violation in THIS file is mechanically
fixable — ``--fix`` must rewrite it to lazy %-args so the output re-lints
clean and is byte-stable on a second pass (the bare-print half of FLX007,
which has no mechanical fix, lives in flx007_print.py).
"""

import logging

logger = logging.getLogger("flox_tpu.fixture")
log = logging.getLogger("flox_tpu.fixture.child")


def eager_fstring(ngroups):
    logger.debug(f"ngroups={ngroups}")  # expect: FLX007


def eager_fstring_multi(nslabs, nbytes):
    logger.debug(f"staged {nslabs} slabs ({nbytes} bytes, 100% done)")  # expect: FLX007


def eager_percent(size):
    logger.info("size=%d" % size)  # expect: FLX007


def eager_percent_tuple(start, stop):
    logger.info("slab [%d, %d)" % (start, stop))  # expect: FLX007


def eager_concat(name):
    logger.warning("failed for " + name)  # expect: FLX007


def eager_concat_str_call(count):
    logger.warning("retries=" + str(count))  # expect: FLX007


def eager_format(path):
    log.error("cannot read {}".format(path))  # expect: FLX007


def eager_log_method(level, n):
    logger.log(level, f"slabs={n}")  # expect: FLX007


def eager_inline_getlogger(x):
    logging.getLogger("flox_tpu").debug(f"x={x}")  # expect: FLX007


def clean_lazy_args(ngroups, size):
    logger.debug("ngroups=%d size=%d", ngroups, size)


def clean_constant_message():
    logger.info("stream finished")


def clean_exception_lazy(exc):
    logger.warning("retrying after %s", exc)


def clean_not_a_logger(tracer, x):
    # .debug on a non-logger receiver is not a logging call
    tracer.debug(f"x={x}")


def clean_numeric_binop(a, b):
    logger.debug("%s", a + b)
