"""Clean fixture: idiomatic flox_tpu-style code — zero findings expected."""

import jax
import jax.numpy as jnp
import numpy as np

_CACHE: dict = {}


@jax.jit
def segment_mean(codes, array, *, size: int = 8):
    ones = jnp.ones_like(array)
    totals = jax.ops.segment_sum(array, codes, num_segments=size)
    counts = jax.ops.segment_sum(ones, codes, num_segments=size)
    return totals / jnp.where(counts > 0, counts, 1)


def cached_program(shape: tuple, dtype: str):
    cache_key = (shape, dtype)
    fn = _CACHE.get(cache_key)
    if fn is None:
        fn = jax.jit(lambda x: x * 2)
        _CACHE[cache_key] = fn
    return fn


def host_summary(values) -> float:
    arr = np.asarray(values)
    return float(arr.sum())
