"""FLX002 fixture: recompile traps in program-cache keys."""

import jax
import numpy as np

_PROGRAM_CACHE: dict = {}


def lookup_with_list_key(shape, opts):
    cache_key = (shape, [o for o in opts])  # expect: FLX002
    return _PROGRAM_CACHE.get(cache_key)


def lookup_with_array_key(codes):
    codes_arr = np.asarray(codes)
    cache_key = ("reduce", codes_arr)  # expect: FLX002
    fn = _PROGRAM_CACHE.get(cache_key)
    if fn is None:
        fn = jax.jit(lambda x: x)
        _PROGRAM_CACHE[cache_key] = fn
    return fn


def lookup_with_fstring_key(values):
    values_arr = np.asarray(values)
    key = f"program-{values_arr}"  # expect: FLX002
    return _PROGRAM_CACHE.get(key)


def dict_in_subscript(kwargs):
    return _PROGRAM_CACHE[{"kw": kwargs}]  # expect: FLX002


def good_key(codes, method):
    codes_arr = np.asarray(codes)
    # static metadata and content-hashing are the sanctioned key material
    cache_key = (codes_arr.shape, str(codes_arr.dtype), method, codes_arr.tobytes())
    return _PROGRAM_CACHE.get(cache_key)


def good_fstring_key(codes):
    codes_arr = np.asarray(codes)
    key = f"program-{codes_arr.dtype}"  # metadata only: fine
    return _PROGRAM_CACHE.get(key)
