"""FLX013 fixture: an executor-submitted writer missing the lock, and a
protected helper whose callers all hold it (held-at-entry: clean)."""

import threading
from concurrent.futures import ThreadPoolExecutor

_JOBS: dict = {}
_JOBS_LOCK = threading.Lock()


def _record(key: str, value: float) -> None:
    _JOBS[key] = value  # expect: FLX013


def record_locked(key: str, value: float) -> None:
    with _JOBS_LOCK:
        _JOBS[key] = value


def _store_entry(key: str, value: float) -> None:
    # every caller holds _JOBS_LOCK, so this write is protected (the
    # held-at-entry meet proves it — no finding here)
    _JOBS[key] = value


def record_via_helper(key: str, value: float) -> None:
    with _JOBS_LOCK:
        _store_entry(key, value)


def submit_all(executor: ThreadPoolExecutor, items) -> None:
    for key, value in items:
        executor.submit(_record, key, value)
