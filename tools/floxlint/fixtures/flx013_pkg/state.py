"""FLX013 fixture: a shared dict written from a worker thread without the
lock its other writers hold (plus the clean shapes around it)."""

import threading

_STATE = {"ready": False}
_TABLE: dict = {}  # single-writer: never flagged
_STATE_LOCK = threading.Lock()


def set_ready(flag: bool) -> None:
    _STATE["ready"] = flag  # expect: FLX013


def set_reason(reason: str) -> None:
    with _STATE_LOCK:
        _STATE["reason"] = reason


def note(key: str, value: str) -> None:
    _TABLE[key] = value


def _worker() -> None:
    set_ready(True)


def start() -> None:
    t = threading.Thread(target=_worker, daemon=True)
    t.start()
    with _STATE_LOCK:
        _STATE["started"] = True
