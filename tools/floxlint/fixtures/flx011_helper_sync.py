"""FLX011 fixture: host-syncs leaking through local helpers.

FLX001 cannot see these — the sync lives in a plain (untraced) helper —
but the call happens inside a jitted region, so the device->host pull still
lands mid-program. The clean shapes pin the negative space: helpers that
stay on device, helpers fed static metadata, and host-side callers."""

import jax
import jax.numpy as jnp
import numpy as np


def _threshold(value):
    return float(value) > 0.5


def _to_host(block):
    return np.asarray(block)


def _item_of(arr):
    first = arr.reshape(-1)
    return first.item()


def _on_device(block):
    return jnp.sum(block)


def _shape_of(block):
    # metadata-only helper: no sync on the value itself
    return block.shape[-1]


@jax.jit
def bad_helper_sync(x):
    total = jnp.sum(x)
    if _threshold(total):  # expect: FLX011
        return x
    return x * 2


@jax.jit
def bad_helper_np(x):
    host = _to_host(x)  # expect: FLX011
    return x + host.shape[0]


@jax.jit
def bad_helper_item(x):
    return x * _item_of(x)  # expect: FLX011


@jax.jit
def clean_helper_on_device(x):
    return _on_device(x) + 1


@jax.jit
def clean_metadata_helper(x):
    return x / _shape_of(x)


def clean_host_side_caller(values):
    # not traced: helpers may sync freely here
    arr = _to_host(values)
    return _threshold(arr.mean())
