"""Fixture: FLX020 untyped-escape analysis over the serve call graph."""


class ServeError(Exception):
    code = "f20_base"


class BoomError(ServeError):
    code = "f20_boom"


class Dispatcher:
    def _execute(self, msg: dict) -> dict:
        self._validate(msg)
        narrow = self._guarded(msg)
        broad = self._screened(msg)
        self._typed(msg)
        return {"ok": narrow, "broad": broad}

    def _validate(self, msg: dict) -> None:
        if "op" not in msg:
            raise ValueError("missing op")  # expect: FLX020

    def _guarded(self, msg: dict) -> bool:
        try:
            self._parse(msg)
        except KeyError:
            return False
        return True

    def _parse(self, msg: dict) -> None:
        raise KeyError("contained: the only caller catches KeyError")

    def _screened(self, msg: dict) -> bool:
        try:
            return self._risky(msg)
        except Exception as exc:
            classify_error(exc)
            return False

    def _risky(self, msg: dict) -> bool:
        raise RuntimeError("contained: the only caller screens broadly")

    def _typed(self, msg: dict) -> None:
        raise BoomError("typed raises become wire answers, never escapes")


def classify_error(exc: Exception) -> str:
    return type(exc).__name__
