"""Seeded FLX007 violations: bare ``print`` in library code.

Split from flx007_eager_logging.py because print has NO mechanical fix
(rewriting it needs a logger decision) — keeping it here lets the autofix
self-tests require the eager-logging fixture to re-lint fully clean after
``--fix``. The clean shapes pin the CLI exemptions: prints inside ``main``
functions and under ``if __name__ == "__main__":`` are the sanctioned
output channel.
"""


def bare_print(result):
    print(result)  # expect: FLX007


def bare_print_formatted(ngroups):
    print(f"ngroups={ngroups}")  # expect: FLX007


def main(argv=None):
    # the CLI surface: print IS the output channel here
    print("report follows")
    return 0


if __name__ == "__main__":
    print("running fixture as a script")
