"""Fixture: FLX018 producer side — the docs-table drift anchors here."""  # expect: FLX018

METRICS = None

_SEED_GAUGES = (
    "f18.depth",
    "f18.ghost_gauge",  # expect: FLX018
)


def serve_one() -> None:
    METRICS.inc("f18.requests")
    METRICS.set_gauge("f18.depth", 0)
