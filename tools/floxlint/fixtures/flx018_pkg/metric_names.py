"""Fixture: shared-constants module — every name must resolve to an emit."""

F18_REQUESTS = "f18.requests"
F18_BOGUS = "f18.bogus"  # expect: FLX018
