"""Fixture: FLX018 consumer side — registry reads and scrape literals."""

from .emit import METRICS


def snapshot() -> dict:
    return {
        "requests": METRICS.get("f18.requests"),
        "missing": METRICS.get("f18.missing"),  # expect: FLX018
    }


def pick(row: dict) -> bool:
    if row.get("name") == "flox_tpu_f18_requests_total":
        return True
    return row.get("name") == "flox_tpu_f18_request_total"  # expect: FLX018
