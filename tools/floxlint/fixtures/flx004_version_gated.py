"""FLX004 fixture: version-gated jax APIs accessed without the compat shim."""

import jax
from jax.experimental.shard_map import shard_map as raw_shard_map  # expect: FLX004


def build_program(program, mesh, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(  # expect: FLX004
            program, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )
    )


def tree_helpers(tree):
    return jax.tree_map(lambda x: x + 1, tree)  # expect: FLX004


def flat_index(axes):
    return jax.lax.axis_size(axes[0])  # expect: FLX004


def modern_tree_is_fine(tree):
    return jax.tree.map(lambda x: x + 1, tree)
