"""FLX016 fixture: a signal handler reaching a non-reentrant lock, next to
the sanctioned RLock and spawn-a-thread shapes."""

import signal
import threading

_LOCK = threading.Lock()
_RLOCK = threading.RLock()
_FLUSHED: dict = {}
_DRAINED: dict = {}


def _on_term(signum, frame) -> None:
    flush()


def flush() -> None:
    with _LOCK:  # expect: FLX016
        _FLUSHED["at"] = True


def _on_usr1(signum, frame) -> None:
    drain()


def drain() -> None:
    with _RLOCK:  # clean: reentrant locks are the sanctioned handler shape
        _DRAINED["at"] = True


def _on_usr2(signum, frame) -> None:
    # clean: handing off to a daemon thread is signal-safe by construction
    threading.Thread(target=_background, daemon=True).start()


def _background() -> None:
    with _LOCK:
        _FLUSHED["bg"] = True


def install() -> None:
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(getattr(signal, "SIGUSR1", signal.SIGTERM), _on_usr1)
    signal.signal(getattr(signal, "SIGUSR2", signal.SIGTERM), _on_usr2)
