"""Seeded FLX006 violations: broad excepts in retry loops that swallow.

Every violating line carries the corpus's trailing expect-marker; the clean
shapes below them pin the rule's negative space (re-raise, classify,
specific types, no loop, nested scope).
"""

import time


def retry_swallows_everything(loader):
    for _attempt in range(3):
        try:
            return loader()
        except Exception:  # expect: FLX006
            time.sleep(0.1)
    return None


def bare_except_in_while(fetch):
    result = None
    while result is None:
        try:
            result = fetch()
        except:  # noqa: E722  # expect: FLX006
            continue
    return result


def tuple_catch_swallows(fetch, log):
    for _ in range(5):
        try:
            return fetch()
        except (ValueError, Exception):  # expect: FLX006
            log("retrying")
    return None


def clean_reraises_on_last_attempt(loader):
    for attempt in range(3):
        try:
            return loader()
        except Exception:
            if attempt == 2:
                raise
            time.sleep(0.1)
    return None


def clean_routes_through_classifier(loader, sink):
    from flox_tpu.resilience import classify_error

    for _attempt in range(3):
        try:
            return loader()
        except Exception as exc:
            sink(classify_error(exc))
    return None


def clean_specific_types(loader):
    for _attempt in range(3):
        try:
            return loader()
        except (OSError, ConnectionError):
            time.sleep(0.1)
    return None


def clean_probe_not_in_loop(probe):
    try:
        return probe()
    except Exception:
        return None


def clean_nested_scope_is_not_this_loops_retry_path(items):
    out = []
    for item in items:
        def parse(raw=item):
            try:
                return int(raw)
            except Exception:
                return None

        out.append(parse())
    return out
