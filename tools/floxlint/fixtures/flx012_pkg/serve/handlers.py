"""Seeded FLX012 violations: unforensic broad excepts in a serve-plane
module (this file lives under a ``serve`` path component, which is the
rule's scope). Violating lines carry the corpus's trailing expect-marker;
the clean shapes below pin the negative space (re-raise, classify, record,
specific types)."""

from flox_tpu import telemetry
from flox_tpu.resilience import classify_error


def swallows_silently(answer, work):
    try:
        return work()
    except Exception:  # expect: FLX012
        answer({"ok": False})


def bare_except_swallows(answer, work):
    try:
        return work()
    except:  # noqa: E722  # expect: FLX012
        return None


def tuple_catch_swallows(answer, work, log):
    try:
        return work()
    except (ValueError, BaseException):  # expect: FLX012
        log("oops")


def clean_reraises(work):
    try:
        return work()
    except Exception:
        raise


def clean_classifies(work):
    try:
        return work()
    except Exception as exc:
        if classify_error(exc) != "transient":
            raise
        return None


def clean_records_to_flight(answer, work):
    try:
        return work()
    except Exception as exc:
        telemetry.record_serve_error(exc, what="fixture")
        answer({"ok": False, "error": type(exc).__name__})


def clean_dumps_flight(work):
    try:
        return work()
    except Exception:
        telemetry.flight_dump(reason="fixture")
        return None


def clean_specific_types(answer, work):
    try:
        return work()
    except (ValueError, KeyError) as exc:  # naming types IS classifying
        answer({"ok": False, "error": type(exc).__name__})
