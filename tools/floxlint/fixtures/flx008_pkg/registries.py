"""Module-level state for the FLX008 fixture.

``_CLEARED_CACHE`` is referenced by ``cache.clear_all`` directly,
``_PROBE_RESULT`` through the one-level ``reset_probes`` helper — both
clean. ``_ORPHAN_CACHE`` accretes at runtime but is unreachable from
``clear_all``: the seeded violation. ``KERNELS`` is a static registry
populated at import time only, which the rule must exempt (tables are not
caches), and ``_SCRATCH`` mutates at runtime but is not cache-named."""

_CLEARED_CACHE: dict = {}
_ORPHAN_CACHE: dict = {}  # expect: FLX008
_PROBE_RESULT: list = []

KERNELS = {
    "sum": sum,
    "max": max,
}

_SCRATCH: list = []


def remember(key, value):
    _CLEARED_CACHE[key] = value
    _ORPHAN_CACHE[key] = value
    _SCRATCH.append(key)
    return value


def probe_once():
    if not _PROBE_RESULT:
        _PROBE_RESULT.append(True)
    return _PROBE_RESULT[0]


def reset_probes():
    _PROBE_RESULT.clear()
