"""Cache module for the FLX008 fixture: ``clear_all`` clears the named
cache directly and the probe memo through a one-level helper call, but
misses ``_ORPHAN_CACHE``."""


def clear_all():
    from .registries import _CLEARED_CACHE, reset_probes

    _CLEARED_CACHE.clear()
    reset_probes()
