"""FLX008 fixture package: a mini flox_tpu with a ``cache`` module whose
``clear_all`` misses one runtime cache (see registries.py markers)."""
