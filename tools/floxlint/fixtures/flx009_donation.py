"""FLX009 fixture: donated buffers referenced after dispatch.

The donation idiom (pipeline.maybe_donate / jax.jit donate_argnums) lets
XLA alias the carry into the output; the buffer passed in is dead after the
call. The seeded violations reference it anyway; the clean shapes pin the
sanctioned carry idiom (rebind the result to the same name) and non-Name
arguments the rule must leave alone."""

import jax
import jax.numpy as jnp


def maybe_donate(fun, *, donate_argnums):
    # stand-in for flox_tpu.pipeline.maybe_donate (basename-matched)
    return jax.jit(fun, donate_argnums=donate_argnums)


def build_step():
    def step(state, slab):
        return state + jnp.sum(slab)

    return jax.jit(step, donate_argnums=(0,))


def bad_direct_jit(state, slab):
    step = jax.jit(lambda acc, x: acc + x, donate_argnums=(0,))
    out = step(state, slab)
    return out + state  # expect: FLX009


def bad_through_factory(state, slab):
    step = build_step()
    new = step(state, slab)
    total = jnp.sum(state)  # expect: FLX009
    return new, total


def bad_maybe_donate(state, slab):
    jitted = maybe_donate(lambda acc, x: acc + x, donate_argnums=(0,))
    out = jitted(state, slab)
    del out
    return state  # expect: FLX009


def bad_second_position(prefix, counts, slab):
    update = jax.jit(lambda p, c, s: (p, c + s), donate_argnums=(1,))
    prefix, new_counts = update(prefix, counts, slab)
    return new_counts + counts.shape[0], counts  # expect: FLX009


def bad_loop_redonation(state, slabs, outs):
    step = build_step()
    for slab in slabs:
        outs.append(step(state, slab))  # expect: FLX009
    return outs


def clean_carry_rebind(state, slabs):
    step = build_step()
    for slab in slabs:
        state = step(state, slab)
    return state


def clean_loop_rebind_later(state, slabs):
    step = build_step()
    for slab in slabs:
        out = step(state, slab)
        state = out
    return state


def clean_tuple_rebind(prefix, counts, slab):
    update = jax.jit(lambda p, c, s: (p + 1, c + s), donate_argnums=(0, 1))
    prefix, counts = update(prefix, counts, slab)
    return prefix, counts


def clean_fresh_value(slabs):
    step = build_step()
    state = jnp.zeros((8,))
    for slab in slabs:
        state = step(state, slab)
    return state


def clean_expression_arg(state, slab):
    step = build_step()
    out = step(state + 0.0, slab)  # donated operand is a fresh temporary
    return out + state


def clean_undonated(state, slab):
    plain = jax.jit(lambda acc, x: acc + x)
    out = plain(state, slab)
    return out + state
