"""Definition site for the FLX005 fixture package exports."""

from typing import Any


def untyped_reduce(array, codes, size=8):  # expect: FLX005
    return array, codes, size


def untyped_scan(array, *by, func: str = "cumsum"):  # expect: FLX005
    return array, by, func


def annotated_reduce(array: Any, codes: Any, *, size: int = 8) -> Any:
    return array, codes, size


def _not_exported(a, b):
    # missing annotations but not in __all__ -> no finding
    return a + b
