"""FLX005 fixture package: exports with and without annotations.

Expected-findings markers live at the definition sites in ``api.py``.
"""

from .api import annotated_reduce, untyped_reduce, untyped_scan

__all__ = ["annotated_reduce", "untyped_reduce", "untyped_scan", "_private_helper"]
