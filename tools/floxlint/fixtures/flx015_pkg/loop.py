"""FLX015 fixture: blocking calls on the event loop — direct, one call
down, and the clean ``to_thread`` / bounded-lock shapes."""

import asyncio
import queue
import threading
import time

from . import io_helpers

_Q: queue.Queue = queue.Queue()
_AQ: asyncio.Queue = asyncio.Queue()
_LOCK = threading.Lock()


async def tick() -> None:
    time.sleep(0.01)  # expect: FLX015
    await asyncio.sleep(0)


async def snapshot() -> None:
    io_helpers.dump("x")  # the open() inside is the finding site


async def pull() -> object:
    return _Q.get()  # expect: FLX015


async def offloaded() -> None:
    # clean: the to_thread boundary hands dump's IO to a worker thread
    await asyncio.to_thread(io_helpers.dump, "x")


async def guarded() -> int:
    # clean: bounded lock acquisition around a dict poke is idiomatic
    with _LOCK:
        return 1


async def drained() -> object:
    # clean: asyncio.Queue.get is awaited, not blocking
    return await _AQ.get()
