"""FLX015 fixture helper: file IO that must only run off-loop."""


def dump(payload: str) -> None:
    with open("/tmp/flx015-fixture", "w") as fh:  # expect: FLX015
        fh.write(payload)
