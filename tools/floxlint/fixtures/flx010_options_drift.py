"""FLX010 fixture: OPTIONS fields drifting from their env/validator mirrors.

``good_knob`` carries the full triangle (env mirror + validator; the docs
leg is skipped here because the fixture corpus has no docs/ directory next
to its lint root). The seeded violations drop one leg each."""

import os


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


OPTIONS = {
    "good_knob": _env_int("FLOX_TPU_GOOD_KNOB", 4),
    "good_path_knob": os.environ.get("FLOX_TPU_GOOD_PATH_KNOB") or None,
    "no_env_mirror": 0.25,  # expect: FLX010
    "no_validator": _env_int("FLOX_TPU_NO_VALIDATOR", 8),  # expect: FLX010
}

_VALIDATORS = {
    "good_knob": lambda x: x >= 0,
    "good_path_knob": lambda x: x is None or isinstance(x, str),
    "no_env_mirror": lambda x: 0 < x <= 1,
}
