"""floxlint: JAX-hazard static analysis for the flox_tpu codebase.

An AST-based linter for the failure modes that erase TPU performance without
failing any test:

* FLX001 — host-sync hazard: ``np.*`` / ``float()`` / ``int()`` / ``bool()``
  / ``.item()`` applied to traced values inside jitted code.
* FLX002 — recompile trap: unhashable or array-content-derived components in
  jit/program cache keys.
* FLX003 — dtype-policy violation: narrow-float (bf16/f16) casts or
  accumulators outside ``flox_tpu/dtypes.py``, and ``jnp.float64`` use that
  bypasses the x64 gate.
* FLX004 — version-gated API access: ``jax.shard_map``-style attributes that
  must go through the compat shim in ``flox_tpu/parallel/mesh.py``.
* FLX005 — untyped public API: functions exported from ``__init__.py``
  missing parameter or return annotations.

Run as ``python -m tools.floxlint flox_tpu/``. Suppress a finding with a
trailing ``# floxlint: disable=FLX001`` comment (comma-separated rule ids or
``all``), or a whole file with ``# floxlint: disable-file=FLX001``.
"""

from .core import Finding, LintError, lint_file, lint_paths
from .registry import RULES, get_rules

__all__ = ["Finding", "LintError", "RULES", "get_rules", "lint_file", "lint_paths"]
