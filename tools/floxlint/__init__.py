"""floxlint: JAX-hazard static analysis for the flox_tpu codebase.

An AST-based linter for the failure modes that erase TPU performance (or
corrupt results) without failing any test. The per-file rules:

* FLX001 — host-sync hazard: ``np.*`` / ``float()`` / ``int()`` / ``bool()``
  / ``.item()`` applied to traced values inside jitted code.
* FLX002 — recompile trap: unhashable or array-content-derived components in
  jit/program cache keys.
* FLX003 — dtype-policy violation: narrow-float (bf16/f16) casts or
  accumulators outside ``flox_tpu/dtypes.py``, and ``jnp.float64`` use that
  bypasses the x64 gate.
* FLX004 — version-gated API access: ``jax.shard_map``-style attributes that
  must go through the compat shim in ``flox_tpu/parallel/mesh.py``.
* FLX005 — untyped public API: functions exported from ``__init__.py``
  missing parameter or return annotations.
* FLX006 — swallowed retry exception: broad ``except`` in retry loops that
  neither re-raises nor routes through ``resilience.classify_error``.
* FLX007 — eager logging: f-string/%/.format-built log messages and bare
  ``print()`` in library code.

The semantic rules run over a **project index** (the whole lint tree parsed
once, imports and package re-exports resolved, plus a call graph) instead
of file-at-a-time:

* FLX008 — cache-registry completeness: every module-level mutable cache
  that accretes at runtime must be reachable from ``cache.clear_all``.
* FLX009 — donation-after-use: a value dispatched through a
  ``donate_argnums``/``maybe_donate`` path must not be referenced
  afterwards in the caller (tracked through one level of step factories).
* FLX010 — OPTIONS/env drift: every ``options.OPTIONS`` field needs its
  ``FLOX_TPU_*`` env mirror, a ``_VALIDATORS`` entry, and a docs/ mention.
* FLX011 — host-sync through helpers: interprocedural FLX001 — a traced
  function calling a local helper that ``.item()``s / ``np.*``s its traced
  argument.
* FLX012 — serve-unforensic except: broad serve-plane handlers that swallow
  without classifying or flight-recording.

The v3 concurrency rules add a per-function effect analysis (``effects.py``:
locks acquired with held-sets, blocking calls, shared-state writes) and an
interprocedural concurrency model (``concurrency.py``: spawn sites,
thread/signal reachability, held-at-entry meet, the global lock
acquisition-order graph) on top of the same index:

* FLX013 — unlocked shared write: module-level mutable state written on a
  thread- or signal-reachable path without the lock its other writers hold.
* FLX014 — lock-order inversion: a cycle in the global acquisition-order
  graph (export it with ``--lock-graph out.json``/``.dot``).
* FLX015 — blocking call on the event loop: sleep/file/socket/subprocess/
  queue/device-sync calls reachable from a coroutine with no
  ``to_thread``/executor boundary.
* FLX016 — signal-unsafe handler: a signal handler reaching a non-reentrant
  lock acquisition or a blocking wait.

Run as ``python -m tools.floxlint flox_tpu/ tools/``. Output formats:
``human`` (default), ``json``, and ``sarif`` (SARIF 2.1.0 for GitHub code
scanning). ``--baseline FILE`` suppresses known findings and fails on
baseline drift (stale entries); ``--update-baseline`` writes the file.
``--fix`` applies the mechanical rewrites (FLX007 eager logging -> lazy
%-args, FLX004 version-gate wrapping). ``--explain FLXnnn`` prints a rule's
rationale, example, and fix from the registry. Suppress a finding with a trailing
``# floxlint: disable=FLX001`` comment (comma-separated rule ids or
``all``), the ``# noqa: FLX001`` alias, or a whole file with
``# floxlint: disable-file=FLX001``.
"""

from .core import Finding, LintError, lint_file, lint_paths
from .registry import RULES, get_rules, rule_id_range

__all__ = [
    "Finding",
    "LintError",
    "RULES",
    "get_rules",
    "lint_file",
    "lint_paths",
    "rule_id_range",
]
