"""Lint driver: file discovery, parsing, suppressions, rule dispatch."""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator, Sequence

_SUPPRESS_RE = re.compile(r"#\s*floxlint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\s]+)")
#: ``# noqa: FLX001[,FLX002]`` is accepted as a line-disable alias — the ids
#: are mandatory, so ruff-style bare ``# noqa`` (or ``# noqa: E722``) never
#: silences floxlint findings
_NOQA_RE = re.compile(r"#\s*noqa:\s*((?:FLX\d{3}[,\s]*)+)", re.IGNORECASE)

#: directory names pruned while recursing into a lint root (passing such a
#: directory — or a file inside one — explicitly still lints it: the
#: self-test suite lints the seeded fixture corpus that way)
_PRUNED_DIR_NAMES = frozenset({"fixtures"})


@dataclass(frozen=True, order=True)
class Finding:
    """One lint finding, addressed by (path, line) so output sorts stably."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def format_human(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def as_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule,
            "message": self.message,
        }


class LintError(Exception):
    """Unrecoverable driver error (bad path, unreadable file)."""


@dataclass
class Suppressions:
    """Per-file suppression comments, parsed from the token stream (not the
    AST — comments never reach the AST)."""

    file_rules: frozenset[str] = frozenset()
    line_rules: dict[int, frozenset[str]] = field(default_factory=dict)

    def active(self, rule: str, line: int) -> bool:
        for ruleset in (self.file_rules, self.line_rules.get(line, frozenset())):
            if "ALL" in ruleset or rule.upper() in ruleset:
                return True
        return False


def parse_suppressions(source: str) -> Suppressions:
    file_rules: set[str] = set()
    line_rules: dict[int, frozenset[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return Suppressions()
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if m:
            kind, raw = m.group(1), m.group(2)
            rules = frozenset(r.strip().upper() for r in raw.split(",") if r.strip())
            if kind == "disable-file":
                file_rules |= rules
                continue
        else:
            m = _NOQA_RE.search(tok.string)
            if not m:
                continue
            rules = frozenset(
                r.upper() for r in re.findall(r"FLX\d{3}", m.group(1), re.IGNORECASE)
            )
        line = tok.start[0]
        line_rules[line] = line_rules.get(line, frozenset()) | rules
    return Suppressions(file_rules=frozenset(file_rules), line_rules=line_rules)


@dataclass
class FileContext:
    """Everything a rule needs to analyze one file."""

    path: Path
    source: str
    tree: ast.Module
    #: directory being linted, for package-level rules (FLX005)
    root: Path | None = None

    @property
    def display_path(self) -> str:
        return str(self.path)


@dataclass
class ProjectContext:
    """Everything a project-scoped rule (``scope = "project"``) needs: the
    lint root, the parsed-once :class:`~tools.floxlint.index.ProjectIndex`,
    and the call graph over it. Rules implement ``check_project(pctx)``
    instead of ``check(ctx)``."""

    root: Path
    index: "object"  #: tools.floxlint.index.ProjectIndex
    callgraph: "object"  #: tools.floxlint.callgraph.CallGraph


def rule_scope(rule) -> str:
    """"file" (default) or "project"."""
    return getattr(rule, "scope", "file")


class _SuppressionIndex:
    """Lazily-loaded suppression tables keyed by path — findings may point
    into files other than the one being walked (FLX005 resolves exports to
    their definition sites)."""

    def __init__(self) -> None:
        self._cache: dict[str, Suppressions] = {}

    def seed(self, path: str, source: str) -> None:
        if path not in self._cache:
            self._cache[path] = parse_suppressions(source)

    def suppressed(self, finding: Finding) -> bool:
        sup = self._cache.get(finding.path)
        if sup is None:
            try:
                source = Path(finding.path).read_text()
            except OSError:
                return False
            sup = parse_suppressions(source)
            self._cache[finding.path] = sup
        return sup.active(finding.rule, finding.line)


def iter_python_files(paths: Sequence[str | Path]) -> Iterator[tuple[Path, Path]]:
    """Yield (file, lint_root) pairs for every .py under ``paths``.

    Directories named in ``_PRUNED_DIR_NAMES`` ("fixtures") strictly below a
    given root are skipped — ``floxlint tools/`` must not lint the seeded
    violation corpus — but a pruned directory passed explicitly as a path is
    linted in full (that is how the self-tests exercise the corpus)."""
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                rel_dirs = f.relative_to(p).parts[:-1]
                if any(part in _PRUNED_DIR_NAMES for part in rel_dirs):
                    continue
                yield f, p
        elif p.is_file():
            yield p, p.parent
        else:
            raise LintError(f"no such file or directory: {p}")


def lint_file(
    path: str | Path,
    rules: Iterable | None = None,
    *,
    root: Path | None = None,
    _index: _SuppressionIndex | None = None,
) -> list[Finding]:
    """Lint one file; returns findings after suppression filtering."""
    from .registry import get_rules

    path = Path(path)
    try:
        source = path.read_text()
    except OSError as exc:
        raise LintError(f"cannot read {path}: {exc}") from exc
    index = _index if _index is not None else _SuppressionIndex()
    index.seed(str(path), source)
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        return [
            Finding(
                path=str(path),
                line=exc.lineno or 1,
                col=(exc.offset or 1) - 1,
                rule="FLX000",
                message=f"syntax error: {exc.msg}",
            )
        ]
    ctx = FileContext(path=path, source=source, tree=tree, root=root)
    findings: list[Finding] = []
    for rule in rules if rules is not None else get_rules():
        if rule_scope(rule) != "file":
            continue  # project rules run once per root, via lint_paths
        findings.extend(rule.check(ctx))
    return sorted(f for f in findings if not index.suppressed(f))


def run_project_rules(
    project_rules: Sequence,
    files: Sequence[Path],
    root: Path,
    _index: _SuppressionIndex | None = None,
    project_index=None,
) -> list[Finding]:
    """Run ``scope == "project"`` rules once over ``files`` (one lint root),
    returning suppression-filtered findings. ``project_index`` short-circuits
    the parse when the caller restored one from ``--index-cache``."""
    if not project_rules:
        return []
    from .callgraph import CallGraph
    from .index import ProjectIndex

    index = _index if _index is not None else _SuppressionIndex()
    pidx = project_index if project_index is not None else ProjectIndex.build(files, root)
    pctx = ProjectContext(root=root, index=pidx, callgraph=CallGraph.build(pidx))
    findings: list[Finding] = []
    for rule in project_rules:
        findings.extend(rule.check_project(pctx))
    return sorted(f for f in findings if not index.suppressed(f))


def lint_run(
    paths: Sequence[str | Path],
    rules: Iterable | None = None,
    *,
    index_cache: str | Path | None = None,
) -> tuple[list[Finding], int]:
    """The one driver loop: file rules per file, project rules once per
    lint root over its whole file set, findings deduplicated (package-level
    rules can re-derive the same finding from several entry files).
    Returns (findings, files_checked). ``index_cache`` round-trips the
    parsed project index through a pickle while the tree is byte-identical
    (CI shares it between the gate and SARIF steps)."""
    from .registry import get_rules

    all_rules = list(rules) if rules is not None else get_rules()
    project_rules = [r for r in all_rules if rule_scope(r) == "project"]
    index = _SuppressionIndex()
    out: set[Finding] = set()
    files_checked = 0
    groups: dict[Path, list[Path]] = {}
    for f, lint_root in iter_python_files(paths):
        files_checked += 1
        out.update(lint_file(f, all_rules, root=lint_root, _index=index))
        groups.setdefault(lint_root, []).append(f)
    for lint_root in sorted(groups):
        files = groups[lint_root]
        cached = None
        if index_cache or project_rules:
            from . import index as index_mod

            if index_cache:
                cached = index_mod.load_cached(Path(index_cache), files, lint_root)
            if cached is None:
                cached = index_mod.ProjectIndex.build(files, lint_root)
                if index_cache:
                    index_mod.save_cache(Path(index_cache), cached, files)
        out.update(
            run_project_rules(
                project_rules, files, lint_root, _index=index, project_index=cached
            )
        )
    return sorted(out), files_checked


def lint_paths(paths: Sequence[str | Path], rules: Iterable | None = None) -> list[Finding]:
    """Findings-only wrapper over :func:`lint_run` (the stable public API)."""
    return lint_run(paths, rules)[0]
