"""Lint driver: file discovery, parsing, suppressions, rule dispatch."""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator, Sequence

_SUPPRESS_RE = re.compile(r"#\s*floxlint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\s]+)")


@dataclass(frozen=True, order=True)
class Finding:
    """One lint finding, addressed by (path, line) so output sorts stably."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def format_human(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def as_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule,
            "message": self.message,
        }


class LintError(Exception):
    """Unrecoverable driver error (bad path, unreadable file)."""


@dataclass
class Suppressions:
    """Per-file suppression comments, parsed from the token stream (not the
    AST — comments never reach the AST)."""

    file_rules: frozenset[str] = frozenset()
    line_rules: dict[int, frozenset[str]] = field(default_factory=dict)

    def active(self, rule: str, line: int) -> bool:
        for ruleset in (self.file_rules, self.line_rules.get(line, frozenset())):
            if "ALL" in ruleset or rule.upper() in ruleset:
                return True
        return False


def parse_suppressions(source: str) -> Suppressions:
    file_rules: set[str] = set()
    line_rules: dict[int, frozenset[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return Suppressions()
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        kind, raw = m.group(1), m.group(2)
        rules = frozenset(r.strip().upper() for r in raw.split(",") if r.strip())
        if kind == "disable-file":
            file_rules |= rules
        else:
            line = tok.start[0]
            line_rules[line] = line_rules.get(line, frozenset()) | rules
    return Suppressions(file_rules=frozenset(file_rules), line_rules=line_rules)


@dataclass
class FileContext:
    """Everything a rule needs to analyze one file."""

    path: Path
    source: str
    tree: ast.Module
    #: directory being linted, for package-level rules (FLX005)
    root: Path | None = None

    @property
    def display_path(self) -> str:
        return str(self.path)


class _SuppressionIndex:
    """Lazily-loaded suppression tables keyed by path — findings may point
    into files other than the one being walked (FLX005 resolves exports to
    their definition sites)."""

    def __init__(self) -> None:
        self._cache: dict[str, Suppressions] = {}

    def seed(self, path: str, source: str) -> None:
        if path not in self._cache:
            self._cache[path] = parse_suppressions(source)

    def suppressed(self, finding: Finding) -> bool:
        sup = self._cache.get(finding.path)
        if sup is None:
            try:
                source = Path(finding.path).read_text()
            except OSError:
                return False
            sup = parse_suppressions(source)
            self._cache[finding.path] = sup
        return sup.active(finding.rule, finding.line)


def iter_python_files(paths: Sequence[str | Path]) -> Iterator[tuple[Path, Path]]:
    """Yield (file, lint_root) pairs for every .py under ``paths``."""
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                yield f, p
        elif p.is_file():
            yield p, p.parent
        else:
            raise LintError(f"no such file or directory: {p}")


def lint_file(
    path: str | Path,
    rules: Iterable | None = None,
    *,
    root: Path | None = None,
    _index: _SuppressionIndex | None = None,
) -> list[Finding]:
    """Lint one file; returns findings after suppression filtering."""
    from .registry import get_rules

    path = Path(path)
    try:
        source = path.read_text()
    except OSError as exc:
        raise LintError(f"cannot read {path}: {exc}") from exc
    index = _index if _index is not None else _SuppressionIndex()
    index.seed(str(path), source)
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        return [
            Finding(
                path=str(path),
                line=exc.lineno or 1,
                col=(exc.offset or 1) - 1,
                rule="FLX000",
                message=f"syntax error: {exc.msg}",
            )
        ]
    ctx = FileContext(path=path, source=source, tree=tree, root=root)
    findings: list[Finding] = []
    for rule in rules if rules is not None else get_rules():
        findings.extend(rule.check(ctx))
    return sorted(f for f in findings if not index.suppressed(f))


def lint_paths(paths: Sequence[str | Path], rules: Iterable | None = None) -> list[Finding]:
    """Lint files/directories; deduplicates findings (package-level rules can
    re-derive the same finding from several entry files)."""
    index = _SuppressionIndex()
    out: set[Finding] = set()
    for f, lint_root in iter_python_files(paths):
        out.update(lint_file(f, rules, root=lint_root, _index=index))
    return sorted(out)
