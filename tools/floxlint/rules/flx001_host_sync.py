"""FLX001 — host-sync hazard inside traced code.

``float(x)`` / ``int(x)`` / ``bool(x)`` / ``x.item()`` / ``np.anything(x)``
on a traced value forces a device->host transfer (or a concretization error)
in the middle of a jitted program — the silent sync stalls the whole XLA
pipeline the paper's fused-bundle design exists to keep on device
(flox_tpu/core.py _jitted_bundle)."""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding
from .common import ImportMap, collect_traced_functions, collect_traced_names

_HOST_BUILTINS = ("float", "int", "bool", "complex")
_HOST_METHODS = ("item", "tolist", "to_py", "__array__")


class HostSyncRule:
    id = "FLX001"
    name = "host-sync-hazard"
    description = (
        "np.*/float()/int()/bool()/.item() applied to a traced value inside "
        "jitted or kernel code forces a device->host sync"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        imports = ImportMap.from_tree(ctx.tree)
        for fn in collect_traced_functions(ctx.tree, imports):
            traced = collect_traced_names(fn, imports)

            def is_traced_expr(node: ast.AST) -> bool:
                return any(
                    isinstance(sub, ast.Name) and sub.id in traced for sub in ast.walk(node)
                )

            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                # float(x) / int(x) / bool(x) / complex(x)
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _HOST_BUILTINS
                    and node.func.id not in imports.aliases
                    and node.args
                    and is_traced_expr(node.args[0])
                ):
                    yield Finding(
                        path=ctx.display_path,
                        line=node.lineno,
                        col=node.col_offset,
                        rule=self.id,
                        message=(
                            f"`{node.func.id}()` on a traced value inside "
                            f"`{fn.name}` forces a host sync; keep the value on "
                            "device (jnp ops) or hoist the conversion out of the "
                            "traced region"
                        ),
                    )
                    continue
                # x.item() / x.tolist() on a traced root
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _HOST_METHODS
                    and is_traced_expr(node.func.value)
                ):
                    yield Finding(
                        path=ctx.display_path,
                        line=node.lineno,
                        col=node.col_offset,
                        rule=self.id,
                        message=(
                            f"`.{node.func.attr}()` on a traced value inside "
                            f"`{fn.name}` forces a host sync"
                        ),
                    )
                    continue
                # np.<func>(traced) — numpy eagerly pulls the array to host
                if imports.resolves_to(node.func, "numpy") and any(
                    is_traced_expr(a) for a in node.args
                ):
                    yield Finding(
                        path=ctx.display_path,
                        line=node.lineno,
                        col=node.col_offset,
                        rule=self.id,
                        message=(
                            "numpy call on a traced value inside "
                            f"`{fn.name}` pulls the array to host; use the jnp "
                            "equivalent so the op stays in the XLA program"
                        ),
                    )
