"""FLX011 — host-sync leak through helpers (interprocedural FLX001).

FLX001 catches ``float(x)`` / ``.item()`` / ``np.*(x)`` on a traced value
*inside* a traced function. The same hazard one call away is invisible to a
per-file pass: a jitted region calls an innocent-looking local helper, and
the helper concretizes its argument. The sync still lands in the middle of
the XLA program — it just lives in another stack frame.

This rule closes that hole one level deep: for every project function it
precomputes which *parameters* flow into a host-sync operation
(``float``/``int``/``bool``/``complex`` builtins, ``.item()``-family
methods, ``np.*`` calls — the FLX001 set, seeded per-parameter so each
finding can name the guilty argument), then flags any call from a traced
function (FLX001's notion: jit-decorated, or passed by name to a tracing
entrypoint) that feeds a traced value into a sync-tainted position. The
finding points at the call site — the traced frame where the sync will
actually stall the pipeline.
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING, Iterator

from ..core import Finding
from .common import (
    ImportMap,
    collect_traced_functions,
    collect_traced_names,
    dotted_name,
)

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core import ProjectContext

_HOST_BUILTINS = ("float", "int", "bool", "complex")
_HOST_METHODS = ("item", "tolist", "to_py", "__array__")


class HelperHostSyncRule:
    id = "FLX011"
    name = "helper-host-sync"
    description = (
        "a traced function calls a local helper that host-syncs "
        "(float()/.item()/np.*) on the traced argument"
    )
    scope = "project"

    def check_project(self, pctx: "ProjectContext") -> Iterator[Finding]:
        tainted = _sync_tainted_params(pctx)
        if not tainted:
            return
        for mod in pctx.index.modules.values():
            traced_fns = collect_traced_functions(mod.tree, mod.imports)
            traced_ids = {id(fn) for fn in traced_fns}
            for fn in traced_fns:
                traced_names = collect_traced_names(fn, mod.imports)
                for call in ast.walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    callee_name = dotted_name(call.func)
                    if callee_name is None:
                        continue
                    resolved = pctx.index.resolve_symbol(mod.name, callee_name)
                    if resolved is None or resolved not in tainted:
                        continue
                    helper = pctx.index.function(resolved)
                    if helper is not None and id(helper.node) in traced_ids:
                        continue  # the helper is itself traced: FLX001 owns it
                    for param, reason in self._hazardous_args(
                        call, tainted[resolved], traced_names
                    ):
                        yield Finding(
                            path=str(mod.path),
                            line=call.lineno,
                            col=call.col_offset,
                            rule=self.id,
                            message=(
                                f"`{callee_name}()` host-syncs its parameter "
                                f"`{param}` ({reason}); calling it on a traced "
                                f"value inside `{fn.name}` forces a "
                                "device->host sync one frame down — inline a "
                                "jnp equivalent or hoist the call out of the "
                                "traced region"
                            ),
                        )

    def _hazardous_args(
        self, call: ast.Call, taint: dict, traced_names: set[str]
    ) -> Iterator[tuple[str, str]]:
        def is_traced(expr: ast.AST) -> bool:
            return any(
                isinstance(sub, ast.Name) and sub.id in traced_names
                for sub in ast.walk(expr)
            )

        params: list[str] = taint["params"]
        for i, arg in enumerate(call.args):
            if i < len(params) and params[i] in taint["tainted"] and is_traced(arg):
                yield params[i], taint["tainted"][params[i]]
        for kw in call.keywords:
            if kw.arg in taint["tainted"] and is_traced(kw.value):
                yield kw.arg, taint["tainted"][kw.arg]


def _sync_tainted_params(pctx: "ProjectContext") -> dict[str, dict]:
    """canonical function -> {"params": [names in positional order],
    "tainted": {param -> reason}} for helpers that host-sync a parameter."""
    out: dict[str, dict] = {}
    for mod in pctx.index.modules.values():
        for fi in mod.functions.values():
            fn = fi.node
            args = fn.args
            params = [a.arg for a in args.posonlyargs + args.args]
            all_params = params + [a.arg for a in args.kwonlyargs]
            tainted: dict[str, str] = {}
            for param in all_params:
                reason = _param_sync_reason(fn, param, mod.imports)
                if reason is not None:
                    tainted[param] = reason
            if tainted:
                out[fi.qualname] = {"params": params, "tainted": tainted}
    return out


def _param_sync_reason(fn, param: str, imports: ImportMap) -> str | None:
    """How (if at all) values derived from ``param`` reach a host-sync op
    in ``fn``'s own body."""
    derived = _derived_names(fn, param)

    def mentions(expr: ast.AST) -> bool:
        return any(
            isinstance(sub, ast.Name) and sub.id in derived
            for sub in ast.walk(expr)
        )

    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            continue
        if not isinstance(node, ast.Call):
            continue
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in _HOST_BUILTINS
            and node.func.id not in imports.aliases
            and node.args
            and mentions(node.args[0])
        ):
            # reasons carry no line numbers: they end up in finding
            # messages, which the baseline fingerprints line-free
            return f"via `{node.func.id}()`"
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _HOST_METHODS
            and mentions(node.func.value)
        ):
            return f"via `.{node.func.attr}()`"
        if imports.resolves_to(node.func, "numpy") and any(
            mentions(a) for a in node.args
        ):
            return f"via `{dotted_name(node.func)}(...)`"
    return None


def _derived_names(fn, param: str) -> set[str]:
    """Names derived from ``param`` inside ``fn`` (fixpoint over simple
    assignments, like FLX001's propagation but seeded from one parameter)."""
    derived = {param}
    for _ in range(2):
        before = len(derived)
        for node in ast.walk(fn):
            value = None
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                value, targets = node.value, list(node.targets)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            elif isinstance(node, ast.AugAssign):
                value, targets = node.value, [node.target]
            if value is None:
                continue
            if any(
                isinstance(sub, ast.Name) and sub.id in derived
                for sub in ast.walk(value)
            ):
                for t in targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            derived.add(sub.id)
        if len(derived) == before:
            break
    return derived
