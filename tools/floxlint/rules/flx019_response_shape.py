"""FLX019 — response-shape drift.

The serve protocol's error envelope is load-bearing: a router retries on
``code == "load_shed"`` with ``retry_after_ms`` backoff, sheds on
``circuit_open``, and re-resolves on ``unknown_dataset`` — so an error
answer that lacks a machine-readable ``code`` silently downgrades every
client to string-matching ``message``. And the documented per-op response
rows are the client's deserialization guide: a field the doc promises
that the handler never produces is a KeyError waiting in every consumer.

Two checks, both scoped to *protocol modules* (modules defining a
top-level ``_REQUEST_FIELDS`` set — nothing outside the wire layer is a
response envelope, so helper dicts elsewhere never match):

* an error-response dict literal (``"ok": False``) that carries no
  ``"code"`` key — exempt when the enclosing function spreads
  ``**_error_response(...)`` into it or assigns ``var["code"] = ...``
  (the shared-envelope construction pattern);
* a response field documented in the ``docs/serving.md`` contract:ops
  table that the op's handler never produces. (One direction only:
  handlers legitimately spread dynamic payloads — ``**info`` — so
  produced-but-undocumented fields are not knowable statically.)
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING, Iterator

from ..core import Finding
from .common import dotted_name
from ..contract import (
    cached_contract,
    cell_tokens,
    find_docs_file,
    parse_contract_tables,
    protocol_modules,
)

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core import ProjectContext


class ResponseShapeDriftRule:
    id = "FLX019"
    name = "response-shape-drift"
    description = (
        "an error response lacks the machine-readable 'code' field, or a "
        "documented response field is never produced by the op's handler"
    )
    scope = "project"
    example = (
        'answer({"id": rid, "ok": False, "message": "profiler busy"}) — no\n'
        '"code": the router cannot classify the failure and falls back to\n'
        "string-matching the message"
    )
    fix_hint = (
        "build error answers through _error_response(rid, exc) (spreads the\n"
        'typed envelope) or add an explicit "code" literal; for doc drift,\n'
        "regenerate the contract:ops row from the artifact"
    )

    def check_project(self, pctx: "ProjectContext") -> Iterator[Finding]:
        contract = cached_contract(pctx)
        seen_docs: set[str] = set()
        for mod in protocol_modules(pctx.index):
            yield from self._check_error_envelopes(mod)
            docs = find_docs_file(mod.path)
            if docs is None or str(docs) in seen_docs:
                continue
            seen_docs.add(str(docs))
            yield from self._check_documented_fields(
                pctx, mod.package, docs, contract
            )

    # -- "ok": False without "code" ----------------------------------------

    def _check_error_envelopes(self, mod) -> Iterator[Finding]:
        for fn_node, dicts in _dicts_by_function(mod.tree):
            exempt = fn_node is not None and _assigns_code_subscript(fn_node)
            for node in dicts:
                keys = {
                    k.value
                    for k in node.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                }
                if "code" in keys:
                    continue
                if not _is_error_envelope(node):
                    continue
                if exempt or _spreads_error_response(node):
                    continue
                yield Finding(
                    path=str(mod.path), line=node.lineno, col=node.col_offset,
                    rule=self.id,
                    message=(
                        'error response ("ok": False) carries no '
                        'machine-readable "code" — clients fall back to '
                        "string-matching; route it through _error_response() "
                        "or add an explicit code literal"
                    ),
                )

    # -- documented fields the handler never produces ----------------------

    def _check_documented_fields(self, pctx, pkg, docs, contract):
        try:
            tables = parse_contract_tables(docs.read_text())
        except OSError:
            return
        for row in tables.get("ops", ()):
            cells = list(row.items())
            if not cells:
                continue
            op_tokens = cell_tokens(cells[0][1])
            fields_cell = row.get("response fields", "")
            for op in op_tokens:
                entry = contract["ops"].get(op)
                if entry is None or entry["module"].partition(".")[0] != pkg:
                    continue  # undeclared ops are FLX017's finding
                produced = set(entry["response_fields"])
                for token in cell_tokens(fields_cell):
                    if token not in produced:
                        mod = pctx.index.modules.get(entry["module"])
                        yield Finding(
                            path=str(mod.path) if mod else entry["module"],
                            line=entry["line"], col=0, rule=self.id,
                            message=(
                                f"{docs.name} documents response field "
                                f"{token!r} for op {op!r} but the handler "
                                "never produces it — clients indexing the "
                                "field will KeyError"
                            ),
                        )


def _dicts_by_function(tree: ast.Module):
    """(enclosing function or None, dict literals) pairs covering the whole
    module, each dict attributed to its innermost function."""
    owner: dict[int, ast.AST | None] = {}

    def mark(node, fn):
        for child in ast.iter_child_nodes(node):
            inner = (
                child
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
                else fn
            )
            if isinstance(child, ast.Dict):
                owner[id(child)] = fn
            mark(child, inner)

    mark(tree, None)
    groups: dict[int, tuple[ast.AST | None, list[ast.Dict]]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            fn = owner.get(id(node))
            key = id(fn) if fn is not None else 0
            groups.setdefault(key, (fn, []))[1].append(node)
    return list(groups.values())


def _is_error_envelope(node: ast.Dict) -> bool:
    for k, v in zip(node.keys, node.values):
        if (
            isinstance(k, ast.Constant)
            and k.value == "ok"
            and isinstance(v, ast.Constant)
            and v.value is False
        ):
            return True
    return False


def _spreads_error_response(node: ast.Dict) -> bool:
    for k, v in zip(node.keys, node.values):
        if k is None and isinstance(v, ast.Call):
            called = dotted_name(v.func)
            if called and called.split(".")[-1] == "_error_response":
                return True
    return False


def _assigns_code_subscript(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Subscript)
            and isinstance(node.targets[0].slice, ast.Constant)
            and node.targets[0].slice.value == "code"
        ):
            return True
    return False
