"""FLX004 — version-gated JAX API accessed without the compat shim.

``jax.shard_map`` exists only in newer jax releases (older ones spell it
``jax.experimental.shard_map.shard_map`` with ``check_rep`` instead of
``check_vma``); ``jax.tree_map`` is removed in newer ones. Bare access works
on the developer's jax and AttributeErrors on the deployment's — the
ROADMAP's production posture needs every such attribute to go through one
shim (``flox_tpu/parallel/mesh.py::shard_map``) so the version fallback
lives in exactly one place. The shim itself carries an inline
``# floxlint: disable=FLX004``."""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding
from .common import ImportMap, dotted_name

#: canonical attribute paths that MUST be reached through a compat shim,
#: mapped to the remediation message
_GATED_APIS = {
    "jax.shard_map": "use flox_tpu.parallel.mesh.shard_map (falls back to jax.experimental.shard_map and maps check_vma->check_rep)",
    "jax.experimental.shard_map": "import it only inside the flox_tpu.parallel.mesh.shard_map shim",
    "jax.lax.axis_size": "use flox_tpu.parallel.mesh.axis_size (falls back to the static psum(1, axis) idiom)",
    "jax.tree_map": "removed in newer jax; use jax.tree.map",
    "jax.tree_multimap": "removed in newer jax; use jax.tree.map",
    "jax.tree_util.tree_multimap": "removed in newer jax; use jax.tree.map",
}


class VersionGatedApiRule:
    id = "FLX004"
    name = "version-gated-api"
    description = (
        "bare access to a jax API that only exists in some jax versions "
        "(jax.shard_map, jax.tree_map, ...) — must go through the compat shim"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        imports = ImportMap.from_tree(ctx.tree)
        reported: set[tuple[int, str]] = set()

        def report(node: ast.AST, api: str) -> Iterator[Finding]:
            if (node.lineno, api) in reported:
                return
            reported.add((node.lineno, api))
            yield Finding(
                path=ctx.display_path,
                line=node.lineno,
                col=node.col_offset,
                rule=self.id,
                message=f"version-gated API `{api}`: {_GATED_APIS[api]}",
            )

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                resolved = imports.resolve(node)
                if resolved is None:
                    continue
                if resolved in _GATED_APIS:
                    yield from report(node, resolved)
                else:
                    for api in _GATED_APIS:
                        if resolved.startswith(api + "."):
                            yield from report(node, api)
                            break
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for api in _GATED_APIS:
                    if node.module == api or node.module.startswith(api + "."):
                        yield from report(node, api)
                    else:
                        for a in node.names:
                            if f"{node.module}.{a.name}" in _GATED_APIS:
                                yield from report(node, f"{node.module}.{a.name}")
            elif isinstance(node, ast.Import):
                for a in node.names:
                    for api in _GATED_APIS:
                        if a.name == api or a.name.startswith(api + "."):
                            yield from report(node, api)
