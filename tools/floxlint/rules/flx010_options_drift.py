"""FLX010 — OPTIONS/env drift.

Every knob in ``flox_tpu.options.OPTIONS`` is part of a triangle: the
programmatic field, its ``FLOX_TPU_*`` environment mirror (how CI matrices
and operators flip modes without code changes), and its ``_VALIDATORS``
entry (the set-time check that rejects what the env seeding also refuses —
the "cannot seed what set_options refuses" contract from PR 3). A field
missing any corner drifts silently: an env-only knob cannot be validated, a
validator-only knob cannot be swept in CI, and an undocumented knob cannot
be discovered. This rule pins all three statically:

* **env mirror** — the field's value expression must mention a
  ``FLOX_TPU_*`` string constant (``_env_int("FLOX_TPU_X", ...)``,
  ``os.environ.get("FLOX_TPU_X")``, ...);
* **set-time validation** — the field must have a ``_VALIDATORS`` entry;
* **docs** — the field name must appear somewhere under ``docs/`` (checked
  only when a ``docs/`` directory exists next to the lint root, so fixture
  corpora and scratch trees skip it).

Applies to any module that defines both a module-level ``OPTIONS`` dict
literal and a ``_VALIDATORS`` dict literal.
"""

from __future__ import annotations

import ast
from functools import lru_cache
from pathlib import Path
from typing import TYPE_CHECKING, Iterator

from ..core import Finding

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core import ProjectContext


class OptionsEnvDriftRule:
    id = "FLX010"
    name = "options-env-drift"
    description = (
        "an OPTIONS field is missing its FLOX_TPU_* env mirror, its "
        "_VALIDATORS entry, or a mention in docs/"
    )
    scope = "project"

    def check_project(self, pctx: "ProjectContext") -> Iterator[Finding]:
        docs_text = _docs_text(pctx.root.resolve().parent / "docs")
        for mod in pctx.index.modules.values():
            options = _toplevel_dict(mod.tree, "OPTIONS")
            validators = _toplevel_dict(mod.tree, "_VALIDATORS")
            if options is None or validators is None:
                continue
            validated = {
                k.value
                for k in validators.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
            for key, value in zip(options.keys, options.values):
                if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
                    continue
                field = key.value
                if not _has_env_mirror(value):
                    yield self._finding(
                        mod, key,
                        f"OPTIONS[{field!r}] has no FLOX_TPU_* env mirror — "
                        "seed it with _env_int/_env_float/_env_choice (or "
                        "os.environ.get) so CI matrices can flip it without "
                        "code changes",
                    )
                if field not in validated:
                    yield self._finding(
                        mod, key,
                        f"OPTIONS[{field!r}] has no _VALIDATORS entry — a bad "
                        "value must raise at set_options() time, not surface "
                        "mid-stream",
                    )
                if docs_text is not None and field not in docs_text:
                    yield self._finding(
                        mod, key,
                        f"OPTIONS[{field!r}] is not mentioned anywhere under "
                        "docs/ — document the knob (docs/implementation.md "
                        "carries the options table)",
                    )

    def _finding(self, mod, node: ast.AST, message: str) -> Finding:
        return Finding(
            path=str(mod.path), line=node.lineno, col=node.col_offset,
            rule=self.id, message=message,
        )


def _toplevel_dict(tree: ast.Module, name: str) -> ast.Dict | None:
    for node in tree.body:
        value: ast.AST | None = None
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        ):
            value = node.value
        elif (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == name
        ):
            value = node.value
        if isinstance(value, ast.Dict):
            return value
    return None


def _has_env_mirror(value: ast.AST) -> bool:
    for sub in ast.walk(value):
        if (
            isinstance(sub, ast.Constant)
            and isinstance(sub.value, str)
            and sub.value.startswith("FLOX_TPU_")
        ):
            return True
    return False


@lru_cache(maxsize=8)
def _docs_text_cached(docs_dir: str) -> str | None:
    d = Path(docs_dir)
    if not d.is_dir():
        return None
    chunks = []
    for md in sorted(d.rglob("*.md")):
        try:
            chunks.append(md.read_text())
        except OSError:
            continue
    return "\n".join(chunks)


def _docs_text(docs_dir: Path) -> str | None:
    return _docs_text_cached(str(docs_dir))
