"""FLX003 — dtype-policy violation.

The dtype policy lives in ``flox_tpu/dtypes.py`` (promotion / fill
resolution) and ``flox_tpu/kernels.py::_acc_dtype`` (bf16/f16 accumulate in
f32 and cast back once, at finalize). Two bug classes bypass it:

* casting to / allocating in a narrow float (bf16, f16): sums saturate at
  256 in bf16 — the exact bug class behind ``TestBf16Accumulation``;
* ``jnp.float64`` without the x64 gate: silently downcasts to f32 under
  default jax config, or flips program caches when ``jax_enable_x64``
  changes — every f64 choice must branch on ``x64_enabled()`` /
  ``jax.config.jax_enable_x64``.

Intentional narrowing at an API boundary belongs in ``dtypes.py`` (exempt)
or behind an explicit ``# floxlint: disable=FLX003``."""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding
from .common import ImportMap

#: modules allowed to spell dtype decisions directly
_EXEMPT_BASENAMES = ("dtypes.py",)

_NARROW_STRINGS = frozenset({"bfloat16", "float16", "half", "f2", "e"})
_NARROW_ATTRS = (
    "jax.numpy.bfloat16",
    "jax.numpy.float16",
    "numpy.float16",
    "numpy.half",
    "ml_dtypes.bfloat16",
)
_F64_ATTRS = ("jax.numpy.float64",)
_X64_GATE_MARKERS = ("x64_enabled", "jax_enable_x64")


class DtypePolicyRule:
    id = "FLX003"
    name = "dtype-policy"
    description = (
        "narrow-float (bf16/f16) casts or accumulators outside dtypes.py, and "
        "jnp.float64 use that bypasses the x64 gate"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.path.name in _EXEMPT_BASENAMES:
            return
        imports = ImportMap.from_tree(ctx.tree)
        gated = _gated_nodes(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            # x.astype(<dtype>)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("astype", "view")
                and node.args
            ):
                yield from self._check_dtype_value(ctx, imports, node.args[0], gated, "astype")
            # jnp.zeros(..., dtype=<dtype>) and friends
            for kw in node.keywords:
                if kw.arg in ("dtype", "preferred_element_type") and kw.value is not None:
                    yield from self._check_dtype_value(
                        ctx, imports, kw.value, gated, f"{kw.arg}="
                    )

    def _check_dtype_value(
        self,
        ctx: FileContext,
        imports: ImportMap,
        value: ast.AST,
        gated: set[int],
        where: str,
    ) -> Iterator[Finding]:
        narrow = False
        f64 = False
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            narrow = value.value in _NARROW_STRINGS
        elif imports.resolves_to(value, *_NARROW_ATTRS):
            narrow = True
        elif imports.resolves_to(value, *_F64_ATTRS):
            f64 = id(value) not in gated
        if narrow:
            yield Finding(
                path=ctx.display_path,
                line=value.lineno,
                col=value.col_offset,
                rule=self.id,
                message=(
                    f"narrow-float dtype in `{where}` — bf16/f16 accumulators "
                    "saturate (mantissa cannot count past 256); accumulate via "
                    "kernels._acc_dtype / the dtypes.py policy and cast back "
                    "once at finalize"
                ),
            )
        elif f64:
            yield Finding(
                path=ctx.display_path,
                line=value.lineno,
                col=value.col_offset,
                rule=self.id,
                message=(
                    f"`jnp.float64` in `{where}` without an x64 gate — under "
                    "default jax config this silently becomes f32; write "
                    "`jnp.float64 if utils.x64_enabled() else jnp.float32`"
                ),
            )


def _gated_nodes(tree: ast.Module) -> set[int]:
    """ids of AST nodes that sit inside an x64-gated conditional (an IfExp or
    If whose test mentions x64_enabled()/jax_enable_x64)."""
    gated: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.IfExp, ast.If)) and _mentions_gate(node.test):
            for sub in ast.walk(node):
                gated.add(id(sub))
    return gated


def _mentions_gate(test: ast.AST) -> bool:
    for sub in ast.walk(test):
        if isinstance(sub, ast.Name) and any(m in sub.id for m in _X64_GATE_MARKERS):
            return True
        if isinstance(sub, ast.Attribute) and any(m in sub.attr for m in _X64_GATE_MARKERS):
            return True
    return False
