"""FLX009 — donated buffer referenced after dispatch.

The streaming executor jits its step programs with ``donate_argnums`` (via
``pipeline.maybe_donate``) so the dense ``(…, size)`` carry updates in
place across slabs. Donation invalidates the argument buffer: XLA may alias
it into the output, so a caller that touches the donated value *after* the
dispatch reads freed (or silently overwritten) memory — on TPU this
surfaces as a ``Buffer has been deleted or donated`` error at best and as
wrong numerics at worst, and only on platforms where the donation probe
passes, which is exactly not the CPU where tests run.

The rule tracks, inside each function, names bound to a donating callable:

* directly — ``jax.jit(fn, donate_argnums=(0,))`` or
  ``maybe_donate(fn, donate_argnums=(0,))``, or
* through one level of helper calls — a project function whose return
  value is such a jit (the step-factory pattern), resolved via the call
  graph/index.

At each call of a donating name, any *plain-name* argument in a donated
position becomes dead unless the same statement rebinds it (the
``state = step(state, slab)`` carry idiom). A later load of a dead name —
before any rebinding — is the finding, reported at the offending load.
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING, Iterator

from ..core import Finding
from .common import ImportMap, assigned_names, dotted_name

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core import ProjectContext

_DONATE_KWARGS = ("donate_argnums", "donate_argnames")


class DonationAfterUseRule:
    id = "FLX009"
    name = "donation-after-use"
    description = (
        "a value passed through a donate_argnums/maybe_donate dispatch is "
        "referenced afterwards in the caller — the buffer may be freed or "
        "aliased into the output"
    )
    scope = "project"

    def check_project(self, pctx: "ProjectContext") -> Iterator[Finding]:
        factories = _donating_factories(pctx)
        for mod in pctx.index.modules.values():
            for fi in mod.functions.values():
                yield from self._check_function(
                    mod.name, mod.path, fi.node, mod.imports, pctx, factories
                )

    def _check_function(
        self, module, path, fn, imports: ImportMap, pctx, factories
    ) -> Iterator[Finding]:
        donating = _donating_names(module, fn, imports, pctx, factories)
        if not donating:
            return
        parents = _parent_map(fn)
        statements = _ordered_statements(fn)
        for stmt in statements:
            for call in _calls_in_statement(stmt):
                name = call.func.id if isinstance(call.func, ast.Name) else None
                if name is None or name not in donating:
                    continue
                positions = donating[name]
                donated_args = {
                    a.id
                    for i, a in enumerate(call.args)
                    if i in positions and isinstance(a, ast.Name)
                }
                killed = set(_stmt_assigned_names(stmt))
                for dead in sorted(donated_args - killed):
                    # loop back-edge: a donation inside a loop whose body
                    # never rebinds the name re-dispatches a freed buffer
                    # on the next iteration — same source line, so the
                    # linear next-use scan below cannot see it
                    loop = _enclosing_loop(fn, stmt, parents)
                    if loop is not None and dead not in _stored_names_in(loop):
                        yield Finding(
                            path=str(path),
                            line=call.lineno,
                            col=call.col_offset,
                            rule=self.id,
                            message=(
                                f"`{dead}` is donated into `{name}(...)` "
                                "inside a loop without being rebound — the "
                                "next iteration re-dispatches a freed/"
                                "aliased buffer; rebind the result to "
                                f"`{dead}` (carry idiom)"
                            ),
                        )
                        continue
                    use = _next_use(fn, dead, stmt)
                    if use is not None:
                        yield Finding(
                            path=str(path),
                            line=use.lineno,
                            col=use.col_offset,
                            rule=self.id,
                            # no line numbers in the message: the baseline
                            # fingerprints (path, rule, message) and must
                            # survive findings shifting up or down a file
                            message=(
                                f"`{dead}` was donated into `{name}(...)` "
                                "and is referenced afterwards — the buffer "
                                "may be freed/aliased by XLA; rebind the "
                                "result to the same name (carry idiom) or "
                                "copy before dispatch"
                            ),
                        )


def _donating_factories(pctx) -> dict[str, tuple[int, ...]]:
    """Canonical qualname -> donated positions, for project functions whose
    return value is a donating jit (one helper level)."""
    out: dict[str, tuple[int, ...]] = {}
    for mod in pctx.index.modules.values():
        for fi in mod.functions.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Return) or node.value is None:
                    continue
                positions = _donate_positions(node.value, mod.imports)
                if positions:
                    out[fi.qualname] = positions
    return out


def _donate_positions(value: ast.AST, imports: ImportMap) -> tuple[int, ...] | None:
    """Donated argnums if ``value`` is a donating-jit call, else None."""
    if not isinstance(value, ast.Call):
        return None
    fn_name = dotted_name(value.func)
    if fn_name is None:
        return None
    basename = fn_name.rpartition(".")[2]
    is_jit_like = imports.resolves_to(value.func, "jax.jit", "jax.pmap") or basename in (
        "jit", "maybe_donate"
    )
    if not is_jit_like:
        return None
    for kw in value.keywords:
        if kw.arg in _DONATE_KWARGS:
            positions = _int_tuple(kw.value)
            if positions:
                return positions
    return None


def _int_tuple(node: ast.AST) -> tuple[int, ...] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _donating_names(
    module: str, fn, imports: ImportMap, pctx, factories
) -> dict[str, tuple[int, ...]]:
    """Local names bound (in ``fn``) to a donating callable."""
    out: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        positions = _donate_positions(node.value, imports)
        if positions is None and isinstance(node.value, ast.Call):
            callee = dotted_name(node.value.func)
            if callee is not None:
                resolved = pctx.index.resolve_symbol(module, callee)
                if resolved is not None:
                    positions = factories.get(resolved)
        if positions:
            out[target.id] = positions
    return out


def _ordered_statements(fn) -> list[ast.stmt]:
    """All statements in ``fn``'s own body (nested defs excluded), in
    source order."""
    out: list[ast.stmt] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(child, ast.stmt):
                out.append(child)
            visit(child)

    visit(fn)
    return sorted(out, key=lambda s: (s.lineno, s.col_offset))


def _calls_in_statement(stmt: ast.stmt) -> Iterator[ast.Call]:
    """Calls in the statement's own expressions — nested statements (a For
    body, an If branch) are separate entries in ``_ordered_statements`` and
    carry their own kill sets, so descending into them here would re-process
    their calls with the wrong one."""

    def visit(node: ast.AST) -> Iterator[ast.Call]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.stmt, ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                yield child
            yield from visit(child)

    yield from visit(stmt)


def _stmt_assigned_names(stmt: ast.stmt) -> Iterator[str]:
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            yield from assigned_names(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        yield from assigned_names(stmt.target)
    elif isinstance(stmt, ast.For):
        yield from assigned_names(stmt.target)


def _parent_map(fn) -> dict[int, ast.AST]:
    parents: dict[int, ast.AST] = {}

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
            visit(child)

    visit(fn)
    return parents


def _enclosing_loop(fn, stmt: ast.stmt, parents: dict[int, ast.AST]):
    """Nearest For/While containing ``stmt`` inside ``fn`` (None if the
    statement is straight-line code)."""
    node: ast.AST | None = stmt
    while node is not None and node is not fn:
        node = parents.get(id(node))
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            return node
    return None


def _stored_names_in(scope: ast.AST) -> set[str]:
    return {
        node.id
        for node in ast.walk(scope)
        if isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store,))
    }


def _next_use(fn, name: str, after: ast.stmt) -> ast.Name | None:
    """First event on ``name`` after ``after``'s last line: a Load returns
    the node (finding), a Store ends the hazard (the name was rebound)."""
    boundary = getattr(after, "end_lineno", after.lineno) or after.lineno
    events: list[tuple[int, int, str, ast.Name]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id == name and node.lineno > boundary:
            kind = "load" if isinstance(node.ctx, ast.Load) else "store"
            events.append((node.lineno, node.col_offset, kind, node))
    for _, _, kind, node in sorted(events, key=lambda e: (e[0], e[1])):
        return node if kind == "load" else None
    return None
