"""FLX017 — undeclared or undocumented protocol surface.

The serve plane's external surface — protocol ops, machine-readable error
codes, HTTP endpoints — is contract-checked against the marker-delimited
tables in ``docs/serving.md`` (``<!-- contract:ops -->``,
``<!-- contract:errors -->``, ``<!-- contract:endpoints -->``). The
contract compiler (``tools/floxlint/contract.py``) extracts the code-side
surface from the AST; this rule diffs it against the doc tables in **both
directions**:

* an op / error code / endpoint implemented in code but absent from its
  table is *undocumented* — a client cannot discover it, and the fleet
  router (ROADMAP item 1) cannot generate a stub for it;
* a table row with no implementation is *undeclared* — clients coded
  against the doc will get ``unknown op`` answers at runtime.

Anchoring: the rule runs once per package that contains a *protocol
module* (a module defining a top-level ``_REQUEST_FIELDS`` string set)
and resolves the nearest ``docs/serving.md`` climbing from that module —
so fixture corpora carry their own ``docs/`` and the real tree resolves
to the repo-level one. Packages without a protocol module (tools, tests)
skip entirely. Code-side findings anchor at the drifting surface's
definition line; doc-side findings anchor at line 1 of the protocol
module (the owner of the surface the doc over-promises).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterator

from ..core import Finding
from ..contract import (
    cached_contract,
    cell_tokens,
    find_docs_file,
    parse_contract_tables,
    protocol_modules,
)

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core import ProjectContext


class ContractDocsDriftRule:
    id = "FLX017"
    name = "contract-docs-drift"
    description = (
        "a serve op, error code, or HTTP endpoint drifted between the code "
        "surface and the docs/serving.md contract tables"
    )
    scope = "project"
    example = (
        'docs/serving.md contract:ops table documents op `ghost` but no\n'
        "dispatch branch implements it; op `profile` is dispatched in\n"
        "serve/__main__.py but has no table row"
    )
    fix_hint = (
        "regenerate the table row from the artifact\n"
        "(python -m tools.floxlint --contract -) or remove the dead row;\n"
        "never hand-edit a surface into docs without implementing it"
    )

    def check_project(self, pctx: "ProjectContext") -> Iterator[Finding]:
        contract = cached_contract(pctx)
        anchors: dict[str, object] = {}
        for mod in protocol_modules(pctx.index):
            anchors.setdefault(mod.package, mod)
        for pkg in sorted(anchors):
            anchor = anchors[pkg]
            docs = find_docs_file(anchor.path)
            if docs is None:
                continue
            try:
                tables = parse_contract_tables(docs.read_text())
            except OSError:
                continue
            yield from self._check_ops(pctx, pkg, anchor, docs, tables, contract)
            yield from self._check_errors(pctx, pkg, anchor, docs, tables, contract)
            yield from self._check_endpoints(pctx, pkg, anchor, docs, tables, contract)

    # -- sections -----------------------------------------------------------

    def _check_ops(self, pctx, pkg, anchor, docs, tables, contract):
        code_ops = {
            op: entry
            for op, entry in contract["ops"].items()
            if entry["module"].partition(".")[0] == pkg
        }
        if "ops" not in tables:
            yield self._doc_finding(
                anchor,
                f"{docs.name} has no <!-- contract:ops --> table — the "
                f"{len(code_ops)} serve op(s) of package {pkg!r} are "
                "undocumented",
            )
            return
        doc_ops = _first_column(tables["ops"])
        for op in sorted(set(code_ops) - doc_ops):
            entry = code_ops[op]
            yield self._code_finding(
                pctx, entry["module"], entry["line"],
                f"serve op {op!r} is dispatched here but has no row in the "
                f"{docs.name} contract:ops table — undocumented surface",
            )
        for op in sorted(doc_ops - set(code_ops)):
            yield self._doc_finding(
                anchor,
                f"{docs.name} contract:ops table documents op {op!r} but no "
                "dispatch branch implements it — undeclared surface",
            )

    def _check_errors(self, pctx, pkg, anchor, docs, tables, contract):
        code_errors = {
            code: entry
            for code, entry in contract["errors"].items()
            if entry["module"].partition(".")[0] == pkg
        }
        if "errors" not in tables:
            if code_errors:
                yield self._doc_finding(
                    anchor,
                    f"{docs.name} has no <!-- contract:errors --> table — "
                    f"the {len(code_errors)} error code(s) of package "
                    f"{pkg!r} are undocumented",
                )
            return
        doc_codes = _first_column(tables["errors"])
        for code in sorted(set(code_errors) - doc_codes):
            entry = code_errors[code]
            yield self._code_finding(
                pctx, entry["module"], entry["line"],
                f"error code {code!r} "
                f"({entry['class'] or 'synthesized'}) is answered on the "
                f"wire but has no row in the {docs.name} contract:errors "
                "table — clients cannot classify it",
            )
        for code in sorted(doc_codes - set(code_errors)):
            yield self._doc_finding(
                anchor,
                f"{docs.name} contract:errors table documents code {code!r} "
                "but nothing in the package raises or answers it",
            )

    def _check_endpoints(self, pctx, pkg, anchor, docs, tables, contract):
        code_paths: dict[str, tuple[str, int]] = {}
        for module, paths in contract["endpoints"].items():
            if module.partition(".")[0] != pkg:
                continue
            for path, entry in paths.items():
                code_paths.setdefault(path, (module, entry["line"]))
        if "endpoints" not in tables:
            if code_paths:
                yield self._doc_finding(
                    anchor,
                    f"{docs.name} has no <!-- contract:endpoints --> table — "
                    f"the {len(code_paths)} HTTP endpoint(s) of package "
                    f"{pkg!r} are undocumented",
                )
            return
        doc_paths = _first_column(tables["endpoints"])
        for path in sorted(set(code_paths) - doc_paths):
            module, line = code_paths[path]
            yield self._code_finding(
                pctx, module, line,
                f"HTTP endpoint {path!r} is served here but has no row in "
                f"the {docs.name} contract:endpoints table",
            )
        for path in sorted(doc_paths - set(code_paths)):
            yield self._doc_finding(
                anchor,
                f"{docs.name} contract:endpoints table documents {path!r} "
                "but no handler serves it",
            )

    # -- finding constructors ----------------------------------------------

    def _code_finding(self, pctx, module: str, line: int, message: str) -> Finding:
        mod = pctx.index.modules.get(module)
        path = str(mod.path) if mod is not None else module
        return Finding(path=path, line=line, col=0, rule=self.id, message=message)

    def _doc_finding(self, anchor, message: str) -> Finding:
        return Finding(
            path=str(anchor.path), line=1, col=0, rule=self.id, message=message
        )


def _first_column(rows: list[dict]) -> set[str]:
    out: set[str] = set()
    for row in rows:
        if not row:
            continue
        first = next(iter(row.values()))
        out.update(cell_tokens(first))
    return out
