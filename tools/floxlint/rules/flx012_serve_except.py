"""FLX012 — unforensic broad except in the serve plane.

The serve tier answers errors instead of crashing on them — a malformed
line, a failed dispatch, or an unreadable manifest each gets a JSON
response or a log line, and the loop keeps serving. That discipline has a
failure mode of its own: a broad ``except Exception`` that swallows the
error WITHOUT consulting the resilience classifier and WITHOUT leaving a
flight-recorder trace makes the fault invisible — the serve chaos
postmortem (``telemetry.flight_dump``) shows a healthy replica that was
quietly eating device-loss errors for an hour. Every broad handler under
``flox_tpu/serve/`` must therefore either

* re-raise (``raise`` anywhere in the handler),
* classify (``resilience.classify_error`` — the FLX006 gate), or
* record (``telemetry.record_serve_error`` / ``telemetry.flight_dump`` —
  the answer path's forensic tail).

Handlers for specific exception types are always fine — naming the types
IS a classification. Scope: files with a ``serve`` path component, i.e.
the ``flox_tpu/serve/`` package (and the fixture corpus's ``serve`` dir).
"""

from __future__ import annotations

import ast
from pathlib import PurePath
from typing import Iterator

from ..core import FileContext, Finding

#: calling any of these inside the handler satisfies the rule
_SANCTIONED_CALLS = (
    "classify_error",
    "record_serve_error",
    "flight_dump",
)


class ServeBroadExceptRule:
    id = "FLX012"
    name = "serve-unforensic-except"
    description = (
        "bare `except:`/`except Exception:` in flox_tpu/serve/ that neither "
        "re-raises, consults resilience.classify_error, nor records to the "
        "flight recorder (telemetry.record_serve_error / flight_dump)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "serve" not in PurePath(ctx.display_path).parts:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not _catches_everything(handler.type):
                    continue
                if _reraises_classifies_or_records(handler):
                    continue
                yield Finding(
                    path=ctx.display_path,
                    line=handler.lineno,
                    col=handler.col_offset,
                    rule="FLX012",
                    message=(
                        "broad except in the serve plane swallows the error "
                        "invisibly; re-raise, consult "
                        "resilience.classify_error, or leave a flight trace "
                        "via telemetry.record_serve_error / flight_dump"
                    ),
                )


def _catches_everything(expr: ast.expr | None) -> bool:
    if expr is None:  # bare `except:`
        return True
    elts = expr.elts if isinstance(expr, ast.Tuple) else [expr]
    for el in elts:
        name = None
        if isinstance(el, ast.Name):
            name = el.id
        elif isinstance(el, ast.Attribute):
            name = el.attr
        if name in ("Exception", "BaseException"):
            return True
    return False


def _reraises_classifies_or_records(handler: ast.ExceptHandler) -> bool:
    for sub in ast.walk(handler):
        if isinstance(sub, ast.Raise):
            return True
        if isinstance(sub, ast.Call):
            fn = sub.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None
            )
            if name in _SANCTIONED_CALLS:
                return True
    return False
