"""FLX rule implementations, one module per rule."""

from .flx001_host_sync import HostSyncRule
from .flx002_recompile import RecompileTrapRule
from .flx003_dtype import DtypePolicyRule
from .flx004_version import VersionGatedApiRule
from .flx005_api import UntypedPublicApiRule

__all__ = [
    "HostSyncRule",
    "RecompileTrapRule",
    "DtypePolicyRule",
    "VersionGatedApiRule",
    "UntypedPublicApiRule",
]
