"""Shared AST machinery for the FLX rules: alias resolution (what does
``jnp`` mean in this module?) and a conservative traced-value propagation."""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` Attribute/Name chain -> "a.b.c"; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class ImportMap:
    """Local alias -> canonical dotted module/object path for one module."""

    aliases: dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_tree(cls, tree: ast.Module) -> "ImportMap":
        aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return cls(aliases)

    def resolve(self, node: ast.AST) -> str | None:
        """Canonical path of a Name/Attribute chain, e.g. ``jnp.sum`` ->
        "jax.numpy.sum" under ``import jax.numpy as jnp``."""
        name = dotted_name(node)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        base = self.aliases.get(head)
        if base is None:
            return name  # unimported chains resolve to themselves
        return f"{base}.{rest}" if rest else base

    def resolves_to(self, node: ast.AST, *prefixes: str) -> bool:
        resolved = self.resolve(node)
        if resolved is None:
            return False
        return any(resolved == p or resolved.startswith(p + ".") for p in prefixes)


def names_in(node: ast.AST) -> Iterator[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id


def assigned_names(target: ast.AST) -> Iterator[str]:
    """Plain-name targets of an assignment (tuples unpacked, no attrs/subs)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from assigned_names(elt)
    elif isinstance(target, ast.Starred):
        yield from assigned_names(target.value)


# canonical prefixes whose call results are traced/device values
TRACED_CALL_PREFIXES = ("jax.numpy", "jax.lax", "jax.nn", "jax.random", "jax.scipy")


def collect_traced_names(
    func: ast.FunctionDef | ast.AsyncFunctionDef, imports: ImportMap
) -> set[str]:
    """Names holding (potentially) traced values inside ``func``: every
    parameter, plus a fixpoint over assignments whose RHS mentions a traced
    name or calls into jax.numpy/jax.lax. Conservative in the
    under-approximating direction: attribute stores, globals, and values
    returned by unknown helpers are NOT considered traced."""
    traced: set[str] = set()
    args = func.args
    for a in args.posonlyargs + args.args + args.kwonlyargs:
        traced.add(a.arg)
    if args.vararg:
        traced.add(args.vararg.arg)
    if args.kwarg:
        traced.add(args.kwarg.arg)

    def rhs_traced(value: ast.AST) -> bool:
        for sub in ast.walk(value):
            if isinstance(sub, ast.Name) and sub.id in traced:
                return True
            if isinstance(sub, ast.Call) and imports.resolves_to(sub.func, *TRACED_CALL_PREFIXES):
                return True
        return False

    # two passes reach a fixpoint for straight-line + simple loop bodies
    for _ in range(2):
        before = len(traced)
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and rhs_traced(node.value):
                for t in node.targets:
                    traced.update(assigned_names(t))
            elif isinstance(node, ast.AugAssign) and (
                rhs_traced(node.value) or any(n in traced for n in names_in(node.target))
            ):
                traced.update(assigned_names(node.target))
            elif isinstance(node, ast.AnnAssign) and node.value is not None and rhs_traced(node.value):
                traced.update(assigned_names(node.target))
            elif isinstance(node, ast.For) and rhs_traced(node.iter):
                traced.update(assigned_names(node.target))
        if len(traced) == before:
            break
    return traced


# call targets that trace their function argument(s)
TRACING_ENTRYPOINTS = (
    "jax.jit",
    "jax.pmap",
    "jax.vmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.lax.fori_loop",
    "jax.lax.while_loop",
    "jax.lax.scan",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.map",
    "jax.lax.associative_scan",
    "jax.experimental.pallas.pallas_call",
)
# local helper names treated as tracing entrypoints wherever they appear
TRACING_ENTRYPOINT_BASENAMES = ("shard_map", "pallas_call", "jit", "checkpoint")


def _is_tracing_entrypoint(call: ast.Call, imports: ImportMap) -> bool:
    if imports.resolves_to(call.func, *TRACING_ENTRYPOINTS):
        return True
    name = dotted_name(call.func)
    return name is not None and name.split(".")[-1] in TRACING_ENTRYPOINT_BASENAMES


def collect_traced_functions(tree: ast.Module, imports: ImportMap) -> list[ast.FunctionDef]:
    """Function defs whose bodies run under a JAX trace: decorated with a
    tracing transform, or referenced by name as an argument to one. Nested
    defs inside a traced function are traced too."""
    traced_names: set[str] = set()
    defs: dict[str, list[ast.FunctionDef]] = {}

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
        if isinstance(node, ast.Call) and _is_tracing_entrypoint(node, imports):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    traced_names.add(arg.id)

    traced: list[ast.FunctionDef] = []
    seen: set[int] = set()

    def add_with_nested(fn: ast.FunctionDef) -> None:
        if id(fn) in seen:
            return
        seen.add(id(fn))
        traced.append(fn)
        for sub in ast.walk(fn):
            if sub is not fn and isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add_with_nested(sub)

    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(target, ast.Call):  # @partial(jax.jit, ...)
                target = target.func
            if _is_tracing_entrypoint_name(target, imports):
                add_with_nested(node)
                break
            if isinstance(dec, ast.Call) and imports.resolves_to(dec.func, "functools.partial"):
                if dec.args and _is_tracing_entrypoint_name(dec.args[0], imports):
                    add_with_nested(node)
                    break
        if node.name in traced_names:
            add_with_nested(node)
    return traced


def _is_tracing_entrypoint_name(node: ast.AST, imports: ImportMap) -> bool:
    if imports.resolves_to(node, *TRACING_ENTRYPOINTS):
        return True
    name = dotted_name(node)
    return name is not None and name.split(".")[-1] in TRACING_ENTRYPOINT_BASENAMES
