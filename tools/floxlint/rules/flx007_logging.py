"""FLX007 — eager-formatted logging and bare ``print`` in library code.

``logger.debug(f"ngroups={ngroups}")`` formats its message on EVERY call,
whether or not the debug level is enabled — on a hot path (per-slab, per
kernel dispatch) that is real work burned for messages nobody sees. The
lazy form, ``logger.debug("ngroups=%d", ngroups)``, defers formatting to
the logging framework, which skips it when the level is off. The same
applies to ``%``-interpolated, concatenated, and ``str.format`` message
arguments. ``logging.Logger`` supports exactly this, so the eager spellings
are always avoidable.

Bare ``print()`` in library code bypasses the logging tree entirely: users
cannot filter, redirect, or silence it, and on a worker thread it interleaves
arbitrarily. Library modules must log (or go through the telemetry layer);
``print`` belongs to CLI entry points only — calls inside a function named
``main`` (the sanctioned CLI entry convention, e.g.
``flox_tpu.telemetry.main``) or under ``if __name__ == "__main__":`` are
exempt.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding

#: logging method names whose first positional argument is a message
_LOG_METHODS = frozenset(
    {"debug", "info", "warning", "warn", "error", "exception", "critical", "log"}
)

#: receiver names that mark the call as a logging call (logger.debug /
#: log.warning / logging.info); anything else named .debug() is not ours
_LOGGER_NAMES = frozenset({"logger", "log", "logging"})


class EagerLoggingRule:
    id = "FLX007"
    name = "eager-logging"
    description = (
        "f-string/%/.format()-formatted logging calls (formatted even when the "
        "level is off) and bare print() in library code"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        exempt = _cli_exempt_nodes(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            finding = self._check_print(ctx, node, exempt) or self._check_log(ctx, node)
            if finding is not None:
                yield finding

    def _check_print(
        self, ctx: FileContext, node: ast.Call, exempt: set[int]
    ) -> Finding | None:
        if not (isinstance(node.func, ast.Name) and node.func.id == "print"):
            return None
        if id(node) in exempt:
            return None
        return Finding(
            path=ctx.display_path,
            line=node.lineno,
            col=node.col_offset,
            rule=self.id,
            message=(
                "bare print() in library code cannot be filtered or redirected; "
                "log through the module's `flox_tpu.*` child logger (print is "
                "fine in `main()` CLI entry points and under "
                '`if __name__ == "__main__":`)'
            ),
        )

    def _check_log(self, ctx: FileContext, node: ast.Call) -> Finding | None:
        msg = log_message_arg(node)
        if msg is None:
            return None
        how = _eager_kind(msg)
        if how is None:
            return None
        return Finding(
            path=ctx.display_path,
            line=msg.lineno,
            col=msg.col_offset,
            rule=self.id,
            message=(
                f"{how} logging message is formatted even when the level is "
                'off; use lazy %-style args: logger.debug("x=%s", x)'
            ),
        )


def log_message_arg(node: ast.Call) -> ast.AST | None:
    """The message argument of a logging call (``logger.debug(msg, ...)`` /
    ``logger.log(level, msg, ...)``), or None when ``node`` is not a logging
    call. Shared by the rule and the ``--fix`` rewriter so they cannot
    disagree about what counts as a log call."""
    func = node.func
    if not (isinstance(func, ast.Attribute) and func.attr in _LOG_METHODS):
        return None
    receiver = func.value
    recv_name = None
    if isinstance(receiver, ast.Name):
        recv_name = receiver.id
    elif isinstance(receiver, ast.Attribute):
        recv_name = receiver.attr
    elif isinstance(receiver, ast.Call):
        # logging.getLogger(...).debug(...)
        inner = receiver.func
        if isinstance(inner, ast.Attribute) and inner.attr == "getLogger":
            recv_name = "logger"
    if recv_name is None or recv_name.lower() not in _LOGGER_NAMES:
        return None
    # .log(level, msg, ...) carries the message second
    args = node.args[1:] if func.attr == "log" else node.args
    return args[0] if args else None


def _eager_kind(msg: ast.AST) -> str | None:
    """The eager-formatting kind of a message argument, or None if lazy."""
    if isinstance(msg, ast.JoinedStr):
        return "f-string"
    if isinstance(msg, ast.BinOp) and isinstance(msg.op, (ast.Mod, ast.Add)):
        # "x=%s" % x  /  "x=" + str(x): only flag when a string literal is
        # visibly involved — arithmetic between names is not a message build
        for side in (msg.left, msg.right):
            if isinstance(side, ast.Constant) and isinstance(side.value, str):
                return "%-interpolated" if isinstance(msg.op, ast.Mod) else "concatenated"
        return None
    if (
        isinstance(msg, ast.Call)
        and isinstance(msg.func, ast.Attribute)
        and msg.func.attr == "format"
    ):
        return ".format()-built"
    return None


def _cli_exempt_nodes(tree: ast.Module) -> set[int]:
    """ids of Call nodes inside a ``main`` function or an
    ``if __name__ == "__main__":`` block — the CLI surface where print IS
    the output channel."""
    exempt: set[int] = set()
    for node in ast.walk(tree):
        is_main_fn = (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in ("main", "_main")
        )
        is_main_guard = isinstance(node, ast.If) and _is_name_main_test(node.test)
        if is_main_fn or is_main_guard:
            for sub in ast.walk(node):
                exempt.add(id(sub))
    return exempt


def _is_name_main_test(test: ast.AST) -> bool:
    if not (isinstance(test, ast.Compare) and len(test.comparators) == 1):
        return False
    sides = (test.left, test.comparators[0])
    has_name = any(isinstance(s, ast.Name) and s.id == "__name__" for s in sides)
    has_main = any(
        isinstance(s, ast.Constant) and s.value == "__main__" for s in sides
    )
    return has_name and has_main
