"""FLX008 — cache-registry completeness.

``cache.clear_all`` is the package's analogue of the reference's
``flox.cache.cache.clear()``: benchmarks clear it between timing rounds and
tests rely on it to reset process state. Every module-level mutable cache
that accretes entries at runtime must therefore be reachable from it — a
cache that ``clear_all`` misses leaks memory across benchmark rounds and
lets one test's compiled programs poison the next's counters. PR 2 guarded
this with a runtime introspection test; this rule makes the same invariant
static, so a new ``_FOO_CACHE`` without the matching ``clear_all`` entry
fails the lint before any test runs.

Scope: modules in the same top-level package as a ``*.cache`` module that
defines ``clear_all``. A candidate is a module-level ALL_CAPS name whose
name marks it as cache-like (CACHE / MEMO / REGISTRY / SNAPSHOT / PROBE /
LEDGER — the cost-attribution tables of ISSUE 9 accrete per program key
exactly like a cache — / TABLE — the durable-store table of ISSUE 18
accretes one entry per opened store),
bound to a mutable container literal or constructor, and mutated from at
least one function body (import-time-populated static registries such as
``AGGREGATIONS`` or ``KERNELS`` are exempt: they are tables, not caches).
Reachability is name-based, matching the runtime test: the candidate's name
must appear in ``clear_all``'s body or in the body of a function
``clear_all`` directly calls (one level through the call graph).
"""

from __future__ import annotations

import ast
import re
from typing import TYPE_CHECKING, Iterator

from ..core import Finding
from .common import dotted_name

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core import ProjectContext

_NAME_TOKEN = re.compile(r"CACHE|MEMO|REGISTR|SNAPSHOT|PROBE|LEDGER|TABLE")
_CONTAINER_CALLS = frozenset(
    {"dict", "list", "set", "defaultdict", "OrderedDict", "Counter", "deque",
     # the in-repo LRU wrapper around OrderedDict (flox_tpu.cache.LRUCache):
     # the compiled-program caches are bound to it, and swapping a dict for
     # an LRU must not take a cache off this rule's radar
     "LRUCache"}
)
_MUTATING_METHODS = frozenset(
    {"append", "add", "update", "setdefault", "extend", "insert", "clear",
     "pop", "popitem", "remove", "discard", "appendleft"}
)


class CacheRegistryRule:
    id = "FLX008"
    name = "cache-registry-completeness"
    description = (
        "module-level mutable cache/registry that accretes at runtime but is "
        "not reachable from cache.clear_all"
    )
    scope = "project"

    def check_project(self, pctx: "ProjectContext") -> Iterator[Finding]:
        index = pctx.index
        mutating_positions = _param_mutating_positions(index)
        for mod in index.modules.values():
            if mod.name.rpartition(".")[2] != "cache":
                continue
            clear_all = mod.functions.get(f"{mod.name}.clear_all")
            if clear_all is None:
                continue
            cleared = _names_reached_from(pctx, clear_all.qualname)
            package = mod.package
            for other in index.modules.values():
                if other.package != package:
                    continue
                for cand_name, node in _candidates(other, pctx, mutating_positions):
                    if cand_name in cleared:
                        continue
                    yield Finding(
                        path=str(other.path),
                        line=node.lineno,
                        col=node.col_offset,
                        rule=self.id,
                        message=(
                            f"module-level cache/registry `{cand_name}` in "
                            f"`{other.name}` accretes at runtime but is never "
                            f"cleared by `{mod.name}.clear_all` — register it "
                            "there (or suppress with a rationale if it is "
                            "deliberately process-lifetime state)"
                        ),
                    )


def _names_reached_from(pctx: "ProjectContext", qualname: str) -> set[str]:
    """Every identifier mentioned in ``qualname``'s body plus the bodies of
    its direct project callees: Name ids, attribute tails, and import alias
    names (``from .cohorts import _COHORTS_CACHE`` counts as a mention)."""
    names: set[str] = set()
    fns = [qualname, *pctx.callgraph.reachable(qualname, max_depth=1)]
    for fn_qual in fns:
        fi = pctx.index.function(fn_qual)
        if fi is None:
            continue
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Name):
                names.add(node.id)
            elif isinstance(node, ast.Attribute):
                names.add(node.attr)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for a in node.names:
                    names.add(a.asname or a.name)
    return names


def _candidates(mod, pctx, mutating_positions) -> Iterator[tuple[str, ast.AST]]:
    """(name, defining node) for every runtime-mutated cache-like
    module-level container in ``mod``."""
    mutated = _runtime_mutated_names(mod.tree)
    mutated |= _mutated_through_calls(mod, pctx, mutating_positions)
    for node in mod.tree.body:
        targets: list[ast.Name] = []
        value: ast.AST | None = None
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            targets = [node.target]
            value = node.value
        if value is None or not targets:
            continue
        if not _is_mutable_container(value):
            continue
        for t in targets:
            name = t.id
            if name != name.upper() or not _NAME_TOKEN.search(name):
                continue
            if name in mutated:
                yield name, node


def _is_mutable_container(value: ast.AST) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        fn = value.func
        base = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        return base in _CONTAINER_CALLS
    return False


def _bare_mutation_targets(scope: ast.AST) -> set[str]:
    """Names mutated in place anywhere under ``scope``: subscript stores,
    deletes, or mutating method calls on the bare name."""
    mutated: set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
                    mutated.add(t.value.id)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
                    mutated.add(t.value.id)
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.attr in _MUTATING_METHODS
        ):
            mutated.add(node.func.value.id)
    return mutated


def _runtime_mutated_names(tree: ast.Module) -> set[str]:
    """Names mutated from inside any function body in the module (module
    top-level mutation is import-time population, which is exempt)."""
    mutated: set[str] = set()
    for outer in ast.walk(tree):
        if isinstance(outer, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mutated |= _bare_mutation_targets(outer)
    return mutated


def _param_mutating_positions(index) -> dict[str, set[int]]:
    """canonical function -> positional-arg indices it mutates in place
    (``def _probed_ok(memo, ...): memo.append(...)`` mutates position 0) —
    the one-level-interprocedural half of runtime-mutation detection."""
    out: dict[str, set[int]] = {}
    for mod in index.modules.values():
        for fi in mod.functions.values():
            args = fi.node.args
            params = [a.arg for a in args.posonlyargs + args.args]
            mutated = _bare_mutation_targets(fi.node)
            positions = {i for i, p in enumerate(params) if p in mutated}
            if positions:
                out[fi.qualname] = positions
    return out


def _mutated_through_calls(mod, pctx, mutating_positions: dict[str, set[int]]) -> set[str]:
    """Module-level names passed (from a function body in ``mod``) into a
    project function that mutates that parameter in place."""
    mutated: set[str] = set()
    for outer in ast.walk(mod.tree):
        if not isinstance(outer, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(outer):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None:
                continue
            resolved = pctx.index.resolve_symbol(mod.name, callee)
            if resolved is None:
                continue
            for i in mutating_positions.get(resolved, ()):
                if i < len(node.args) and isinstance(node.args[i], ast.Name):
                    mutated.add(node.args[i].id)
    return mutated
