"""FLX005 — untyped public API.

Every function a package exports through ``__init__.py`` (its ``__all__``,
falling back to the import list) is a contract surface: annotations are what
lets mypy — and downstream users embedding groupby_reduce in their own jitted
training steps — catch shape/dtype plumbing mistakes before they trace.
Triggered from the package ``__init__.py``; findings point at the definition
site in the source module."""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator

from ..core import FileContext, Finding


class UntypedPublicApiRule:
    id = "FLX005"
    name = "untyped-public-api"
    description = (
        "function exported from a package __init__.py is missing parameter "
        "or return annotations"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.path.name != "__init__.py":
            return
        pkg_dir = ctx.path.parent
        exported = _exported_names(ctx.tree)
        if not exported:
            return
        # exported name -> module file that defines it (relative imports only)
        sources = _relative_import_sources(ctx.tree, pkg_dir)
        for name in sorted(exported):
            target = sources.get(name)
            if target is None:
                # defined in __init__ itself?
                fn = _find_function(ctx.tree, name)
                if fn is not None:
                    yield from self._check_function(str(ctx.path), fn)
                continue
            mod_file, original = target
            try:
                mod_tree = ast.parse(mod_file.read_text(), filename=str(mod_file))
            except (OSError, SyntaxError):
                continue
            fn = _find_function(mod_tree, original)
            if fn is not None:
                yield from self._check_function(str(mod_file), fn)

    def _check_function(
        self, path: str, fn: ast.FunctionDef | ast.AsyncFunctionDef
    ) -> Iterator[Finding]:
        args = fn.args
        missing = [
            a.arg
            for a in args.posonlyargs + args.args + args.kwonlyargs
            if a.annotation is None and a.arg not in ("self", "cls")
        ]
        if args.vararg is not None and args.vararg.annotation is None:
            missing.append(f"*{args.vararg.arg}")
        if args.kwarg is not None and args.kwarg.annotation is None:
            missing.append(f"**{args.kwarg.arg}")
        needs_return = fn.returns is None
        if not missing and not needs_return:
            return
        parts = []
        if missing:
            parts.append(f"unannotated parameter(s): {', '.join(missing)}")
        if needs_return:
            parts.append("missing return annotation")
        yield Finding(
            path=path,
            line=fn.lineno,
            col=fn.col_offset,
            rule=self.id,
            message=f"exported function `{fn.name}` has {'; '.join(parts)}",
        )


def _exported_names(tree: ast.Module) -> set[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        return {
                            e.value
                            for e in node.value.elts
                            if isinstance(e, ast.Constant) and isinstance(e.value, str)
                        }
    # no __all__: every name imported from a submodule is public API
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.level > 0:
            names.update(a.asname or a.name for a in node.names if a.name != "*")
    return names


def _relative_import_sources(
    tree: ast.Module, pkg_dir: Path
) -> dict[str, tuple[Path, str]]:
    """local/exported name -> (module file, original name) for level-1
    relative imports (``from .core import groupby_reduce``)."""
    sources: dict[str, tuple[Path, str]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ImportFrom) and node.level == 1 and node.module):
            continue
        mod_file = pkg_dir / f"{node.module.replace('.', '/')}.py"
        if not mod_file.is_file():
            mod_file = pkg_dir / node.module.replace(".", "/") / "__init__.py"
            if not mod_file.is_file():
                continue
        for a in node.names:
            if a.name != "*":
                sources[a.asname or a.name] = (mod_file, a.name)
    return sources


def _find_function(
    tree: ast.Module, name: str
) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
    for node in tree.body:  # top-level defs only — methods are not exports
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name == name:
            return node
    return None
