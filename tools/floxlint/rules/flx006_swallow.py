"""FLX006 — swallowed exception in a retry loop.

A ``try`` inside a ``for``/``while`` whose handler catches ``Exception``
(or everything, via a bare ``except:``) and neither re-raises nor consults
the resilience classifier swallows fatal programming errors along with the
transient ones: the retry loop spins on a ``TypeError`` exactly as happily
as on an IO hiccup, and the bug surfaces hours later as a hung or silently
wrong stream. ``flox_tpu.resilience.classify_error`` is the sanctioned
gate — transient errors retry, everything else must surface — so a broad
handler in a retry path must either call a classifier or contain a
``raise``.

Handlers inside nested function definitions are NOT attributed to an outer
loop (a helper defined inside a loop is not that loop's retry path), and
handlers for specific exception types are always fine — naming the types
IS a classification.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding

#: calling any of these inside the handler counts as classifying the error
_CLASSIFIER_NAMES = ("classify_error", "is_transient", "is_fatal", "is_oom")


class SwallowedRetryExceptionRule:
    id = "FLX006"
    name = "swallowed-retry-exception"
    description = (
        "bare `except:`/`except Exception:` inside a retry loop that neither "
        "re-raises nor classifies the error swallows fatal failures"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        yield from _walk(ctx.tree, False, ctx.display_path)


def _walk(node: ast.AST, in_loop: bool, path: str) -> Iterator[Finding]:
    for child in ast.iter_child_nodes(node):
        child_in_loop = in_loop
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
            # a new scope: its handlers belong to ITS loops, not ours
            child_in_loop = False
        elif isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
            child_in_loop = True
        if isinstance(child, ast.Try) and child_in_loop:
            yield from _check_try(child, path)
        yield from _walk(child, child_in_loop, path)


def _check_try(node: ast.Try, path: str) -> Iterator[Finding]:
    for handler in node.handlers:
        if not _catches_everything(handler.type):
            continue
        if _reraises_or_classifies(handler):
            continue
        yield Finding(
            path=path,
            line=handler.lineno,
            col=handler.col_offset,
            rule="FLX006",
            message=(
                "broad except inside a retry loop swallows fatal errors along "
                "with transient ones; re-raise, or route through "
                "resilience.classify_error and re-raise the non-transient kinds"
            ),
        )


def _catches_everything(expr: ast.expr | None) -> bool:
    if expr is None:  # bare `except:`
        return True
    elts = expr.elts if isinstance(expr, ast.Tuple) else [expr]
    for el in elts:
        name = None
        if isinstance(el, ast.Name):
            name = el.id
        elif isinstance(el, ast.Attribute):
            name = el.attr
        if name in ("Exception", "BaseException"):
            return True
    return False


def _reraises_or_classifies(handler: ast.ExceptHandler) -> bool:
    for sub in ast.walk(handler):
        if isinstance(sub, ast.Raise):
            return True
        if isinstance(sub, ast.Call):
            fn = sub.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None
            )
            if name in _CLASSIFIER_NAMES:
                return True
    return False
