"""FLX014 — lock-order inversion across the call graph.

Two locks acquired in opposite orders on two paths deadlock the first time
the schedules interleave — and the RLock web across telemetry, exposition,
serve, and fleet had never been order-checked before this rule. The model
builds a global acquisition-order graph: an edge ``A -> B`` wherever B is
acquired while A is held, either by lexical nesting (``with A: with B:``,
``with A, B:``) or interprocedurally (holding A while calling into any
function whose call closure acquires B). A cycle in that graph is a
potential deadlock; a self-edge on a *plain* ``threading.Lock`` is a
guaranteed one (the PR 8 signal-handler bug class — re-entering a
non-reentrant lock). RLock self-edges are their design contract and are
not recorded.

The same graph ships two other ways: ``python -m tools.floxlint
--lock-graph out.json`` emits it as a JSON/dot review artifact (so the
router and dataset-registry PRs can diff lock discipline in review), and
``flox_tpu.faults.stress_schedule(lock_order=True)`` enforces it at
runtime with acquisition-order assertions under a hostile scheduler.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterator

from ..concurrency import model_for
from ..core import Finding

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core import ProjectContext


class LockOrderInversionRule:
    id = "FLX014"
    name = "lock-order-inversion"
    description = (
        "cycle in the global lock-acquisition-order graph (potential "
        "deadlock), or a non-reentrant lock re-acquired on its own path"
    )
    scope = "project"
    example = (
        "def ab():\n"
        "    with _A:\n"
        "        with _B: ...     # orders A -> B\n"
        "def ba():\n"
        "    with _B:\n"
        "        helper()         # helper() acquires _A: orders B -> A"
    )
    fix_hint = (
        "pick one global order for the locks in the cycle and acquire them "
        "in that order on every path (release and re-acquire if a path "
        "needs them the other way); for a self-cycle on a plain Lock, make "
        "it an RLock or drop the inner acquisition"
    )

    def check_project(self, pctx: "ProjectContext") -> Iterator[Finding]:
        model = model_for(pctx)
        graph = model.lock_graph
        for cycle in graph.cycles():
            edge_descr: list[str] = []
            first_site: str | None = None
            if len(cycle) == 1:
                site = graph.edges.get((cycle[0], cycle[0]), "")
                first_site = site
                edge_descr.append(f"{cycle[0]} -> {cycle[0]} at {site}")
                message = (
                    f"non-reentrant lock `{cycle[0]}` can be re-acquired on "
                    f"its own path ({site}) — a guaranteed self-deadlock; "
                    "make it an RLock or drop the nested acquisition"
                )
            else:
                ring = cycle + [cycle[0]]
                for a, b in zip(ring, ring[1:]):
                    site = graph.edges.get((a, b))
                    if site is None:
                        continue
                    if first_site is None:
                        first_site = site
                    edge_descr.append(f"{a} -> {b} at {site}")
                message = (
                    "lock-order inversion: "
                    + "; ".join(edge_descr)
                    + " — these locks are taken in conflicting orders and "
                    "can deadlock; pick one global order"
                )
            path, line = _split_site(first_site)
            yield Finding(
                path=path, line=line, col=0, rule=self.id, message=message
            )


def _split_site(site: str | None) -> tuple[str, int]:
    if not site:
        return "<unknown>", 1
    path, _, line = site.rpartition(":")
    try:
        return path or site, int(line)
    except ValueError:
        return site, 1
