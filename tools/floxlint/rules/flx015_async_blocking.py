"""FLX015 — blocking call reachable from inside the asyncio event loop.

The serve dispatcher is a single event loop: one coroutine that blocks —
``time.sleep``, file or socket IO, subprocess, a blocking queue get/put, a
``jax.device_get``, a thread join or ``future.result()`` — stalls *every*
in-flight request behind it, which is exactly the wedge the watchdog
exists to catch at runtime. Until now "coroutines only block via
``to_thread``" was enforced by review; this rule enforces it statically.

Roots are every ``async def`` in the project. From each root the model
walks plain call edges only — an ``asyncio.to_thread`` / executor-submit
boundary hands the work to a thread and ends event-loop reachability, so
offloaded helpers are clean by construction. Each potentially-blocking
site found on-loop is reported once, at the blocking call itself (that is
where the ``await asyncio.to_thread(…)`` fix or the rationale'd ``# noqa``
belongs).

Deliberately *not* flagged: bounded lock acquisition (``with _LOCK:``
around a dict update is idiomatic and microsecond-bounded — flagging it
would bury the real wedges) and ``asyncio.Queue`` operations (awaited, not
blocking).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterator

from .. import effects as fx
from ..concurrency import model_for
from ..core import Finding

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core import ProjectContext

#: blocking kinds that wedge the loop (LOCK_ACQUIRE deliberately excluded)
_FLAGGED = frozenset(
    {
        fx.SLEEP,
        fx.FILE_IO,
        fx.SOCKET,
        fx.SUBPROCESS,
        fx.QUEUE_OP,
        fx.DEVICE_SYNC,
        fx.THREAD_JOIN,
        fx.FUTURE_RESULT,
        fx.EVENT_WAIT,
    }
)


class AsyncBlockingRule:
    id = "FLX015"
    name = "async-blocking-call"
    description = (
        "blocking call (sleep, file/socket IO, subprocess, queue, device "
        "sync, join/result) reachable from an asyncio coroutine without a "
        "to_thread/executor boundary"
    )
    scope = "project"
    example = (
        "async def _handle_device_loss(self, …):\n"
        "    telemetry.flight_dump(reason='device-lost')  # open()+fsync on "
        "the event loop"
    )
    fix_hint = (
        "offload the blocking call: `await asyncio.to_thread(fn, …)` (or "
        "loop.run_in_executor); if the block is deliberate and bounded, "
        "say why with `# noqa: FLX015`"
    )

    def check_project(self, pctx: "ProjectContext") -> Iterator[Finding]:
        model = model_for(pctx)
        roots = sorted(q for q, eff in model.effects.items() if eff.is_async)
        seen: set[tuple[str, int, int, str]] = set()
        for root in roots:
            on_loop = [root, *sorted(model.reachable_calls(root))]
            for fn in on_loop:
                eff = model.effects.get(fn)
                if eff is None:
                    continue
                if eff.is_async and fn != root:
                    continue  # nested coroutine: awaited, reported as a root
                fi = pctx.index.function(fn)
                if fi is None:
                    continue
                for op in eff.blocking:
                    if op.kind not in _FLAGGED:
                        continue
                    key = (str(fi.path), op.lineno, op.col, op.kind)
                    if key in seen:
                        continue
                    seen.add(key)
                    where = (
                        "directly in the coroutine"
                        if fn == root
                        else f"in `{fn}`, reached without a thread boundary"
                    )
                    yield Finding(
                        path=str(fi.path),
                        line=op.lineno,
                        col=op.col,
                        rule=self.id,
                        message=(
                            f"blocking {op.kind} call (`{op.detail}`) runs on "
                            f"the event loop: {where} from async "
                            f"`{root}` — offload with `await "
                            "asyncio.to_thread(…)`"
                        ),
                    )
