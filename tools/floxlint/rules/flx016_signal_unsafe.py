"""FLX016 — non-reentrant operation reachable from a signal handler.

A Python signal handler runs *between bytecodes of whatever frame happened
to be executing* on the main thread. If that interrupted frame holds a
plain ``threading.Lock`` and the handler (or anything it calls) tries to
acquire the same lock, the process deadlocks — the exact bug class PR 8
fixed by hand when the SIGUSR2 flight-dump handler re-entered the metrics
registry, and the reason the registry/records/export locks are RLocks
today. Queue operations, thread joins, and ``future.result()`` carry the
same hazard through their internal locks.

Roots are every handler registered via ``signal.signal``. The walk follows
plain call edges only: a handler that just spawns a daemon thread
(``profiling.install_capture_signal``'s pattern) is signal-safe by
construction, because the unsafe work happens on the new thread. The
documented dump/flush set — file IO and *reentrant* lock acquisition — is
deliberately exempt: that is precisely what the flight recorder's RLock
design exists to permit from a handler.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterator

from .. import effects as fx
from ..concurrency import model_for
from ..core import Finding

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core import ProjectContext

#: blocking kinds whose internal locks make them handler-unsafe
_UNSAFE_BLOCKING = frozenset(
    {fx.QUEUE_OP, fx.THREAD_JOIN, fx.FUTURE_RESULT, fx.SUBPROCESS, fx.EVENT_WAIT}
)


class SignalUnsafeRule:
    id = "FLX016"
    name = "signal-unsafe-operation"
    description = (
        "signal handler reaches a non-reentrant operation (plain-Lock "
        "acquire, queue op, join/result) that can deadlock against the "
        "interrupted frame"
    )
    scope = "project"
    example = (
        "def _handler(signum, frame):\n"
        "    flush()                 # flush() does `with _LOCK:` — if the\n"
        "                            # interrupted frame holds _LOCK: deadlock"
    )
    fix_hint = (
        "make the lock an RLock (re-entering is then safe), or hand the "
        "work to a daemon thread from the handler "
        "(threading.Thread(target=…, daemon=True).start()) so nothing "
        "non-reentrant runs in the interrupted frame"
    )

    def check_project(self, pctx: "ProjectContext") -> Iterator[Finding]:
        model = model_for(pctx)
        seen: set[tuple[str, int, int]] = set()
        for root in sorted(model.signal_entries):
            for fn in [root, *sorted(model.reachable_calls(root))]:
                eff = model.effects.get(fn)
                fi = pctx.index.function(fn)
                if eff is None or fi is None:
                    continue
                for acq in eff.acquisitions:
                    if acq.kind != fx.LOCK or not acq.blocking:
                        continue
                    key = (str(fi.path), acq.lineno, acq.col)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Finding(
                        path=str(fi.path),
                        line=acq.lineno,
                        col=acq.col,
                        rule=self.id,
                        message=(
                            f"non-reentrant lock `{acq.lock}` is acquired on "
                            f"a path reachable from signal handler `{root}` "
                            "— if the interrupted frame holds it the process "
                            "deadlocks; use an RLock or hand off to a daemon "
                            "thread"
                        ),
                    )
                for op in eff.blocking:
                    if op.kind not in _UNSAFE_BLOCKING:
                        continue
                    key = (str(fi.path), op.lineno, op.col)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Finding(
                        path=str(fi.path),
                        line=op.lineno,
                        col=op.col,
                        rule=self.id,
                        message=(
                            f"{op.kind} operation (`{op.detail}`) is "
                            f"reachable from signal handler `{root}` — its "
                            "internal lock can deadlock against the "
                            "interrupted frame; hand the work to a daemon "
                            "thread"
                        ),
                    )
