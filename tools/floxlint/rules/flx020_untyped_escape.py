"""FLX020 — untyped exception escape from the serve plane.

The serve loop's promise (docs/serving.md): one bad client line must
never take the replica down, and every failure a client sees carries a
machine-readable ``code``. FLX012 checks the *except* side of that
promise file-locally; this rule checks the *raise* side
interprocedurally: a ``raise`` of anything that is not a ``ServeError``
subclass, sitting on a call path from a serve entry point
(``_amain`` / ``Dispatcher._execute``) with no catch frame in between,
escapes as an untyped exception — at best it becomes a generic
``"execution"`` envelope with no retry semantics, at worst it unwinds
the loop.

The analysis runs on the per-domain serve graph built by the contract
compiler: call edges inside the serve package (``self.method`` receivers
resolved, ``asyncio.to_thread``/``create_task`` wrappers unwrapped),
each annotated with the exception names its call site's ``try`` frames
catch. A raise site is flagged only when its exception type can cross
*every* frame back to an entry — so a json-protocol helper whose
``TypeError`` is caught narrowly at its only call site is clean, and so
is anything under a broad ``except Exception`` guard. Unresolvable
exception classes are skipped, never guessed.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterator

from ..core import Finding
from ..contract import cached_serve_graphs

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core import ProjectContext


class UntypedEscapeRule:
    id = "FLX020"
    name = "untyped-serve-escape"
    description = (
        "an untyped (non-ServeError) raise can propagate uncaught to the "
        "serve loop / dispatcher entry"
    )
    scope = "project"
    example = (
        "def _load_slab(path):          # called from Dispatcher._execute\n"
        '    raise ValueError("bad slab header")   # no catch frame between\n'
        "                                          # here and the entry"
    )
    fix_hint = (
        "raise a ServeError subclass with a code (the client can classify\n"
        "it), or catch-and-classify at the boundary:\n"
        "    except Exception as exc:\n"
        "        telemetry.record_serve_error(exc, what=...)\n"
        "        answer(**_error_response(rid, exc))"
    )

    def check_project(self, pctx: "ProjectContext") -> Iterator[Finding]:
        for domain, graph in sorted(cached_serve_graphs(pctx).items()):
            entries = ", ".join(_short(q) for q in graph.entries)
            for site in graph.escapes():
                fn = site.qualname[len(domain) + 1:] or site.qualname
                yield Finding(
                    path=site.path, line=site.line, col=0, rule=self.id,
                    message=(
                        f"untyped {site.exc_name} raised in {fn} can escape "
                        f"uncaught to the serve entry ({entries}) — raise a "
                        "ServeError subclass or add a catch frame on the "
                        "call path"
                    ),
                )


def _short(qualname: str) -> str:
    """``pkg.serve.dispatcher.Dispatcher._execute`` -> ``Dispatcher._execute``,
    ``pkg.serve.__main__._amain`` -> ``_amain``."""
    parts = qualname.split(".")
    if len(parts) >= 2 and parts[-2][:1].isupper():
        return ".".join(parts[-2:])
    return parts[-1]
