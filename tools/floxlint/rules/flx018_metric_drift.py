"""FLX018 — metric-name drift.

A metric name is only real if a producer emits it. Three drift shapes,
all checked against the contract compiler's emit-site table (every
constant name reaching ``METRICS.inc/observe/set_gauge`` or
``telemetry.count``):

* **documented-not-emitted** — a name in the ``docs/serving.md``
  ``<!-- contract:metrics -->`` table that no producer emits: dashboards
  built from the doc chart a flat line forever;
* **seeded-not-emitted** — a gauge listed in a module-level ``*_GAUGES``
  seed tuple (exported as 0 from metrics-server start so scrapes never
  404) with no runtime emit site anywhere: the seed *hides* the missing
  producer behind a permanently-zero series;
* **consumer-unresolved** — a consumer referencing a name nothing emits:
  ``METRICS.get("...")`` / ``METRICS.percentile("...")`` call sites, the
  constants of a shared ``metric_names`` module, and raw
  ``flox_tpu_*`` Prometheus literals (folded back through the exposition
  rename: ``flox_tpu_`` prefix, ``.`` -> ``_``, counters append
  ``_total``). This replaces the old CI grep assertions with resolved
  symbols — a scrape-name typo in the fleet federator becomes a lint
  error, not a silently-empty column.

Anchoring: the rule runs per package that has at least one constant-name
emit site, so tools/ and test trees skip. Label conventions fold:
``name|key=value`` emits register the base name, and consumers of the
base match it.
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING, Iterator

from ..core import Finding
from .common import dotted_name
from ..contract import (
    _emit_site,
    _metric_name_of,
    _seeded_gauge_names,
    cached_contract,
    cell_tokens,
    find_docs_file,
    parse_contract_tables,
)

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core import ProjectContext

_PROM_PREFIX = "flox_tpu_"


class MetricDriftRule:
    id = "FLX018"
    name = "metric-name-drift"
    description = (
        "a metric name is documented, seeded, or consumed that no producer "
        "emits (or a consumer literal fails to resolve against the contract)"
    )
    scope = "project"
    example = (
        'fleet.py reads `flox_tpu_serve_request_total` (typo: the counter\n'
        "renders as flox_tpu_serve_requests_total) — the fleet-top column\n"
        "stays empty on every replica"
    )
    fix_hint = (
        "consume names through the shared flox_tpu.metric_names constants\n"
        "(prom_name() for the Prometheus rendering) so the contract checks\n"
        "them; for seeded gauges, add the runtime set_gauge() producer or\n"
        "drop the name from the seed tuple"
    )

    def check_project(self, pctx: "ProjectContext") -> Iterator[Finding]:
        contract = cached_contract(pctx)
        emitted_by_pkg: dict[str, set[str]] = {}
        for name, entry in contract["metrics"].items():
            for module in entry["modules"]:
                emitted_by_pkg.setdefault(module.partition(".")[0], set()).add(name)
        for pkg in sorted(emitted_by_pkg):
            emitted = emitted_by_pkg[pkg]
            mods = sorted(
                (m for m in pctx.index.modules.values() if m.package == pkg),
                key=lambda m: m.name,
            )
            yield from self._check_docs(pkg, mods, emitted, contract)
            yield from self._check_seeded(mods, contract)
            yield from self._check_consumers(mods, emitted)

    # -- documented-not-emitted --------------------------------------------

    def _check_docs(self, pkg, mods, emitted, contract):
        anchor = next(
            (
                m
                for m in mods
                if any(
                    m.name in contract["metrics"][n]["modules"] for n in emitted
                )
            ),
            None,
        )
        if anchor is None:
            return
        docs = find_docs_file(anchor.path)
        if docs is None:
            return
        try:
            tables = parse_contract_tables(docs.read_text())
        except OSError:
            return
        for row in tables.get("metrics", ()):
            if not row:
                continue
            for token in cell_tokens(next(iter(row.values()))):
                base = token.partition("|")[0]
                if base not in emitted:
                    yield Finding(
                        path=str(anchor.path), line=1, col=0, rule=self.id,
                        message=(
                            f"{docs.name} contract:metrics table documents "
                            f"{token!r} but no producer in package {pkg!r} "
                            "emits it — the documented series is dead"
                        ),
                    )

    # -- seeded-not-emitted -------------------------------------------------

    def _check_seeded(self, mods, contract):
        for mod in mods:
            for name, line in sorted(_seeded_gauge_names(mod).items()):
                entry = contract["metrics"].get(name)
                if entry is None or not entry["modules"]:
                    yield Finding(
                        path=str(mod.path), line=line, col=0, rule=self.id,
                        message=(
                            f"gauge {name!r} is seeded at metrics-server "
                            "start but has no runtime emit site — the seed "
                            "exports a permanently-zero series that hides "
                            "the missing producer"
                        ),
                    )

    # -- consumer-unresolved ------------------------------------------------

    def _check_consumers(self, mods, emitted):
        folded = {_fold(n): n for n in emitted}
        for mod in mods:
            for node in ast.walk(mod.tree):
                yield from self._check_reader_call(mod, node, emitted)
            for literal in _prom_read_literals(mod.tree):
                yield from self._check_prom_literal(mod, literal, folded)
            if mod.name.split(".")[-1] == "metric_names":
                yield from self._check_names_module(mod, emitted)

    def _check_reader_call(self, mod, node, emitted):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "percentile")
            and node.args
        ):
            return
        recv = dotted_name(node.func.value)
        if recv is None or not (recv == "METRICS" or recv.endswith(".METRICS")):
            return
        named = _metric_name_of(node.args[0])
        if named is None:
            return
        base, _labels, _dynamic = named
        if base not in emitted:
            yield Finding(
                path=str(mod.path), line=node.lineno, col=node.col_offset,
                rule=self.id,
                message=(
                    f"METRICS.{node.func.attr}({base!r}) reads a metric no "
                    "producer emits — the consumer will only ever see the "
                    "zero default"
                ),
            )

    def _check_prom_literal(self, mod, node, folded):
        value = node.value
        candidate = value[len(_PROM_PREFIX):].partition("{")[0]
        options = {candidate}
        if candidate.endswith("_total"):
            options.add(candidate[: -len("_total")])
        if not any(opt in folded for opt in options):
            yield Finding(
                path=str(mod.path), line=node.lineno, col=node.col_offset,
                rule=self.id,
                message=(
                    f"Prometheus literal {value!r} folds back to no emitted "
                    "metric — the scrape consumer reads a series no replica "
                    "produces (use flox_tpu.metric_names.prom_name())"
                ),
            )

    def _check_names_module(self, mod, emitted):
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                continue
            name = node.value.value
            base = name.partition("|")[0]
            if base and not base.startswith(_PROM_PREFIX) and base not in emitted:
                yield Finding(
                    path=str(mod.path), line=node.lineno, col=node.col_offset,
                    rule=self.id,
                    message=(
                        f"metric_names constant {name!r} names a metric no "
                        "producer emits — fix the producer or drop the "
                        "constant"
                    ),
                )


def _fold(registry_name: str) -> str:
    """The exposition rename minus prefix/suffix: ``serve.request_ms`` ->
    ``serve_request_ms``."""
    return registry_name.replace(".", "_")


def _prom_read_literals(tree: ast.Module) -> list[ast.Constant]:
    """``flox_tpu_*`` string constants in *read* positions — ``.get(...)``
    arguments, subscript keys, comparison operands (directly or inside a
    tuple key). Literals merely embedded in rendered output (f-strings,
    ``# TYPE`` lines) or naming contextvars are emit/annotation sites, not
    scrape consumers, and are not checked."""

    def prom_constants(node: ast.AST) -> list[ast.Constant]:
        roots = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
        return [
            n
            for n in roots
            if isinstance(n, ast.Constant)
            and isinstance(n.value, str)
            and n.value.startswith(_PROM_PREFIX)
            and len(n.value) > len(_PROM_PREFIX)
            and not n.value.endswith("_")
        ]

    out: list[ast.Constant] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
        ):
            for arg in node.args[:1]:
                out.extend(prom_constants(arg))
        elif isinstance(node, ast.Subscript):
            out.extend(prom_constants(node.slice))
        elif isinstance(node, ast.Compare):
            for operand in [node.left, *node.comparators]:
                out.extend(prom_constants(operand))
    return out
