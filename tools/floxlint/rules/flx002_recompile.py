"""FLX002 — recompile trap: cache keys built from unhashable or
array-content-dependent components.

The package's speed rests on program caches (``core._jitted_bundle``,
``parallel.mapreduce._PROGRAM_CACHE``, ``streaming._STEP_CACHE``) keyed by
hashable, trace-stable tuples. A list/dict in the key raises at runtime; an
ndarray (or an f-string stringifying its contents) silently gives every call
a fresh key — one full XLA recompile per call. Static metadata
(``x.dtype`` / ``x.shape`` / ``x.ndim``) is fine; array *contents* are not
(hash by ``arr.tobytes()`` when content-keying is really wanted)."""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..core import FileContext, Finding
from .common import ImportMap, assigned_names, dotted_name

_KEY_NAME_RE = re.compile(r"(^|_)key$|(^|_)key(_|$)", re.IGNORECASE)
_CACHE_NAME_RE = re.compile(r"cache", re.IGNORECASE)
#: attribute reads of an array that are static metadata, not contents
_STATIC_ATTRS = frozenset({"dtype", "shape", "ndim", "size", "itemsize", "name"})
_ARRAY_CALL_PREFIXES = (
    "numpy.array",
    "numpy.asarray",
    "numpy.ascontiguousarray",
    "numpy.arange",
    "numpy.zeros",
    "numpy.ones",
    "numpy.full",
    "numpy.empty",
    "numpy.concatenate",
    "jax.numpy",
    "jax.device_put",
)


def _collect_array_names(tree: ast.AST, imports: ImportMap) -> set[str]:
    """Names assigned (anywhere in the module) from array constructors."""
    names: set[str] = set()
    for _ in range(2):
        before = len(names)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            is_array = isinstance(value, ast.Call) and imports.resolves_to(
                value.func, *_ARRAY_CALL_PREFIXES
            )
            if not is_array and isinstance(value, ast.Name) and value.id in names:
                is_array = True
            if is_array:
                for t in node.targets:
                    names.update(assigned_names(t))
        if len(names) == before:
            break
    return names


class RecompileTrapRule:
    id = "FLX002"
    name = "recompile-trap"
    description = (
        "unhashable (list/dict/set/ndarray) or array-content-derived values "
        "in a jit/program cache key cause runtime errors or per-call recompiles"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        imports = ImportMap.from_tree(ctx.tree)
        array_names = _collect_array_names(ctx.tree, imports)
        for key_expr in self._key_expressions(ctx.tree):
            yield from self._check_key_expr(ctx, key_expr, array_names)

    # -- key-context discovery ---------------------------------------------

    def _key_expressions(self, tree: ast.AST) -> Iterator[ast.AST]:
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                # a key-named assignment counts as cache-key context only when
                # the RHS is tuple- or string-shaped — device values are also
                # commonly named `key` (sort keys, radix keys)
                if isinstance(node.value, (ast.Tuple, ast.JoinedStr)) and any(
                    _KEY_NAME_RE.search(n) for t in node.targets for n in assigned_names(t)
                ):
                    yield node.value
            elif isinstance(node, ast.Subscript):
                base = dotted_name(node.value)
                if base and _CACHE_NAME_RE.search(base.split(".")[-1]):
                    yield node.slice
            elif isinstance(node, ast.Call):
                func = dotted_name(node.func)
                if func is None:
                    continue
                tail = func.split(".")[-1]
                # cache.get(key, ...) / _step_cached((key...), build)
                if tail in ("get", "setdefault", "pop") and _CACHE_NAME_RE.search(func):
                    if node.args:
                        yield node.args[0]
                elif _CACHE_NAME_RE.search(tail) and node.args:
                    yield node.args[0]

    # -- component checks ---------------------------------------------------

    def _check_key_expr(
        self, ctx: FileContext, expr: ast.AST, array_names: set[str]
    ) -> Iterator[Finding]:
        components = expr.elts if isinstance(expr, ast.Tuple) else [expr]
        for comp in components:
            if isinstance(comp, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp, ast.DictComp)):
                yield Finding(
                    path=ctx.display_path,
                    line=comp.lineno,
                    col=comp.col_offset,
                    rule=self.id,
                    message=(
                        "unhashable container in a cache key — jit static args "
                        "and cache keys must be hashable (use a tuple)"
                    ),
                )
            elif isinstance(comp, ast.Name) and comp.id in array_names:
                yield Finding(
                    path=ctx.display_path,
                    line=comp.lineno,
                    col=comp.col_offset,
                    rule=self.id,
                    message=(
                        f"array `{comp.id}` used directly in a cache key — "
                        "ndarrays are unhashable and their identity is not "
                        "trace-stable; key on static metadata (shape/dtype) or "
                        f"`{comp.id}.tobytes()` if contents must key the cache"
                    ),
                )
            elif isinstance(comp, ast.JoinedStr):
                yield from self._check_fstring(ctx, comp, array_names)

    def _check_fstring(
        self, ctx: FileContext, node: ast.JoinedStr, array_names: set[str]
    ) -> Iterator[Finding]:
        for part in node.values:
            if not isinstance(part, ast.FormattedValue):
                continue
            # names reached through static metadata (x.dtype, x.shape[0],
            # x.ndim, ...) are trace-stable and fine, at any nesting depth
            static_names = {
                sub2.id
                for sub in ast.walk(part.value)
                if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS
                for sub2 in ast.walk(sub.value)
                if isinstance(sub2, ast.Name)
            }
            bad = next(
                (
                    sub.id
                    for sub in ast.walk(part.value)
                    if isinstance(sub, ast.Name)
                    and sub.id in array_names
                    and sub.id not in static_names
                ),
                None,
            )
            if bad is not None:
                yield Finding(
                    path=ctx.display_path,
                    line=node.lineno,
                    col=node.col_offset,
                    rule=self.id,
                    message=(
                        f"f-string cache key stringifies array `{bad}` — that "
                        "syncs the device AND gives every distinct content a "
                        "fresh compile; key on static metadata instead"
                    ),
                )
                return
