"""FLX013 — unlocked shared-mutable-state write on a thread-reachable path.

The serve/fleet plane is threaded: daemon samplers, scrape threads,
``asyncio.to_thread`` workers, executor submits, signal handlers. Any
module-level mutable object those paths write races with every other
writer unless they agree on a lock. This rule makes the agreement
checkable: for each module-level mutable container (FLX008's detection,
without the cache-name restriction) it collects every write site with the
lock set held there — locally (``with`` nesting, ``acquire``/``release``)
*plus* the locks held on every resolved call path into the function (so a
helper whose callers all hold the registry lock counts as protected). If
the writers of an object have settled on one lock and a write site that is
reachable from a thread entry point (``Thread(target=…)``, ``Timer``,
``executor.submit``, ``asyncio.to_thread``, ``loop.run_in_executor``) or a
signal handler skips it, that site is flagged.

Precision choices: single-writer objects are exempt (no cross-thread
disagreement to have), objects none of whose writers hold any lock are
exempt (event-loop- or main-thread-confined state — the dispatcher
registries — is a design, not an accident), a tie between two
candidate locks skips the object rather than guessing, and the candidate
lock must be held at a strict majority of write sites (a lock one caller
happens to hold around a single write is that caller's context, not the
object's discipline). The fix is either
to take the lock or to confine the write to one thread and say so with a
rationale'd ``# noqa: FLX013``.
"""

from __future__ import annotations

from collections import Counter
from typing import TYPE_CHECKING, Iterator

from ..concurrency import model_for
from ..core import Finding
from .. import effects as fx

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..core import ProjectContext


class UnlockedSharedWriteRule:
    id = "FLX013"
    name = "unlocked-shared-write"
    description = (
        "module-level mutable state written on a thread-reachable path "
        "without the lock its other writers hold"
    )
    scope = "project"
    example = (
        "_STATE_LOCK = threading.Lock()\n"
        "def set_ready(flag):\n"
        "    _STATE['ready'] = flag          # written lock-free…\n"
        "def stop():\n"
        "    with _STATE_LOCK:\n"
        "        _STATE['ready'] = False     # …while other writers lock\n"
        "threading.Thread(target=set_ready, args=(True,)).start()"
    )
    fix_hint = (
        "take the same lock the other writers hold (with _STATE_LOCK: …), or "
        "confine all writes to one thread and mark the deliberate exception "
        "with a rationale'd `# noqa: FLX013`"
    )

    def check_project(self, pctx: "ProjectContext") -> Iterator[Finding]:
        model = model_for(pctx)
        concurrent = model.thread_reachable | model.signal_reachable
        # obj -> [(qualname, WriteSite, effective held set)]
        by_obj: dict[str, list[tuple[str, fx.WriteSite, frozenset[str]]]] = {}
        for q, eff in model.effects.items():
            entry_held = model.held_at_entry.get(q, frozenset())
            for w in eff.writes:
                effective = frozenset(w.held) | entry_held
                by_obj.setdefault(w.obj, []).append((q, w, effective))
        for obj in sorted(by_obj):
            sites = by_obj[obj]
            writer_fns = {q for q, _, _ in sites}
            if len(writer_fns) < 2:
                continue  # single-writer objects cannot disagree
            counts: Counter[str] = Counter(
                lock for _, _, held in sites for lock in held
            )
            if not counts:
                continue  # nobody locks: confined-by-design state
            ranked = counts.most_common(2)
            if len(ranked) > 1 and ranked[0][1] == ranked[1][1]:
                continue  # ambiguous discipline — no lock to demand
            protect = ranked[0][0]
            if ranked[0][1] * 2 <= len(sites):
                # the candidate lock is held at a minority of write sites:
                # that is one caller's incidental context (a recovery guard
                # held around a cache clear), not the object's discipline
                continue
            holders = sorted(
                {q for q, _, held in sites if protect in held}
            )
            for q, w, held in sites:
                if protect in held or q not in concurrent:
                    continue
                if holders == [q]:
                    continue  # the only holder is this same function
                fi = pctx.index.function(q)
                if fi is None:
                    continue
                via = model.spawn_kind.get(q)
                how = (
                    f"reachable from a {via} entry point"
                    if via
                    else "reachable from a thread entry point"
                )
                yield Finding(
                    path=str(fi.path),
                    line=w.lineno,
                    col=w.col,
                    rule=self.id,
                    message=(
                        f"`{obj}` is written here without `{protect}`, which "
                        f"its other writer(s) ({', '.join(holders)}) hold; "
                        f"`{q}` is {how} — take the lock, or confine writes "
                        "to one thread and suppress with a rationale"
                    ),
                )
