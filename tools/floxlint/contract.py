"""Static contract compiler (floxlint v4).

The system's external surface — serve-protocol ops, the typed
``ServeError`` hierarchy, the HTTP endpoints, every metric name the
telemetry registry can emit, and the OPTIONS knob table — used to live
only in hand-written docs tables and brittle CI greps. This module
factorizes that contract ONCE, from the AST (the flox move applied to
static analysis), into a versioned, schema-validated, deterministic
``contract.json`` that every consumer reduces over: the FLX017–FLX020
drift rules, the docs tables in ``docs/serving.md``, the runtime
conformance harness (``tests/test_contract.py``), and — per ROADMAP
item 1 — the future fleet router's client stub.

Extraction anchors (all pure AST, nothing is imported):

* **ops** — a *protocol module* is any module defining a top-level
  ``_REQUEST_FIELDS`` set of strings. Its op-dispatch chain
  (``op == "stats"`` / ``op in ("append", ...)`` comparisons on a value
  read via ``.get("op")``) yields one op per comparison; the inline
  aggregation path is the implicit ``reduce`` op. Per op we record the
  ``msg.get("...")`` request fields and the string keys of every response
  dict literal in the branch (the *envelope* fields — spread payloads
  like ``**info`` add dynamic keys on top, which is why conformance
  checks ``envelope ⊆ observed``, never equality).
* **errors** — every class whose base chain reaches a class named
  ``ServeError`` and that sets a string ``code`` class attribute; plus
  *synthesized* codes (``"code": "protocol"`` string literals in
  protocol-module response dicts that match no class). Constructor call
  sites tell us whether a code ever carries ``retry_after_ms`` /
  ``program``; the serve call graph tells us which functions raise it.
* **endpoints** — every ``do_GET`` handler's ``path == "/x"`` chain, with
  query params (``params.get("...")``) and status codes (integer
  constants in 100–599) collected from the branch and, transitively,
  the same-module helpers it calls.
* **metrics** — every name reachable through ``METRICS.inc`` /
  ``METRICS.observe`` / ``METRICS.set_gauge`` / ``telemetry.count`` call
  sites, including the ``name|key=value`` label convention (f-string
  prefixes resolve to the base name + label keys). Module-level
  ``*_GAUGES`` string tuples mark seeded-at-start gauges.
* **knobs** — the FLX010 triangle, machine-readable: every ``OPTIONS``
  field with its ``FLOX_TPU_*`` env mirror and ``_VALIDATORS`` presence.

The serve-escape graph (:func:`build_serve_graph`) is shared with FLX020:
call edges inside the serve package, with ``self.method`` receivers,
``asyncio.to_thread/create_task/ensure_future`` wrappers unwrapped, and
each edge annotated *contained* when the call site sits inside a ``try``
whose handlers catch broadly — the lexical boundary an untyped exception
cannot cross.
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator, Sequence

from .rules.common import dotted_name

CONTRACT_VERSION = 1

#: call wrappers whose first argument is the real callee (the serve plane
#: runs every disk/CPU-bound path off the loop through these)
_ASYNC_WRAPPERS = frozenset(
    {"asyncio.to_thread", "asyncio.create_task", "asyncio.ensure_future"}
)

_BROAD_EXC = frozenset({"Exception", "BaseException"})

#: Python builtins that mark a raise site as untyped for FLX020 (anything
#: unresolvable is skipped — conservatively, never guessed)
_BUILTIN_EXCEPTIONS = frozenset(
    {
        "Exception", "BaseException", "ValueError", "TypeError", "KeyError",
        "IndexError", "RuntimeError", "OSError", "IOError", "LookupError",
        "AttributeError", "NotImplementedError", "AssertionError",
        "ArithmeticError", "ZeroDivisionError", "OverflowError",
        "FileNotFoundError", "PermissionError", "StopIteration",
        "StopAsyncIteration", "MemoryError", "EOFError",
    }
)


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------


def _str_consts(node: ast.AST) -> list[str]:
    return [
        n.value
        for n in ast.walk(node)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    ]


def _own_statements(fn: ast.AST) -> Iterator[ast.AST]:
    """Every node of a function body, excluding nested function bodies
    (those are their own graph nodes)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _metric_name_of(arg: ast.AST) -> tuple[str, list[str], bool] | None:
    """(base name, label keys, dynamic) for a metric-name argument.

    A plain string splits on the ``|key=value`` convention; an f-string
    resolves to its leading literal prefix (``f"serve.request_ms|tenant=
    {label}"`` -> base ``serve.request_ms``, labels ``["tenant"]``).
    None when no leading literal exists (a fully dynamic name).
    """
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        raw, dynamic = arg.value, False
    elif isinstance(arg, ast.JoinedStr):
        prefix = ""
        for part in arg.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                prefix += part.value
            else:
                break
        if not prefix:
            return None
        raw, dynamic = prefix, True
    else:
        return None
    base, _, labelpart = raw.partition("|")
    labels = []
    if labelpart:
        key = labelpart.partition("=")[0].strip()
        if key:
            labels.append(key)
    base = base.strip()
    if not base or base.endswith("."):
        # a dynamic name with only a family prefix ("store.") is not a
        # contract entry — record the site as dynamic instead
        return None
    return base, labels, dynamic


# ---------------------------------------------------------------------------
# ops (serve protocol modules)
# ---------------------------------------------------------------------------


def request_fields(mod) -> list[str] | None:
    """The ``_REQUEST_FIELDS`` string set of a protocol module, or None."""
    node = mod.definitions.get("_REQUEST_FIELDS")
    if node is None or not isinstance(node, (ast.Assign, ast.AnnAssign)):
        return None
    value = node.value
    if value is None:
        return None
    names = [
        c.value
        for n in ast.walk(value)
        if isinstance(n, (ast.Set, ast.Tuple, ast.List))
        for c in n.elts
        if isinstance(c, ast.Constant) and isinstance(c.value, str)
    ]
    return sorted(set(names)) if names else None


def protocol_modules(index) -> list:
    return sorted(
        (m for m in index.modules.values() if request_fields(m) is not None),
        key=lambda m: m.name,
    )


def _op_dispatch_branches(mod) -> list[tuple[str, ast.If, list[ast.stmt]]]:
    """(op name, If node, branch body) per op comparison in the module's
    dispatch chain — ``op == "stats"`` and ``op in ("append", ...)``
    forms, where the compared name was read via ``.get("op")``."""
    op_vars: set[str] = set()
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Attribute)
            and node.value.func.attr == "get"
            and node.value.args
            and isinstance(node.value.args[0], ast.Constant)
            and node.value.args[0].value == "op"
        ):
            op_vars.update(
                t.id for t in node.targets if isinstance(t, ast.Name)
            )
    if not op_vars:
        return []
    out: list[tuple[str, ast.If, list[ast.stmt]]] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.If):
            continue
        test = node.test
        if not (
            isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and test.left.id in op_vars
            and len(test.ops) == 1
        ):
            continue
        comp = test.comparators[0]
        if isinstance(test.ops[0], ast.Eq) and isinstance(comp, ast.Constant):
            if isinstance(comp.value, str):
                out.append((comp.value, node, node.body))
        elif isinstance(test.ops[0], ast.In) and isinstance(
            comp, (ast.Tuple, ast.List, ast.Set)
        ):
            for elt in comp.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    out.append((elt.value, node, node.body))
    return out


def _dict_keys_in(nodes: Sequence[ast.AST]) -> tuple[set[str], bool]:
    """(string keys of every dict literal / string-subscript assignment,
    whether a ``**_error_response(...)`` style spread is present)."""
    keys: set[str] = set()
    spreads_error_response = False
    for root in nodes:
        for node in ast.walk(root):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        keys.add(k.value)
                    elif k is None:  # **spread
                        called = (
                            dotted_name(v.func) if isinstance(v, ast.Call) else None
                        )
                        if called and called.split(".")[-1] == "_error_response":
                            spreads_error_response = True
            elif (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)
                and isinstance(node.targets[0].slice, ast.Constant)
                and isinstance(node.targets[0].slice.value, str)
            ):
                keys.add(node.targets[0].slice.value)
    return keys, spreads_error_response


def _calls_function(nodes: Sequence[ast.AST], name: str) -> bool:
    for root in nodes:
        for node in ast.walk(root):
            if isinstance(node, ast.Call):
                called = dotted_name(node.func)
                if called and called.split(".")[-1] == name:
                    return True
    return False


def _msg_get_keys(nodes: Sequence[ast.AST]) -> set[str]:
    keys: set[str] = set()
    for root in nodes:
        for node in ast.walk(root):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                keys.add(node.args[0].value)
    return keys - {"op"}


def _error_response_keys(mod) -> set[str]:
    """Keys of the shared typed-error envelope helper, when the protocol
    module defines one (``_error_response``)."""
    fn = mod.definitions.get("_error_response")
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        keys, _ = _dict_keys_in([fn])
        return keys
    return set()


def _extract_ops(index, graphs: dict) -> dict:
    ops: dict[str, dict] = {}
    for mod in protocol_modules(index):
        graph = graphs.get(serve_domain_prefix(mod.name))
        err_keys = _error_response_keys(mod)
        for op, node, body in _op_dispatch_branches(mod):
            keys, spreads = _dict_keys_in(body)
            if spreads or _calls_function(body, "_error_response"):
                keys |= err_keys
            codes = _branch_error_codes(index, mod, body, graph)
            entry = {
                "module": mod.name,
                "line": node.lineno,
                "request_fields": sorted(_msg_get_keys(body) | {"op"}),
                "response_fields": sorted(keys),
                "error_codes": sorted(codes),
            }
            if op in ops:  # first definition wins; duplicates merge fields
                prev = ops[op]
                prev["request_fields"] = sorted(
                    set(prev["request_fields"]) | set(entry["request_fields"])
                )
                prev["response_fields"] = sorted(
                    set(prev["response_fields"]) | set(entry["response_fields"])
                )
                prev["error_codes"] = sorted(
                    set(prev["error_codes"]) | set(entry["error_codes"])
                )
            else:
                ops[op] = entry
        # the inline aggregation path: every request line without an "op"
        fields = request_fields(mod) or []
        reduce_fns = [
            fi
            for fi in mod.functions.values()
            if any(
                isinstance(n, ast.Name) and n.id == "_REQUEST_FIELDS"
                for n in ast.walk(fi.node)
            )
        ]
        keys: set[str] = set()
        codes: set[str] = set()
        for fi in reduce_fns:
            fkeys, spreads = _dict_keys_in([fi.node])
            keys |= fkeys
            if spreads or _calls_function([fi.node], "_error_response"):
                keys |= err_keys
            codes |= _branch_error_codes(index, mod, [fi.node], graph)
        if fields and "reduce" not in ops:
            ops["reduce"] = {
                "module": mod.name,
                "line": 1,
                "request_fields": sorted(set(fields) | {"id"}),
                "response_fields": sorted(keys),
                "error_codes": sorted(codes),
            }
    return {k: ops[k] for k in sorted(ops)}


def _literal_codes(nodes: Sequence[ast.AST]) -> dict[str, int]:
    """code -> line for every literal ``"code": "<x>"`` dict entry or
    ``out["code"] = "<x>"`` subscript assignment."""
    codes: dict[str, int] = {}
    for root in nodes:
        for node in ast.walk(root):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (
                        isinstance(k, ast.Constant)
                        and k.value == "code"
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                    ):
                        codes.setdefault(v.value, v.lineno)
            elif (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)
                and isinstance(node.targets[0].slice, ast.Constant)
                and node.targets[0].slice.value == "code"
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                codes.setdefault(node.value.value, node.value.lineno)
    return codes


def _branch_error_codes(index, mod, body, graph) -> set[str]:
    """Codes a branch can answer: literal ``"code": "<x>"`` emits plus every
    typed raise reachable through the serve graph from the branch's calls."""
    codes: set[str] = set(_literal_codes(body))
    if graph is None:
        return codes
    seeds: set[str] = set()
    for root in body:
        for node in ast.walk(root):
            if isinstance(node, ast.Call):
                target = graph.resolve_call(mod, node)
                if target is not None:
                    seeds.add(target)
    reachable = graph.reachable_from(seeds)
    for qual in reachable | seeds:
        for site in graph.raises.get(qual, ()):
            if site.code is not None:
                codes.add(site.code)
    return codes


# ---------------------------------------------------------------------------
# errors (the typed ServeError hierarchy + synthesized codes)
# ---------------------------------------------------------------------------


def _class_defs(index) -> dict[str, tuple[ast.ClassDef, object]]:
    """qualname -> (ClassDef, module) for every class at any nesting."""
    out: dict[str, tuple[ast.ClassDef, object]] = {}
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                out[f"{mod.name}.{node.name}"] = (node, mod)
    return out


def serve_error_classes(index) -> dict[str, tuple[ast.ClassDef, object]]:
    """qualname -> (node, module) for every class deriving (transitively)
    from a class named ``ServeError`` — the base itself included."""
    classes = _class_defs(index)
    derived: dict[str, tuple[ast.ClassDef, object]] = {
        q: v for q, v in classes.items() if q.split(".")[-1] == "ServeError"
    }
    changed = True
    while changed:
        changed = False
        for qual, (node, mod) in classes.items():
            if qual in derived:
                continue
            for base in node.bases:
                base_name = dotted_name(base)
                if base_name is None:
                    continue
                leaf = base_name.split(".")[-1]
                resolved = index.resolve_symbol(mod.name, base_name)
                if leaf == "ServeError" or (
                    resolved is not None and resolved in derived
                ):
                    derived[qual] = (node, mod)
                    changed = True
                    break
    return derived


def _class_code(node: ast.ClassDef) -> str | None:
    for stmt in node.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if (
                    isinstance(t, ast.Name)
                    and t.id == "code"
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)
                ):
                    return stmt.value.value
    return None


def _constructor_kwargs(index, class_name: str) -> set[str]:
    kwargs: set[str] = set()
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                called = dotted_name(node.func)
                if called and called.split(".")[-1] == class_name:
                    kwargs.update(k.arg for k in node.keywords if k.arg)
    return kwargs


def _extract_errors(index, graphs: dict) -> dict:
    errors: dict[str, dict] = {}
    raised_in: dict[str, set[str]] = {}
    for graph in graphs.values():
        for qual, sites in graph.raises.items():
            for site in sites:
                if site.code is not None:
                    raised_in.setdefault(site.code, set()).add(qual)
    for qual, (node, mod) in sorted(serve_error_classes(index).items()):
        name = qual.split(".")[-1]
        if name == "ServeError":
            continue  # the abstract base's "serve_error" never goes on the wire
        code = _class_code(node)
        if code is None:
            continue
        kwargs = _constructor_kwargs(index, name)
        errors[code] = {
            "class": name,
            "module": mod.name,
            "line": node.lineno,
            "retry_after_ms": "retry_after_ms" in kwargs,
            "program": "program" in kwargs,
            "raised_in": sorted(raised_in.get(code, ())),
        }
    # synthesized codes: literal "code" values the protocol layer attaches
    # without a class (protocol / execution / busy ...)
    for mod in protocol_modules(index):
        for code, line in sorted(_literal_codes([mod.tree]).items()):
            if code not in errors:
                errors[code] = {
                    "class": None,
                    "module": mod.name,
                    "line": line,
                    "retry_after_ms": False,
                    "program": False,
                    "raised_in": [],
                }
    return {k: errors[k] for k in sorted(errors)}


# ---------------------------------------------------------------------------
# endpoints (every do_GET path chain)
# ---------------------------------------------------------------------------


def _fn_param_keys(fn: ast.AST) -> set[str]:
    keys: set[str] = set()
    for node in _own_statements(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "params"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            keys.add(node.args[0].value)
    return keys


def _fn_statuses(fn: ast.AST) -> set[int]:
    return {
        n.value
        for n in _own_statements(fn)
        if isinstance(n, ast.Constant)
        and isinstance(n.value, int)
        and not isinstance(n.value, bool)
        and 100 <= n.value <= 599
    }


def _fn_called_names(fn: ast.AST) -> set[str]:
    names: set[str] = set()
    for node in _own_statements(fn):
        if isinstance(node, ast.Call):
            called = dotted_name(node.func)
            if called:
                names.add(called.split(".")[-1])
    return names


def _extract_endpoints(index) -> dict:
    endpoints: dict[str, dict] = {}
    for mod in sorted(index.modules.values(), key=lambda m: m.name):
        handlers = [
            n
            for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name == "do_GET"
        ]
        if not handlers:
            continue
        # per-function fact tables for the whole module: branch facts union
        # transitively over same-module helpers (self._costs -> _parse_top)
        fns: dict[str, ast.AST] = {}
        for n in ast.walk(mod.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.setdefault(n.name, n)

        def closure(names: set[str]) -> set[str]:
            seen: set[str] = set()
            frontier = {n for n in names if n in fns}
            while frontier:
                name = frontier.pop()
                if name in seen:
                    continue
                seen.add(name)
                frontier |= {
                    n for n in _fn_called_names(fns[name]) if n in fns
                } - seen
            return seen

        mod_paths: dict[str, dict] = {}
        for handler in handlers:
            for node in ast.walk(handler):
                if not isinstance(node, ast.If):
                    continue
                test = node.test
                if not (
                    isinstance(test, ast.Compare)
                    and len(test.ops) == 1
                    and isinstance(test.ops[0], ast.Eq)
                    and isinstance(test.comparators[0], ast.Constant)
                    and isinstance(test.comparators[0].value, str)
                    and test.comparators[0].value.startswith("/")
                ):
                    continue
                path = test.comparators[0].value
                branch = ast.Module(body=node.body, type_ignores=[])
                params = _fn_param_keys(branch)
                statuses = _fn_statuses(branch)
                for helper in closure(_fn_called_names(branch)):
                    params |= _fn_param_keys(fns[helper])
                    statuses |= _fn_statuses(fns[helper])
                mod_paths[path] = {
                    "line": node.lineno,
                    "query_params": sorted(params),
                    "statuses": sorted(statuses),
                }
        if mod_paths:
            endpoints[mod.name] = {k: mod_paths[k] for k in sorted(mod_paths)}
    return endpoints


# ---------------------------------------------------------------------------
# metrics (every registry emit site)
# ---------------------------------------------------------------------------

_EMIT_KINDS = {"inc": "counter", "observe": "histogram", "set_gauge": "gauge"}


def _emit_site(node: ast.Call) -> str | None:
    """The metric kind when this call is a registry emit, else None."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    recv = dotted_name(func.value)
    if func.attr in _EMIT_KINDS and recv is not None and (
        recv == "METRICS" or recv.endswith(".METRICS") or recv == "self._metrics"
    ):
        return _EMIT_KINDS[func.attr]
    if func.attr == "count" and recv is not None and (
        recv == "telemetry" or recv.endswith(".telemetry")
    ):
        return "counter"
    return None


def _seeded_gauge_names(mod) -> dict[str, int]:
    """name -> line for every entry of a module-level ``*_GAUGES`` tuple."""
    out: dict[str, int] = {}
    for node in mod.tree.body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (
            node.targets
            if isinstance(node, ast.Assign)
            else [node.target]
        )
        if not any(
            isinstance(t, ast.Name) and t.id.endswith("_GAUGES") for t in targets
        ):
            continue
        value = node.value
        if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    out[elt.value] = elt.lineno
    return out


def _extract_metrics(index) -> tuple[dict, list]:
    metrics: dict[str, dict] = {}
    dynamic_sites: list[dict] = []
    for mod in sorted(index.modules.values(), key=lambda m: m.name):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _emit_site(node)
            if kind is None or not node.args:
                continue
            named = _metric_name_of(node.args[0])
            if named is None:
                dynamic_sites.append({"module": mod.name, "line": node.lineno})
                continue
            base, labels, _dynamic = named
            entry = metrics.setdefault(
                base, {"kinds": [], "labels": [], "modules": [], "seeded": False}
            )
            if kind not in entry["kinds"]:
                entry["kinds"].append(kind)
            for label in labels:
                if label not in entry["labels"]:
                    entry["labels"].append(label)
            if mod.name not in entry["modules"]:
                entry["modules"].append(mod.name)
        for name in _seeded_gauge_names(mod):
            entry = metrics.setdefault(
                name, {"kinds": [], "labels": [], "modules": [], "seeded": False}
            )
            entry["seeded"] = True
            if "gauge" not in entry["kinds"]:
                entry["kinds"].append("gauge")
    for entry in metrics.values():
        entry["kinds"].sort()
        entry["labels"].sort()
        entry["modules"].sort()
    dynamic_sites.sort(key=lambda d: (d["module"], d["line"]))
    return {k: metrics[k] for k in sorted(metrics)}, dynamic_sites


# ---------------------------------------------------------------------------
# knobs (the FLX010 triangle, machine-readable)
# ---------------------------------------------------------------------------


def _extract_knobs(index) -> dict:
    from .rules.flx010_options_drift import _toplevel_dict

    knobs: dict[str, dict] = {}
    for mod in sorted(index.modules.values(), key=lambda m: m.name):
        options = _toplevel_dict(mod.tree, "OPTIONS")
        validators = _toplevel_dict(mod.tree, "_VALIDATORS")
        if options is None or validators is None:
            continue
        validated = {
            k.value
            for k in validators.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        }
        for key, value in zip(options.keys, options.values):
            if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
                continue
            env = next(
                (s for s in _str_consts(value) if s.startswith("FLOX_TPU_")), None
            )
            knobs[key.value] = {
                "module": mod.name,
                "line": key.lineno,
                "env": env,
                "validated": key.value in validated,
            }
    return {k: knobs[k] for k in sorted(knobs)}


# ---------------------------------------------------------------------------
# the serve-escape graph (shared with FLX020)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RaiseSite:
    """One ``raise X(...)`` statement inside a serve-package function."""

    qualname: str  #: the raising function
    path: str
    line: int
    exc_name: str  #: last component of the raised class name
    code: str | None  #: the ServeError code when typed, else None
    contained: bool  #: lexically inside a try whose handlers catch this type
    typed: bool  #: raises a ServeError subclass
    builtin: bool  #: raises a Python builtin exception


@dataclass
class ServeGraph:
    """Call edges + raise sites over one serve package.

    Each edge carries the exception names its call site's enclosing
    ``try`` frames catch (``"*"`` for bare / ``Exception`` /
    ``BaseException``): an exception of a caught type cannot propagate
    across that edge, so escape traversal stops there — which is how a
    json-protocol helper whose TypeError is caught narrowly at its only
    call site stays clean.
    """

    index: object
    domain: str
    #: caller -> [(callee, names caught around the call site)]
    edges: dict[str, list[tuple[str, frozenset[str]]]] = field(
        default_factory=dict
    )
    raises: dict[str, list[RaiseSite]] = field(default_factory=dict)
    entries: list[str] = field(default_factory=list)
    error_codes: dict[str, str] = field(default_factory=dict)  #: class -> code
    _class_lower: dict[str, str] = field(default_factory=dict)

    def resolve_call(self, mod, node: ast.Call) -> str | None:
        """Canonical qualname of a call's target inside the domain, or
        None. Unwraps ``asyncio.to_thread(fn, ...)`` style wrappers,
        resolves ``self.method`` against the enclosing class, and matches
        ``dispatcher.submit`` style receiver-named-after-class calls."""
        called = dotted_name(node.func)
        if called in _ASYNC_WRAPPERS and node.args:
            inner = node.args[0]
            target = inner.func if isinstance(inner, ast.Call) else inner
            called = dotted_name(target)
        if called is None:
            return None
        head, _, rest = called.partition(".")
        if head == "self" and rest:
            return None  # handled by the caller, which knows its class
        resolved = self.index.resolve_symbol(mod.name, called)
        if resolved is not None and self._in_domain(resolved):
            if self.index.function(resolved) is not None:
                return resolved
        # receiver named after a domain class: dispatcher.submit ->
        # <module>.Dispatcher.submit
        if rest and "." not in rest:
            cls = self._class_lower.get(head)
            if cls is not None:
                candidate = f"{cls}.{rest}"
                if self.index.function(candidate) is not None:
                    return candidate
        return None

    def _in_domain(self, qualname: str) -> bool:
        return qualname == self.domain or qualname.startswith(self.domain + ".")

    def reachable_from(self, seeds: set[str]) -> set[str]:
        """Every function reachable from ``seeds`` over all edges (the
        which-ops-can-answer-which-codes attribution — a caught ServeError
        still becomes an error response, so catch frames don't stop it)."""
        seen: set[str] = set()
        frontier = list(seeds)
        while frontier:
            qual = frontier.pop()
            for callee, _caught in self.edges.get(qual, ()):
                if callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        return seen

    def _reachable_passing(self, exc_name: str) -> set[str]:
        """Functions reachable from the entries over edges whose catch
        frames would NOT stop ``exc_name`` on its way back up."""
        seen = set(self.entries)
        frontier = list(self.entries)
        while frontier:
            qual = frontier.pop()
            for callee, caught in self.edges.get(qual, ()):
                if "*" in caught or exc_name in caught:
                    continue
                if callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        return seen

    def escapes(self) -> list[RaiseSite]:
        """FLX020's answer: raise sites of non-ServeError exceptions that
        can propagate all the way to a serve entry — not caught around the
        raise itself, and reachable over edges that don't catch the type."""
        candidates: dict[str, list[RaiseSite]] = {}
        for qual, sites in self.raises.items():
            for site in sites:
                if site.contained or site.typed:
                    continue
                candidates.setdefault(site.exc_name, []).append(site)
        out = []
        for exc_name, sites in candidates.items():
            reachable = self._reachable_passing(exc_name)
            out.extend(s for s in sites if s.qualname in reachable)
        out.sort(key=lambda s: (s.path, s.line))
        return out


def serve_domain_prefix(module_name: str) -> str:
    """The package prefix escape analysis stays inside — up to and
    including the ``serve`` component when one exists."""
    parts = module_name.split(".")
    if "serve" in parts:
        return ".".join(parts[: parts.index("serve") + 1])
    return parts[0]


def serve_domains(index) -> list[str]:
    """Every domain carrying a serve entry — protocol modules and
    ``Dispatcher._execute`` methods each anchor one."""
    domains = {serve_domain_prefix(m.name) for m in protocol_modules(index)}
    for mod in index.modules.values():
        for fi in mod.functions.values():
            if fi.qualname.endswith(".Dispatcher._execute"):
                domains.add(serve_domain_prefix(mod.name))
    return sorted(domains)


def build_serve_graphs(index) -> dict[str, "ServeGraph"]:
    return {d: build_serve_graph(index, d) for d in serve_domains(index)}


def build_serve_graph(index, domain: str) -> ServeGraph:
    graph = ServeGraph(index=index, domain=domain)
    error_classes = serve_error_classes(index)
    typed_names = {q.split(".")[-1] for q in error_classes}
    for qual, (node, _mod) in error_classes.items():
        code = _class_code(node)
        if code is not None:
            graph.error_codes[qual.split(".")[-1]] = code
    domain_mods = [
        m
        for m in index.modules.values()
        if m.name == domain or m.name.startswith(domain + ".")
    ]
    for mod in domain_mods:
        for name, defn in mod.definitions.items():
            if isinstance(defn, ast.ClassDef):
                graph._class_lower.setdefault(name.lower(), f"{mod.name}.{name}")
    for mod in domain_mods:
        for fi in mod.functions.values():
            class_prefix = None
            parts = fi.qualname[len(mod.name) + 1 :].split(".")
            if len(parts) >= 2:
                owner = parts[-2]
                if isinstance(mod.definitions.get(owner), ast.ClassDef):
                    class_prefix = f"{mod.name}.{owner}"
            _walk_function(graph, mod, fi, class_prefix, typed_names)
            if fi.name == "_amain" or (
                fi.name == "_execute"
                and class_prefix is not None
                and class_prefix.split(".")[-1] == "Dispatcher"
            ):
                graph.entries.append(fi.qualname)
    graph.entries.sort()
    return graph


def _handler_names(handlers: list[ast.ExceptHandler]) -> frozenset[str]:
    """Leaf names a Try's handlers catch; ``"*"`` for bare/broad handlers.
    Exception *hierarchies* are not modelled — only an exact leaf-name
    match (or a broad handler) counts as catching, which under-catches and
    therefore over-reports; the broad-handler case covers the idiomatic
    serve guards."""
    names: set[str] = set()
    for handler in handlers:
        if handler.type is None:
            names.add("*")
            continue
        types = (
            handler.type.elts
            if isinstance(handler.type, ast.Tuple)
            else [handler.type]
        )
        for t in types:
            name = dotted_name(t)
            if name is None:
                continue
            leaf = name.split(".")[-1]
            names.add("*" if leaf in _BROAD_EXC else leaf)
    return frozenset(names)


def _walk_function(graph, mod, fi, class_prefix, typed_names) -> None:
    edges: list[tuple[str, frozenset[str]]] = []
    raises: list[RaiseSite] = []

    def visit(stmts, caught: frozenset[str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.Try):
                visit(stmt.body, caught | _handler_names(stmt.handlers))
                for h in stmt.handlers:
                    visit(h.body, caught)
                visit(stmt.orelse, caught)
                visit(stmt.finalbody, caught)
                continue
            if isinstance(stmt, ast.Raise):
                _record_raise(stmt, caught)
            for _name, value in ast.iter_fields(stmt):
                if isinstance(value, list):
                    if value and isinstance(value[0], ast.stmt):
                        visit(value, caught)
                    else:
                        for v in value:
                            if isinstance(v, ast.AST):
                                _visit_expr(v, caught)
                elif isinstance(value, ast.AST):
                    _visit_expr(value, caught)

    def _visit_expr(node, caught: frozenset[str]) -> None:
        stack = [node]
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # nested defs are their own graph nodes
            if isinstance(sub, ast.Call):
                target = _resolve(sub)
                if target is not None:
                    edges.append((target, caught))
            stack.extend(ast.iter_child_nodes(sub))

    def _resolve(call: ast.Call) -> str | None:
        called = dotted_name(call.func)
        if called and called.startswith("self.") and class_prefix:
            meth = called[len("self.") :]
            if "." not in meth:
                candidate = f"{class_prefix}.{meth}"
                if graph.index.function(candidate) is not None:
                    return candidate
            return None
        return graph.resolve_call(mod, call)

    def _record_raise(stmt: ast.Raise, caught: frozenset[str]) -> None:
        exc = stmt.exc
        if exc is None:
            return  # bare re-raise: the original type propagates
        target = exc.func if isinstance(exc, ast.Call) else exc
        name = dotted_name(target)
        if name is None:
            return
        leaf = name.split(".")[-1]
        resolved = graph.index.resolve_symbol(mod.name, name)
        typed = leaf in typed_names or (
            resolved is not None and resolved.split(".")[-1] in typed_names
        )
        builtin = leaf in _BUILTIN_EXCEPTIONS
        if not typed and not builtin and resolved is None:
            return  # unresolvable foreign class: never guessed
        raises.append(
            RaiseSite(
                qualname=fi.qualname,
                path=str(fi.path),
                line=stmt.lineno,
                exc_name=leaf,
                code=graph.error_codes.get(leaf),
                contained="*" in caught or leaf in caught,
                typed=typed,
                builtin=builtin,
            )
        )

    visit(fi.node.body, frozenset())
    if edges:
        graph.edges[fi.qualname] = edges
    if raises:
        graph.raises[fi.qualname] = raises


# ---------------------------------------------------------------------------
# assembly, schema, rendering
# ---------------------------------------------------------------------------


def build_contract(index) -> dict:
    """The full contract over one :class:`ProjectIndex` — deterministic:
    every mapping is key-sorted and every list value sorted or
    insertion-ordered from a sorted walk, so two builds over a
    byte-identical tree render byte-identical JSON."""
    from .sarif import _TOOL_VERSION

    protos = protocol_modules(index)
    graphs = build_serve_graphs(index)
    metrics, dynamic_sites = _extract_metrics(index)
    doc = {
        "contract_version": CONTRACT_VERSION,
        "generated_by": {"tool": "floxlint", "version": _TOOL_VERSION},
        "request_fields": sorted(
            set().union(*(request_fields(m) or [] for m in protos))
        )
        if protos
        else [],
        "ops": _extract_ops(index, graphs),
        "errors": _extract_errors(index, graphs),
        "endpoints": _extract_endpoints(index),
        "metrics": metrics,
        "dynamic_metric_sites": dynamic_sites,
        "knobs": _extract_knobs(index),
    }
    return doc


#: the artifact schema, hand-checked by :func:`validate_contract` (no
#: jsonschema dependency in the minimal container) and mirrored in
#: docs/implementation.md "Contract compiler"
CONTRACT_SCHEMA = {
    "contract_version": int,
    "generated_by": {"tool": str, "version": str},
    "request_fields": [str],
    "ops": {
        "*": {
            "module": str,
            "line": int,
            "request_fields": [str],
            "response_fields": [str],
            "error_codes": [str],
        }
    },
    "errors": {
        "*": {
            "class": (str, type(None)),
            "module": str,
            "line": int,
            "retry_after_ms": bool,
            "program": bool,
            "raised_in": [str],
        }
    },
    "endpoints": {
        "*": {"*": {"line": int, "query_params": [str], "statuses": [int]}}
    },
    "metrics": {
        "*": {"kinds": [str], "labels": [str], "modules": [str], "seeded": bool}
    },
    "dynamic_metric_sites": [{"module": str, "line": int}],
    "knobs": {
        "*": {
            "module": str,
            "line": int,
            "env": (str, type(None)),
            "validated": bool,
        }
    },
}


def validate_contract(doc: dict) -> list[str]:
    """Structural schema check; returns problems (empty = valid)."""
    problems: list[str] = []

    def check(value, schema, where: str) -> None:
        if isinstance(schema, dict):
            if not isinstance(value, dict):
                problems.append(f"{where}: expected object")
                return
            if "*" in schema:
                for k, v in value.items():
                    if not isinstance(k, str):
                        problems.append(f"{where}: non-string key {k!r}")
                    check(v, schema["*"], f"{where}.{k}")
            else:
                for k, sub in schema.items():
                    if k not in value:
                        problems.append(f"{where}: missing key {k!r}")
                    else:
                        check(value[k], sub, f"{where}.{k}")
        elif isinstance(schema, list):
            if not isinstance(value, list):
                problems.append(f"{where}: expected array")
                return
            for i, item in enumerate(value):
                check(item, schema[0], f"{where}[{i}]")
        else:
            types = schema if isinstance(schema, tuple) else (schema,)
            if bool in types and isinstance(value, bool):
                return
            if isinstance(value, bool) and bool not in types:
                problems.append(f"{where}: expected {types}, got bool")
                return
            if not isinstance(value, types):
                problems.append(
                    f"{where}: expected {types}, got {type(value).__name__}"
                )

    check(doc, CONTRACT_SCHEMA, "$")
    if not problems and doc.get("contract_version") != CONTRACT_VERSION:
        problems.append(
            f"$.contract_version: expected {CONTRACT_VERSION}, "
            f"got {doc.get('contract_version')}"
        )
    return problems


def render_contract(doc: dict) -> str:
    """Canonical byte form: key-sorted, 2-space indented, newline-terminated
    — two builds over an identical tree must compare byte-equal."""
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


def contract_for_paths(paths: Sequence[str | Path]) -> dict:
    """Build the contract over explicit paths (the ``--contract`` CLI)."""
    from .core import iter_python_files
    from .index import ProjectIndex

    groups: dict[Path, list[Path]] = {}
    for f, root in iter_python_files(paths):
        groups.setdefault(root, []).append(f)
    if not groups:
        raise ValueError("no Python files under the given paths")
    # one index over the union; the root is the first (sorted) lint root
    root = sorted(groups)[0]
    files = [f for fs in groups.values() for f in fs]
    index = ProjectIndex.build(files, root)
    return build_contract(index)


def cached_contract(pctx) -> dict:
    """The contract for a lint run's project index, built once per index
    (FLX017–FLX020 all reduce over the same artifact)."""
    cached = getattr(pctx.index, "_floxlint_contract", None)
    if cached is None:
        cached = build_contract(pctx.index)
        try:
            pctx.index._floxlint_contract = cached
        except AttributeError:
            pass
    return cached


def cached_serve_graphs(pctx) -> dict[str, "ServeGraph"]:
    """The per-domain serve-escape graphs for a lint run, built once per
    index (FLX020 and the contract build share them)."""
    cached = getattr(pctx.index, "_floxlint_serve_graphs", None)
    if cached is None:
        cached = build_serve_graphs(pctx.index)
        try:
            pctx.index._floxlint_serve_graphs = cached
        except AttributeError:
            pass
    return cached


# ---------------------------------------------------------------------------
# docs tables (shared by FLX017/FLX018/FLX019)
# ---------------------------------------------------------------------------


def find_docs_file(mod_path: Path, filename: str = "serving.md") -> Path | None:
    """Nearest ``docs/<filename>`` climbing from the module's directory —
    fixture packages carry their own ``docs/`` beside the code; the real
    tree resolves to the repo-level ``docs/``."""
    d = Path(mod_path).resolve().parent
    for _ in range(8):
        candidate = d / "docs" / filename
        if candidate.is_file():
            return candidate
        if d.parent == d:
            break
        d = d.parent
    return None


def parse_contract_tables(text: str) -> dict[str, list[dict]]:
    """``<!-- contract:<section> -->`` … ``<!-- /contract:<section> -->``
    delimited markdown tables -> section -> row dicts (header-keyed, raw
    cells; pull tokens out of a cell with :func:`cell_tokens`)."""
    import re

    out: dict[str, list[dict]] = {}
    for m in re.finditer(
        r"<!--\s*contract:([a-z_]+)\s*-->(.*?)<!--\s*/contract:\1\s*-->",
        text,
        re.DOTALL,
    ):
        section, body = m.group(1), m.group(2)
        rows: list[dict] = []
        header: list[str] | None = None
        for line in body.splitlines():
            line = line.strip()
            if not line.startswith("|"):
                continue
            cells = [c.strip() for c in line.strip("|").split("|")]
            if header is None:
                header = [c.strip("`").lower() for c in cells]
                continue
            if all(set(c) <= set("-: ") for c in cells):
                continue  # the |---|---| separator
            rows.append(dict(zip(header, cells)))
        out[section] = rows
    return out


def cell_tokens(cell: str) -> list[str]:
    """The code tokens of one table cell: backticked spans when present
    (``` `append` / `query` ``` -> both), else comma/slash-separated
    words. ``—`` / ``-`` / empty cells yield nothing."""
    import re

    ticked = re.findall(r"`([^`]+)`", cell)
    if ticked:
        return [t.strip() for t in ticked if t.strip()]
    out = []
    for part in re.split(r"[,/]", cell):
        part = part.strip()
        if part and part not in {"—", "-", "–"}:
            out.append(part)
    return out
