"""Suppression baseline (``--baseline`` check / ``--update-baseline`` write).

New semantic rules must be able to land without a flag day: the baseline
file records the findings that existed when a rule shipped, the gate fails
only on findings *not* in the baseline, and — symmetrically — on **drift**:
baseline entries whose finding no longer fires are stale suppressions that
must be deleted, so the baseline can only ever shrink.

Entries are keyed by a line-number-free fingerprint (path, rule, message)
with an occurrence count, so unrelated edits that shift a suppressed
finding up or down a file do not invalidate the baseline, while fixing the
finding (or rewording the rule) retires the entry.
"""

from __future__ import annotations

import hashlib
import json
from collections import Counter
from pathlib import Path
from typing import Sequence

from .core import Finding, LintError


def fingerprint(finding: Finding) -> str:
    raw = f"{finding.path}\x1f{finding.rule}\x1f{finding.message}"
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


def write_baseline(path: str | Path, findings: Sequence[Finding]) -> int:
    """Write the aggregated baseline for ``findings``; returns entry count."""
    counts: Counter[str] = Counter(fingerprint(f) for f in findings)
    seen: set[str] = set()
    entries = []
    for f in sorted(findings):
        fp = fingerprint(f)
        if fp in seen:
            continue
        seen.add(fp)
        entries.append(
            {
                "fingerprint": fp,
                "path": f.path,
                "rule": f.rule,
                "message": f.message,
                "count": counts[fp],
            }
        )
    payload = {"version": 1, "findings": entries}
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")
    return len(entries)


def load_baseline(path: str | Path) -> list[dict]:
    try:
        payload = json.loads(Path(path).read_text())
    except OSError as exc:
        raise LintError(f"cannot read baseline {path}: {exc}") from exc
    except json.JSONDecodeError as exc:
        raise LintError(f"baseline {path} is not valid JSON: {exc}") from exc
    if not isinstance(payload, dict) or "findings" not in payload:
        raise LintError(f"baseline {path} has no 'findings' list")
    return list(payload["findings"])


def apply_baseline(
    findings: Sequence[Finding], entries: Sequence[dict]
) -> tuple[list[Finding], list[dict]]:
    """Split ``findings`` against the baseline.

    Returns ``(new_findings, stale_entries)``: findings not covered by any
    baseline entry (each entry absorbs up to ``count`` occurrences of its
    fingerprint), and entries with *unused* budget — an entry whose count
    exceeds what still fires is a stale suppression: the surplus would
    otherwise silently absorb the same finding if it were reintroduced
    later, so the baseline must shrink to the surviving count."""
    budget: Counter[str] = Counter()
    for e in entries:
        fp = e.get("fingerprint")
        if isinstance(fp, str):
            budget[fp] += int(e.get("count", 1))
    matched: Counter[str] = Counter()
    new: list[Finding] = []
    for f in sorted(findings):
        fp = fingerprint(f)
        if matched[fp] < budget.get(fp, 0):
            matched[fp] += 1
        else:
            new.append(f)
    stale = [
        e
        for e in entries
        if isinstance(e.get("fingerprint"), str)
        and matched[e["fingerprint"]] < budget[e["fingerprint"]]
    ]
    return new, stale
