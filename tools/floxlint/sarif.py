"""SARIF 2.1.0 output (``--format sarif``).

One run, one driver ("floxlint"), the full rule table as
``tool.driver.rules`` (so GitHub code scanning can show rule help even for
rules with zero results this run), and one result per finding with a
single physical location. Columns are 1-based in SARIF where the internal
:class:`~tools.floxlint.core.Finding` carries 0-based ``col`` — the +1
happens exactly once, here. URIs are emitted repo-relative with forward
slashes so the upload-sarif action can anchor code-scanning annotations.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Sequence

from .core import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)
_TOOL_VERSION = "4.0.0"  # floxlint v4: static contract compiler + drift rules


def _relative_uri(path: str) -> str:
    """Repo-relative forward-slash URI when the path is under the cwd,
    else the path as given (absolute paths stay absolute)."""
    p = Path(path)
    try:
        p = p.resolve().relative_to(Path.cwd().resolve())
    except ValueError:
        pass
    return p.as_posix()


def sarif_document(findings: Sequence[Finding], rules: Sequence) -> dict:
    """The SARIF log as a plain dict (the JSON-serializable contract the
    self-tests validate structurally)."""
    ordered_rules = sorted(rules, key=lambda r: r.id)
    rule_index = {r.id: i for i, r in enumerate(ordered_rules)}
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": f"{f.rule} {f.message}"},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": _relative_uri(f.path),
                            "uriBaseId": "%SRCROOT%",
                        },
                        "region": {
                            "startLine": max(f.line, 1),
                            "startColumn": f.col + 1,
                        },
                    }
                }
            ],
        }
        if f.rule in rule_index:
            result["ruleIndex"] = rule_index[f.rule]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "floxlint",
                        "version": _TOOL_VERSION,
                        "informationUri": (
                            "https://github.com/flox-tpu/flox-tpu/blob/main/"
                            "docs/implementation.md"
                        ),
                        "rules": [
                            {
                                "id": r.id,
                                "name": r.name,
                                "shortDescription": {"text": r.description},
                                "defaultConfiguration": {"level": "warning"},
                            }
                            for r in ordered_rules
                        ],
                    }
                },
                "columnKind": "utf16CodeUnits",
                "results": results,
            }
        ],
    }


def format_sarif(findings: Sequence[Finding], rules: Sequence, *, files_checked: int = 0) -> str:
    return json.dumps(sarif_document(findings, rules), indent=2)
