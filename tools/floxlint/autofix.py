"""``--fix`` autofixes for the mechanical rules.

Two rule families have a single sanctioned rewrite, so the linter applies
it instead of just complaining:

* **FLX007** eager logging -> lazy ``%``-args: ``logger.debug(f"n={n}")``
  becomes ``logger.debug('n=%s', n)``; ``%``-interpolated, concatenated and
  ``str.format``-built messages get the equivalent treatment. Bare
  ``print()`` has no mechanical fix (it needs a logger decision) and is
  left alone.
* **FLX004** version-gated API -> compat wrapping: ``jax.tree_map`` /
  ``jax.tree_multimap`` / ``jax.tree_util.tree_multimap`` rewrite to
  ``jax.tree.map``; ``jax.shard_map`` and ``jax.lax.axis_size`` rewrite to
  the ``flox_tpu.parallel.mesh`` shim names, inserting the import after the
  last top-level import if missing. Gated *imports* (``from
  jax.experimental.shard_map import ...``) are structural and stay manual.

Fixes are pure source-span replacements computed from AST positions and
applied back-to-front, so a file the fixer cannot fully fix is still left
syntactically intact. A second ``--fix`` pass over fixed output finds no
eager patterns and must therefore be byte-stable — the self-tests pin that.
Suppressed lines (``# floxlint: disable=...`` / ``# noqa: FLXnnn``) are
never rewritten.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, Sequence

from .core import parse_suppressions
from .rules.common import ImportMap
from .rules.flx007_logging import _eager_kind, log_message_arg

#: rules --fix knows how to rewrite
FIXABLE_RULES = frozenset({"FLX004", "FLX007"})

_MESH_SHIM = "flox_tpu.parallel.mesh"
#: gated attribute chain (resolved) -> shim name imported from _MESH_SHIM
_SHIM_NAMES = {"jax.shard_map": "shard_map", "jax.lax.axis_size": "axis_size"}
_TREE_MAP_APIS = ("jax.tree_map", "jax.tree_multimap", "jax.tree_util.tree_multimap")


def fix_source(source: str) -> tuple[str, int]:
    """Apply every available fix to ``source``; returns (new_source, n)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return source, 0
    suppressions = parse_suppressions(source)
    imports = ImportMap.from_tree(tree)
    edits: list[tuple[int, int, str]] = []
    needed_shim_imports: set[str] = set()

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            edit = _logging_edit(source, node, suppressions)
            if edit is not None:
                edits.append(edit)
        elif isinstance(node, ast.Attribute):
            edit = _version_edit(source, node, imports, suppressions, needed_shim_imports)
            if edit is not None:
                edits.append(edit)

    if not edits:
        return source, 0
    new_source = _apply_edits(source, edits)
    missing = needed_shim_imports - _imported_shim_names(new_source)
    if missing:
        new_source = _insert_import(
            new_source,
            f"from {_MESH_SHIM} import {', '.join(sorted(missing))}",
        )
    return new_source, len(edits)


def fix_paths(paths: Iterable[str | Path]) -> dict[str, int]:
    """Fix files in place; returns {path: edit count} for changed files."""
    out: dict[str, int] = {}
    for raw in paths:
        path = Path(raw)
        try:
            source = path.read_text()
        except OSError:
            continue
        fixed, n = fix_source(source)
        if n and fixed != source:
            path.write_text(fixed)
            out[str(path)] = n
    return out


# -- FLX007: eager logging -> lazy %-args -----------------------------------


def _logging_edit(
    source: str, call: ast.Call, suppressions
) -> tuple[int, int, str] | None:
    msg = log_message_arg(call)
    if msg is None or _eager_kind(msg) is None:
        return None
    if suppressions.active("FLX007", msg.lineno):
        return None
    if call.args and call.args[-1] is not msg:
        return None  # eager message followed by positional args: not ours
    rewritten = _lazy_message(source, msg)
    if rewritten is None:
        return None
    fmt, args = rewritten
    replacement = ", ".join([repr(fmt), *args]) if args else repr(fmt)
    span = _span(source, msg)
    return (*span, replacement) if span else None


def _lazy_message(source: str, msg: ast.AST) -> tuple[str, list[str]] | None:
    """(format string, arg source texts) for an eager message, or None when
    the shape is too clever to rewrite mechanically."""
    if isinstance(msg, ast.JoinedStr):
        fmt, args = "", []
        for value in msg.values:
            if isinstance(value, ast.Constant) and isinstance(value.value, str):
                fmt += value.value.replace("%", "%%")
            elif isinstance(value, ast.FormattedValue):
                if value.conversion != -1 or value.format_spec is not None:
                    return None  # f"{x!r}" / f"{x:.3f}": formatting is load-bearing
                seg = ast.get_source_segment(source, value.value)
                if seg is None:
                    return None
                fmt += "%s"
                args.append(seg)
            else:
                return None
        return fmt, args
    if isinstance(msg, ast.BinOp) and isinstance(msg.op, ast.Mod):
        if not (isinstance(msg.left, ast.Constant) and isinstance(msg.left.value, str)):
            return None
        right = msg.right
        elts = right.elts if isinstance(right, ast.Tuple) else [right]
        args = []
        for elt in elts:
            seg = ast.get_source_segment(source, elt)
            if seg is None:
                return None
            args.append(seg)
        return msg.left.value, args
    if isinstance(msg, ast.BinOp) and isinstance(msg.op, ast.Add):
        terms = _flatten_concat(msg)
        if terms is None:
            return None
        fmt, args = "", []
        saw_literal = False
        for term in terms:
            if isinstance(term, ast.Constant) and isinstance(term.value, str):
                fmt += term.value.replace("%", "%%")
                saw_literal = True
                continue
            # "x=" + str(x): unwrap the str() — %s stringifies anyway
            inner = term
            if (
                isinstance(term, ast.Call)
                and isinstance(term.func, ast.Name)
                and term.func.id == "str"
                and len(term.args) == 1
                and not term.keywords
            ):
                inner = term.args[0]
            seg = ast.get_source_segment(source, inner)
            if seg is None:
                return None
            fmt += "%s"
            args.append(seg)
        return (fmt, args) if saw_literal else None
    if (
        isinstance(msg, ast.Call)
        and isinstance(msg.func, ast.Attribute)
        and msg.func.attr == "format"
        and isinstance(msg.func.value, ast.Constant)
        and isinstance(msg.func.value.value, str)
        and not msg.keywords
    ):
        template = msg.func.value.value
        stripped = template.replace("{}", "")
        if "{" in stripped or "}" in stripped:
            return None  # {0} / {name} / {{ }}: positional mapping is not mechanical
        if template.count("{}") != len(msg.args):
            return None
        args = []
        for a in msg.args:
            seg = ast.get_source_segment(source, a)
            if seg is None:
                return None
            args.append(seg)
        return template.replace("%", "%%").replace("{}", "%s"), args
    return None


def _flatten_concat(node: ast.AST) -> list[ast.AST] | None:
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _flatten_concat(node.left)
        right = _flatten_concat(node.right)
        if left is None or right is None:
            return None
        return left + right
    return [node]


# -- FLX004: version-gated API -> compat spelling ---------------------------


def _version_edit(
    source: str,
    node: ast.Attribute,
    imports: ImportMap,
    suppressions,
    needed_shim_imports: set[str],
) -> tuple[int, int, str] | None:
    resolved = imports.resolve(node)
    if resolved is None or suppressions.active("FLX004", node.lineno):
        return None
    root = _chain_root(node)
    if root is None:
        return None
    if resolved in _TREE_MAP_APIS:
        span = _span(source, node)
        return (*span, f"{root.id}.tree.map") if span else None
    if resolved in _SHIM_NAMES:
        span = _span(source, node)
        if span is None:
            return None
        needed_shim_imports.add(_SHIM_NAMES[resolved])
        return (*span, _SHIM_NAMES[resolved])
    return None


def _chain_root(node: ast.Attribute) -> ast.Name | None:
    base: ast.AST = node
    while isinstance(base, ast.Attribute):
        base = base.value
    return base if isinstance(base, ast.Name) else None


# -- span plumbing ----------------------------------------------------------


def _line_offsets(source: str) -> list[int]:
    offsets = [0]
    for line in source.splitlines(keepends=True):
        offsets.append(offsets[-1] + len(line))
    return offsets


def _span(source: str, node: ast.AST) -> tuple[int, int] | None:
    end_lineno = getattr(node, "end_lineno", None)
    end_col = getattr(node, "end_col_offset", None)
    if end_lineno is None or end_col is None:
        return None
    offsets = _line_offsets(source)
    if node.lineno > len(offsets) - 1 or end_lineno > len(offsets) - 1:
        return None
    return offsets[node.lineno - 1] + node.col_offset, offsets[end_lineno - 1] + end_col


def _apply_edits(source: str, edits: Sequence[tuple[int, int, str]]) -> str:
    applied = source
    last_start = len(source) + 1
    for start, end, replacement in sorted(edits, key=lambda e: e[0], reverse=True):
        if end > last_start:
            continue  # overlapping (nested) edit: outermost wins
        applied = applied[:start] + replacement + applied[end:]
        last_start = start
    return applied


def _imported_shim_names(source: str) -> set[str]:
    """Names already imported from the mesh shim — checked per name, not by
    substring: a pre-existing ``from ...mesh import shard_map`` must not
    suppress the insert a new bare ``axis_size`` still needs."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return set()
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == _MESH_SHIM:
            # an aliased import (shard_map as sm) does not bind the bare
            # name the rewritten call sites use — only unaliased ones count
            out.update(a.name for a in node.names if a.asname in (None, a.name))
    return out


def _insert_import(source: str, import_line: str) -> str:
    """Insert after the last top-level import (or the module docstring)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return source
    insert_after = 0
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            insert_after = getattr(node, "end_lineno", node.lineno)
        elif (
            insert_after == 0
            and isinstance(node, ast.Expr)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            insert_after = getattr(node, "end_lineno", node.lineno)
    lines = source.splitlines(keepends=True)
    newline = "\n"
    lines.insert(insert_after, import_line + newline)
    return "".join(lines)
