"""Output formatting: human (one finding per line) and machine (JSON)."""

from __future__ import annotations

import json
from collections import Counter
from typing import Sequence

from .core import Finding


def format_human(findings: Sequence[Finding], *, files_checked: int) -> str:
    lines = [f.format_human() for f in findings]
    if findings:
        by_rule = Counter(f.rule for f in findings)
        breakdown = ", ".join(f"{r}: {n}" for r, n in sorted(by_rule.items()))
        lines.append("")
        lines.append(
            f"floxlint: {len(findings)} finding(s) in {files_checked} file(s) ({breakdown})"
        )
    else:
        lines.append(f"floxlint: clean — 0 findings in {files_checked} file(s)")
    return "\n".join(lines)


def format_json(findings: Sequence[Finding], *, files_checked: int) -> str:
    by_rule = Counter(f.rule for f in findings)
    return json.dumps(
        {
            "files_checked": files_checked,
            "finding_count": len(findings),
            "findings_by_rule": dict(sorted(by_rule.items())),
            "findings": [f.as_dict() for f in findings],
        },
        indent=2,
    )
