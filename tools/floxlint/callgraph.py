"""Call graph over a :class:`~tools.floxlint.index.ProjectIndex`.

Edges connect canonical function names ("flox_tpu.cache.clear_all" ->
"flox_tpu.telemetry.MetricsRegistry.reset" is out of scope — method
receivers are not resolved — but plain-function calls, including ones
reached through import aliases and package re-exports, are). Each edge
keeps its call sites so interprocedural rules (FLX008 reachability, FLX011
helper-sync) can point findings at the exact offending line.
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass

from .index import ProjectIndex
from .rules.common import dotted_name


@dataclass(frozen=True)
class CallSite:
    caller: str  #: canonical qualname of the calling function
    callee: str  #: canonical qualname of the resolved project function
    node: ast.Call


class CallGraph:
    def __init__(self) -> None:
        #: caller qualname -> set of resolved project callee qualnames
        self.edges: dict[str, set[str]] = {}
        #: caller qualname -> call sites (resolved project calls only)
        self.sites: dict[str, list[CallSite]] = {}

    @classmethod
    def build(cls, index: ProjectIndex) -> "CallGraph":
        graph = cls()
        for mod in index.modules.values():
            for fi in mod.functions.values():
                graph.edges.setdefault(fi.qualname, set())
                graph.sites.setdefault(fi.qualname, [])
                for call in _own_calls(fi.node):
                    name = dotted_name(call.func)
                    if name is None:
                        continue
                    resolved = index.resolve_symbol(mod.name, name)
                    if resolved is None or index.function(resolved) is None:
                        continue
                    graph.edges[fi.qualname].add(resolved)
                    graph.sites[fi.qualname].append(
                        CallSite(caller=fi.qualname, callee=resolved, node=call)
                    )
        return graph

    def callees(self, qualname: str) -> set[str]:
        return self.edges.get(qualname, set())

    def reachable(self, qualname: str, max_depth: int | None = None) -> set[str]:
        """Functions reachable from ``qualname`` (excluded itself), BFS with
        an optional depth bound (depth 1 = direct callees)."""
        out: set[str] = set()
        queue: deque[tuple[str, int]] = deque([(qualname, 0)])
        while queue:
            fn, depth = queue.popleft()
            if max_depth is not None and depth >= max_depth:
                continue
            for callee in self.edges.get(fn, ()):
                if callee not in out and callee != qualname:
                    out.add(callee)
                    queue.append((callee, depth + 1))
        return out


def _own_calls(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> list[ast.Call]:
    """Call nodes in ``fn``'s own body, excluding nested function bodies
    (those attribute to the nested function's own graph node)."""
    calls: list[ast.Call] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, ast.Call):
                calls.append(child)
            visit(child)

    visit(fn)
    return calls
