"""Per-function effect summaries for the concurrency rules (FLX013–FLX016).

Where the project index answers "what does this name resolve to" and the
call graph answers "who calls whom", this module answers "what does this
function *do* to shared state": which module-level mutable objects it
writes (reusing FLX008's container detection), which locks it acquires
(``with``-statements — including multi-item and ``async with`` — plus
``acquire``/``release`` call pairs, resolved through import aliases,
``self`` attributes, local aliases, and lock-named parameters), and where
it can block the calling thread (``time.sleep``, file/socket IO,
subprocess, blocking queue get/put, ``jax.device_get`` /
``block_until_ready``, thread joins, future results, event waits, lock
acquisition).

Everything here is pure AST — nothing is imported or executed — and
intraprocedural: each :class:`FunctionEffects` describes one function body,
with the lock set *held locally* recorded per write site, per acquisition,
and per outgoing call. The interprocedural composition (held-at-entry
propagation, thread reachability, the lock-order graph) lives in
:mod:`tools.floxlint.concurrency`.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Iterable

from .rules.common import ImportMap, dotted_name
from .rules.flx008_cache_registry import _MUTATING_METHODS, _is_mutable_container

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .index import FunctionInfo, ModuleInfo, ProjectIndex

# -- lock kinds --------------------------------------------------------------

LOCK = "lock"  #: non-reentrant threading.Lock (signal/self-deadlock hazard)
RLOCK = "rlock"  #: reentrant
ASYNC_LOCK = "async-lock"  #: asyncio.Lock — guards tasks, not threads

_LOCK_CONSTRUCTORS = {
    "threading.Lock": LOCK,
    "threading.RLock": RLOCK,
    "multiprocessing.Lock": LOCK,
    "multiprocessing.RLock": RLOCK,
    "asyncio.Lock": ASYNC_LOCK,
}

# -- blocking-call taxonomy --------------------------------------------------

SLEEP = "sleep"
FILE_IO = "file-io"
SOCKET = "socket"
SUBPROCESS = "subprocess"
QUEUE_OP = "queue"
DEVICE_SYNC = "device-sync"
THREAD_JOIN = "thread-join"
FUTURE_RESULT = "future-result"
EVENT_WAIT = "event-wait"
LOCK_ACQUIRE = "lock-acquire"

#: canonical dotted names that block outright
_BLOCKING_CALLS = {
    "time.sleep": SLEEP,
    "socket.create_connection": SOCKET,
    "jax.device_get": DEVICE_SYNC,
    "jax.block_until_ready": DEVICE_SYNC,
    "concurrent.futures.wait": FUTURE_RESULT,
    "os.replace": FILE_IO,
    "os.fsync": FILE_IO,
    "shutil.rmtree": FILE_IO,
    "shutil.copy": FILE_IO,
    "shutil.copytree": FILE_IO,
}
#: dotted prefixes that block as a family
_BLOCKING_PREFIXES = ("subprocess.", "urllib.request.", "requests.", "http.client.")

#: constructor dotted name -> receiver type for method-level blocking
_TYPED_CONSTRUCTORS = {
    "queue.Queue": "queue",
    "queue.LifoQueue": "queue",
    "queue.PriorityQueue": "queue",
    "queue.SimpleQueue": "queue",
    "asyncio.Queue": "asyncio-queue",  # await-based: NOT blocking
    "threading.Thread": "thread",
    "threading.Timer": "thread",
    "threading.Event": "event",
    "socket.socket": "socket",
    "concurrent.futures.ThreadPoolExecutor": "executor",
    "concurrent.futures.ProcessPoolExecutor": "executor",
}
#: (receiver type, method) -> blocking kind
_TYPED_METHODS = {
    ("queue", "get"): QUEUE_OP,
    ("queue", "put"): QUEUE_OP,
    ("queue", "join"): QUEUE_OP,
    ("thread", "join"): THREAD_JOIN,
    ("event", "wait"): EVENT_WAIT,
    ("future", "result"): FUTURE_RESULT,
    ("future", "exception"): FUTURE_RESULT,
    ("socket", "connect"): SOCKET,
    ("socket", "accept"): SOCKET,
    ("socket", "recv"): SOCKET,
    ("socket", "send"): SOCKET,
    ("socket", "sendall"): SOCKET,
}


@dataclass(frozen=True)
class Acquisition:
    """One lock acquisition site (``with`` item or ``.acquire()`` call)."""

    lock: str  #: canonical lock id ("mod._LOCK", "mod.Cls._lock", "param:…")
    kind: str  #: LOCK / RLOCK / ASYNC_LOCK
    held_before: tuple[str, ...]  #: locks already held at this point (in order)
    lineno: int
    col: int
    blocking: bool  #: False for ``acquire(blocking=False)``


@dataclass(frozen=True)
class BlockingOp:
    """One potentially-blocking call site."""

    kind: str  #: one of the taxonomy constants above
    detail: str  #: resolved callable / receiver description
    lineno: int
    col: int


@dataclass(frozen=True)
class WriteSite:
    """One in-place mutation (or ``global`` rebind) of a shared object."""

    obj: str  #: canonical id of the module-level object ("mod._STATE")
    held: tuple[str, ...]  #: locks held locally at the write
    lineno: int
    col: int


@dataclass
class CallRecord:
    """One outgoing call with the locally-held lock set (resolution to a
    project function happens in :mod:`.concurrency`)."""

    call: ast.Call
    held: tuple[str, ...]


@dataclass
class FunctionEffects:
    qualname: str
    module: str
    is_async: bool
    writes: list[WriteSite] = field(default_factory=list)
    reads: set[str] = field(default_factory=set)
    acquisitions: list[Acquisition] = field(default_factory=list)
    blocking: list[BlockingOp] = field(default_factory=list)
    calls: list[CallRecord] = field(default_factory=list)
    #: local name -> receiver type ("queue", "thread", …) for spawn detection
    local_types: dict[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class LockDef:
    canonical: str  #: "mod._LOCK" or "mod.Cls._lock"
    kind: str
    module: str
    lineno: int


# -- project-wide universes --------------------------------------------------


def shared_objects(index: "ProjectIndex") -> set[str]:
    """Canonical ids of every module-level mutable container in the project
    (any name — unlike FLX008 this is not restricted to cache-named
    ALL_CAPS bindings: a lowercase module-level list is just as racy)."""
    out: set[str] = set()
    for mod in index.modules.values():
        for node in mod.tree.body:
            targets: list[ast.Name] = []
            value: ast.AST | None = None
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets if isinstance(t, ast.Name)]
                value = node.value
            elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                targets = [node.target]
                value = node.value
            if value is None or not _is_mutable_container(value):
                continue
            for t in targets:
                out.add(f"{mod.name}.{t.id}")
    return out


def lock_defs(index: "ProjectIndex") -> dict[str, LockDef]:
    """Every lock definition in the project: module globals
    (``_LOCK = threading.Lock()``), class-level attributes, and instance
    attributes assigned in methods (``self._lock = threading.RLock()``)."""
    out: dict[str, LockDef] = {}

    def ctor_kind(mod: "ModuleInfo", value: ast.AST) -> str | None:
        if not isinstance(value, ast.Call):
            return None
        resolved = mod.imports.resolve(value.func)
        return _LOCK_CONSTRUCTORS.get(resolved) if resolved else None

    for mod in index.modules.values():
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                kind = ctor_kind(mod, node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            cid = f"{mod.name}.{t.id}"
                            out[cid] = LockDef(cid, kind, mod.name, node.lineno)
            elif isinstance(node, ast.ClassDef):
                prefix = f"{mod.name}.{node.name}"
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        kind = ctor_kind(mod, sub.value)
                        if not kind:
                            continue
                        for t in sub.targets:
                            name = None
                            if isinstance(t, ast.Name):
                                name = t.id  # class-level attribute
                            elif (
                                isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                            ):
                                name = t.attr  # self._lock = … in a method
                            if name:
                                cid = f"{prefix}.{name}"
                                out[cid] = LockDef(cid, kind, mod.name, sub.lineno)
    return out


def module_types(index: "ProjectIndex") -> dict[str, str]:
    """Canonical id -> receiver type for module-level typed objects
    (``_Q = queue.Queue()`` makes ``mod._Q`` a blocking queue)."""
    out: dict[str, str] = {}
    for mod in index.modules.values():
        for node in mod.tree.body:
            value = getattr(node, "value", None)
            if not isinstance(node, (ast.Assign, ast.AnnAssign)) or not isinstance(
                value, ast.Call
            ):
                continue
            resolved = mod.imports.resolve(value.func)
            rtype = _TYPED_CONSTRUCTORS.get(resolved) if resolved else None
            if rtype is None:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Name):
                    out[f"{mod.name}.{t.id}"] = rtype
    return out


# -- the intraprocedural walker ---------------------------------------------


class _EffectWalker:
    """One pass over one function body, tracking the ordered held-lock set
    through ``with`` nesting (and ``acquire``/``release`` pairs, which hold
    from the statement after the acquire to the matching release or the end
    of the enclosing block — a deliberate over-approximation)."""

    def __init__(
        self,
        mod: "ModuleInfo",
        fi: "FunctionInfo",
        index: "ProjectIndex",
        shared: set[str],
        locks: dict[str, LockDef],
        mtypes: dict[str, str],
    ) -> None:
        self.mod = mod
        self.fi = fi
        self.index = index
        self.shared = shared
        self.locks = locks
        self.mtypes = mtypes
        self.imports = mod.imports
        self.out = FunctionEffects(
            qualname=fi.qualname,
            module=mod.name,
            is_async=isinstance(fi.node, ast.AsyncFunctionDef),
        )
        args = fi.node.args
        self.params = {
            a.arg
            for a in args.posonlyargs + args.args + args.kwonlyargs
        }
        self.globals_declared: set[str] = set()
        self.local_lock_aliases: dict[str, str] = {}
        self._prepass()

    # -- pre-pass: local types, lock aliases, global declarations ------------

    def _prepass(self) -> None:
        for node in _own_nodes(self.fi.node):
            if isinstance(node, ast.Global):
                self.globals_declared.update(node.names)
            elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                resolved = self.imports.resolve(node.value.func)
                rtype = _TYPED_CONSTRUCTORS.get(resolved) if resolved else None
                if rtype is None and isinstance(node.value.func, ast.Attribute):
                    if node.value.func.attr == "submit":
                        rtype = "future"  # fut = executor.submit(…)
                if rtype:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.out.local_types[t.id] = rtype
            elif isinstance(node, ast.Assign):
                lock = self._resolve_lock(node.value)
                if lock:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.local_lock_aliases[t.id] = lock

    # -- lock / object resolution --------------------------------------------

    def _resolve_lock(self, expr: ast.AST) -> str | None:
        name = dotted_name(expr)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        if head == "self" and rest and "." not in rest:
            # climb the qualname: mod.Cls.fn -> try mod.Cls.<attr>
            prefix = self.fi.qualname.rsplit(".", 1)[0]
            while prefix and prefix != self.mod.name:
                cand = f"{prefix}.{rest}"
                if cand in self.locks:
                    return cand
                if "lock" in rest.lower():
                    return cand  # lock-named self attribute, ctor unseen
                prefix = prefix.rsplit(".", 1)[0] if "." in prefix else ""
            return None
        if not rest and head in self.local_lock_aliases:
            return self.local_lock_aliases[head]
        if not rest and head in self.params:
            # a parameter only counts as a lock when its name says so
            if "lock" in head.lower() or "mutex" in head.lower():
                return f"param:{self.fi.qualname}:{head}"
            return None
        resolved = self.index.resolve_symbol(self.mod.name, name)
        if resolved is not None and resolved in self.locks:
            return resolved
        return None

    def _lock_kind(self, lock: str) -> str:
        ld = self.locks.get(lock)
        return ld.kind if ld is not None else LOCK  # unknown: assume plain

    def _resolve_shared(self, expr: ast.AST) -> str | None:
        name = dotted_name(expr)
        if name is None:
            return None
        resolved = self.index.resolve_symbol(self.mod.name, name)
        if resolved is not None and resolved in self.shared:
            return resolved
        return None

    def _receiver_type(self, expr: ast.AST) -> str | None:
        name = dotted_name(expr)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        if not rest and head in self.out.local_types:
            return self.out.local_types[head]
        resolved = self.index.resolve_symbol(self.mod.name, name)
        if resolved is not None and resolved in self.mtypes:
            return self.mtypes[resolved]
        return None

    # -- traversal ------------------------------------------------------------

    def run(self) -> FunctionEffects:
        self._visit_block(self.fi.node.body, ())
        return self.out

    def _visit_block(self, stmts: Iterable[ast.stmt], held: tuple[str, ...]) -> None:
        held = tuple(held)
        for s in stmts:
            self._visit_stmt(s, held)
            held = self._apply_sticky(s, held)

    def _visit_stmt(self, s: ast.stmt, held: tuple[str, ...]) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs attribute to their own graph node
        if isinstance(s, (ast.With, ast.AsyncWith)):
            h = held
            for item in s.items:
                self._scan_expr(item.context_expr, h)
                lock = self._resolve_lock(item.context_expr)
                if lock:
                    self.out.acquisitions.append(
                        Acquisition(
                            lock=lock,
                            kind=self._lock_kind(lock),
                            held_before=h,
                            lineno=item.context_expr.lineno,
                            col=item.context_expr.col_offset,
                            blocking=True,
                        )
                    )
                    h = h + (lock,)
            self._visit_block(s.body, h)
            return
        self._record_writes(s, held)
        for expr in _own_exprs(s):
            self._scan_expr(expr, held)
        for block in _child_blocks(s):
            self._visit_block(block, held)

    def _apply_sticky(self, s: ast.stmt, held: tuple[str, ...]) -> tuple[str, ...]:
        """``L.acquire()`` holds L for the rest of the block; ``L.release()``
        drops it."""
        for node in ast.walk(s):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("acquire", "release")
            ):
                continue
            lock = self._resolve_lock(node.func.value)
            if lock is None:
                continue
            if node.func.attr == "acquire" and lock not in held:
                held = held + (lock,)
            elif node.func.attr == "release":
                held = tuple(x for x in held if x != lock)
        return held

    # -- per-expression effects ----------------------------------------------

    def _scan_expr(self, expr: ast.AST, held: tuple[str, ...]) -> None:
        for node in _walk_expr(expr):
            if isinstance(node, ast.Call):
                self._classify_call(node, held)
            elif isinstance(node, (ast.Name, ast.Attribute)):
                obj = self._resolve_shared(node)
                if obj is not None:
                    self.out.reads.add(obj)

    def _classify_call(self, call: ast.Call, held: tuple[str, ...]) -> None:
        self.out.calls.append(CallRecord(call=call, held=held))
        resolved = self.imports.resolve(call.func)
        if resolved is not None:
            kind = _BLOCKING_CALLS.get(resolved)
            if kind is None and any(
                resolved.startswith(p) for p in _BLOCKING_PREFIXES
            ):
                kind = SUBPROCESS if resolved.startswith("subprocess.") else SOCKET
            if kind is not None:
                self.out.blocking.append(
                    BlockingOp(kind, resolved, call.lineno, call.col_offset)
                )
                return
        if isinstance(call.func, ast.Name) and call.func.id == "open":
            self.out.blocking.append(
                BlockingOp(FILE_IO, "open", call.lineno, call.col_offset)
            )
            return
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr in ("acquire", "release"):
                lock = self._resolve_lock(call.func.value)
                if lock is not None and attr == "acquire":
                    blocking = not _kw_is_false(call, "blocking")
                    self.out.acquisitions.append(
                        Acquisition(
                            lock=lock,
                            kind=self._lock_kind(lock),
                            held_before=held,
                            lineno=call.lineno,
                            col=call.col_offset,
                            blocking=blocking,
                        )
                    )
                    if blocking:
                        self.out.blocking.append(
                            BlockingOp(LOCK_ACQUIRE, lock, call.lineno, call.col_offset)
                        )
                return
            if attr == "block_until_ready":
                self.out.blocking.append(
                    BlockingOp(DEVICE_SYNC, attr, call.lineno, call.col_offset)
                )
                return
            rtype = self._receiver_type(call.func.value)
            kind = _TYPED_METHODS.get((rtype, attr)) if rtype else None
            if kind == QUEUE_OP and _kw_is_false(call, "block"):
                kind = None  # q.get(block=False) raises instead of blocking
            if kind is not None:
                self.out.blocking.append(
                    BlockingOp(
                        kind,
                        f"{dotted_name(call.func) or attr}",
                        call.lineno,
                        call.col_offset,
                    )
                )

    def _record_writes(self, s: ast.stmt, held: tuple[str, ...]) -> None:
        def site(obj: str, node: ast.AST) -> None:
            self.out.writes.append(
                WriteSite(obj=obj, held=held, lineno=node.lineno, col=node.col_offset)
            )

        targets: list[ast.AST] = []
        if isinstance(s, ast.Assign):
            targets = list(s.targets)
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            targets = [s.target]
        elif isinstance(s, ast.Delete):
            targets = list(s.targets)
        for t in targets:
            if isinstance(t, ast.Subscript):
                obj = self._resolve_shared(t.value)
                if obj is not None:
                    site(obj, s)
            elif isinstance(t, ast.Name) and t.id in self.globals_declared:
                obj = self._resolve_shared(t)
                if obj is not None:
                    site(obj, s)  # global rebind of a shared container
        # mutating method calls on a shared object anywhere in the statement
        for node in _own_exprs(s):
            for sub in _walk_expr(node):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _MUTATING_METHODS
                ):
                    obj = self._resolve_shared(sub.func.value)
                    if obj is not None:
                        site(obj, sub)


# -- AST helpers -------------------------------------------------------------

_STMT_BLOCK_FIELDS = ("body", "orelse", "finalbody")


def _child_blocks(s: ast.stmt) -> Iterable[list[ast.stmt]]:
    for name in _STMT_BLOCK_FIELDS:
        block = getattr(s, name, None)
        if isinstance(block, list) and block and isinstance(block[0], ast.stmt):
            yield block
    for handler in getattr(s, "handlers", ()) or ():
        yield handler.body
    for case in getattr(s, "cases", ()) or ():
        yield case.body


def _own_exprs(s: ast.stmt) -> Iterable[ast.expr]:
    """Expression children of one statement, excluding nested statement
    blocks (those are visited with their own held-set context)."""
    for name, value in ast.iter_fields(s):
        if name in _STMT_BLOCK_FIELDS or name in ("handlers", "cases"):
            continue
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, ast.expr):
                    yield v


def _walk_expr(expr: ast.AST) -> Iterable[ast.AST]:
    """Walk an expression tree, pruning lambda bodies (their calls run at
    call time, not here)."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _own_nodes(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> Iterable[ast.AST]:
    """All nodes in ``fn``'s own body, excluding nested function/class defs."""
    stack: list[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _kw_is_false(call: ast.Call, name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return kw.value.value is False
    return False


# -- public entry ------------------------------------------------------------


def compute_effects(index: "ProjectIndex") -> dict[str, FunctionEffects]:
    """Effect summaries for every function in the project, keyed by
    canonical qualname."""
    shared = shared_objects(index)
    locks = lock_defs(index)
    mtypes = module_types(index)
    out: dict[str, FunctionEffects] = {}
    for mod in index.modules.values():
        for fi in mod.functions.values():
            out[fi.qualname] = _EffectWalker(
                mod, fi, index, shared, locks, mtypes
            ).run()
    return out
