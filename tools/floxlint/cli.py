"""Command-line entry: ``python -m tools.floxlint flox_tpu/``.

Exit codes: 0 clean, 1 findings, 2 usage/driver error."""

from __future__ import annotations

import argparse
import sys
from typing import Sequence

from .core import LintError, iter_python_files, lint_file
from .core import _SuppressionIndex  # driver-internal, shared across files
from .registry import RULES, get_rules
from .reporting import format_human, format_json


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="floxlint",
        description="JAX-hazard static analysis for flox_tpu (FLX001-FLX005).",
    )
    parser.add_argument("paths", nargs="*", help="files or directories to lint")
    parser.add_argument(
        "--format", choices=("human", "json"), default="human", help="output format"
    )
    parser.add_argument(
        "--select", help="comma-separated rule ids to run (default: all)"
    )
    parser.add_argument("--ignore", help="comma-separated rule ids to skip")
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table and exit"
    )
    return parser


def main(argv: Sequence[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule_id, rule in sorted(RULES.items()):
            print(f"{rule_id}  {rule.name}\n       {rule.description}")
        return 0
    if not args.paths:
        print("floxlint: no paths given (try: python -m tools.floxlint flox_tpu/)", file=sys.stderr)
        return 2
    try:
        rules = get_rules(
            args.select.split(",") if args.select else None,
            args.ignore.split(",") if args.ignore else None,
        )
    except KeyError as exc:
        print(f"floxlint: {exc.args[0]}", file=sys.stderr)
        return 2
    index = _SuppressionIndex()
    findings = set()
    files_checked = 0
    try:
        for path, root in iter_python_files(args.paths):
            files_checked += 1
            findings.update(lint_file(path, rules, root=root, _index=index))
    except LintError as exc:
        print(f"floxlint: {exc}", file=sys.stderr)
        return 2
    ordered = sorted(findings)
    formatter = format_json if args.format == "json" else format_human
    print(formatter(ordered, files_checked=files_checked))
    return 1 if ordered else 0
