"""Command-line entry: ``python -m tools.floxlint flox_tpu/ tools/``.

Exit codes: 0 clean, 1 findings (new findings, or stale baseline entries —
baseline drift), 2 usage/driver error."""

from __future__ import annotations

import argparse
import sys
from typing import Sequence

from .core import LintError, lint_run
from .registry import RULES, explain_rule, get_rules, rule_id_range


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="floxlint",
        # derived from the registry so the blurb can never lag a new rule
        description=f"JAX-hazard static analysis for flox_tpu ({rule_id_range()}).",
    )
    parser.add_argument("paths", nargs="*", help="files or directories to lint")
    parser.add_argument(
        "--format",
        choices=("human", "json", "sarif"),
        default="human",
        help="output format (sarif emits a SARIF 2.1.0 log for code scanning)",
    )
    parser.add_argument(
        "--select", help="comma-separated rule ids to run (default: all)"
    )
    parser.add_argument("--ignore", help="comma-separated rule ids to skip")
    parser.add_argument(
        "--baseline",
        metavar="FILE",
        help=(
            "suppression baseline: known findings recorded in FILE are not "
            "reported; entries whose finding no longer fires are baseline "
            "drift and fail the run"
        ),
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="write the current findings to --baseline and exit 0",
    )
    parser.add_argument(
        "--fix",
        action="store_true",
        help=(
            "apply autofixes for the mechanical rules (FLX007 eager logging "
            "-> lazy %%-args, FLX004 version-gate wrapping), then re-lint"
        ),
    )
    parser.add_argument(
        "--index-cache",
        metavar="FILE",
        help=(
            "pickle the project index here and reuse it while the tree is "
            "byte-identical (CI shares it between lint steps)"
        ),
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table and exit"
    )
    parser.add_argument(
        "--explain",
        metavar="FLXnnn",
        help=(
            "print one rule's documentation, example finding, and fix "
            "pattern (from the registry, so it cannot drift) and exit"
        ),
    )
    parser.add_argument(
        "--contract",
        metavar="FILE",
        help=(
            "compile the machine-readable serve/telemetry contract (ops, "
            "error codes, endpoints, metrics, knobs) over the given paths "
            "(default: flox_tpu/) to FILE as schema-validated JSON ('-' for "
            "stdout) and exit — the artifact CI publishes next to the SARIF "
            "upload and the conformance harness replays"
        ),
    )
    parser.add_argument(
        "--lock-graph",
        metavar="FILE",
        help=(
            "write the computed lock-acquisition-order graph over the given "
            "paths to FILE (.dot for graphviz, anything else as JSON; '-' "
            "for stdout) and exit — the review artifact PRs diff when they "
            "add locks"
        ),
    )
    return parser


def main(argv: Sequence[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule_id, rule in sorted(RULES.items()):
            print(f"{rule_id}  {rule.name}\n       {rule.description}")
        return 0
    if args.explain:
        try:
            print(explain_rule(args.explain), end="")
        except KeyError as exc:
            print(f"floxlint: {exc.args[0]}", file=sys.stderr)
            return 2
        return 0
    if args.lock_graph:
        return _emit_lock_graph(args.paths, args.lock_graph)
    if args.contract:
        return _emit_contract(args.paths, args.contract)
    if not args.paths:
        print("floxlint: no paths given (try: python -m tools.floxlint flox_tpu/)", file=sys.stderr)
        return 2
    if args.update_baseline and not args.baseline:
        print("floxlint: --update-baseline requires --baseline FILE", file=sys.stderr)
        return 2
    try:
        rules = get_rules(
            args.select.split(",") if args.select else None,
            args.ignore.split(",") if args.ignore else None,
        )
    except KeyError as exc:
        print(f"floxlint: {exc.args[0]}", file=sys.stderr)
        return 2
    try:
        findings, files_checked = lint_run(
            args.paths, rules, index_cache=args.index_cache
        )
        if args.fix:
            from .autofix import FIXABLE_RULES, fix_paths

            fixable_paths = {f.path for f in findings if f.rule in FIXABLE_RULES}
            fixed = fix_paths(sorted(fixable_paths))
            if fixed:
                total = sum(fixed.values())
                print(
                    f"floxlint: fixed {total} finding(s) in {len(fixed)} file(s)",
                    file=sys.stderr,
                )
                findings, files_checked = lint_run(
                    args.paths, rules, index_cache=args.index_cache
                )
    except LintError as exc:
        print(f"floxlint: {exc}", file=sys.stderr)
        return 2

    stale: list[dict] = []
    if args.baseline:
        from .baseline import apply_baseline, load_baseline, write_baseline

        if args.update_baseline:
            n = write_baseline(args.baseline, findings)
            print(
                f"floxlint: baseline {args.baseline} updated with {n} entry(ies) "
                f"covering {len(findings)} finding(s)",
                file=sys.stderr,
            )
            return 0
        try:
            entries = load_baseline(args.baseline)
        except LintError as exc:
            print(f"floxlint: {exc}", file=sys.stderr)
            return 2
        findings, stale = apply_baseline(findings, entries)
        for entry in stale:
            print(
                "floxlint: baseline drift: "
                f"{entry.get('path')}: {entry.get('rule')} fires fewer times "
                f"than baselined — shrink or remove the entry in {args.baseline}",
                file=sys.stderr,
            )

    if args.format == "sarif":
        from .sarif import format_sarif

        print(format_sarif(findings, rules, files_checked=files_checked))
    elif args.format == "json":
        from .reporting import format_json

        print(format_json(findings, files_checked=files_checked))
    else:
        from .reporting import format_human

        print(format_human(findings, files_checked=files_checked))
    return 1 if findings or stale else 0


def _emit_contract(paths: Sequence[str], out: str) -> int:
    """``--contract FILE``: compile the serve/telemetry contract over the
    given paths (default: the flox_tpu package) and write it as canonical
    JSON. The emitted artifact is schema-checked before writing — a
    contract the compiler itself cannot validate never ships."""
    from .contract import contract_for_paths, render_contract, validate_contract

    try:
        doc = contract_for_paths(list(paths) or ["flox_tpu"])
    except (LintError, ValueError) as exc:
        sys.stderr.write(f"floxlint: {exc}\n")
        return 2
    problems = validate_contract(doc)
    if problems:
        for p in problems:
            sys.stderr.write(f"floxlint: contract schema: {p}\n")
        return 2
    payload = render_contract(doc)
    if out == "-":
        sys.stdout.write(payload)
    else:
        with open(out, "w") as fh:
            fh.write(payload)
    sys.stderr.write(
        "floxlint: contract: "
        f"{len(doc['ops'])} op(s), {len(doc['errors'])} error code(s), "
        f"{sum(len(p) for p in doc['endpoints'].values())} endpoint(s), "
        f"{len(doc['metrics'])} metric(s), {len(doc['knobs'])} knob(s)"
        + ("" if out == "-" else f" -> {out}")
        + "\n"
    )
    return 0


def _emit_lock_graph(paths: Sequence[str], out: str) -> int:
    """``--lock-graph FILE``: compute the acquisition-order graph over the
    given paths and write it as dot (``*.dot``) or JSON."""
    import json

    from .concurrency import lock_graph_for_paths

    if not paths:
        sys.stderr.write(
            "floxlint: --lock-graph needs paths to analyze "
            "(try: python -m tools.floxlint --lock-graph out.json flox_tpu/)\n"
        )
        return 2
    try:
        graph = lock_graph_for_paths(paths)
    except LintError as exc:
        sys.stderr.write(f"floxlint: {exc}\n")
        return 2
    payload = (
        graph.to_dot() if out.endswith(".dot") else json.dumps(graph.to_json(), indent=2) + "\n"
    )
    if out == "-":
        sys.stdout.write(payload)
    else:
        with open(out, "w") as fh:
            fh.write(payload)
    cycles = graph.cycles()
    sys.stderr.write(
        f"floxlint: lock-order graph: {len(graph.nodes)} lock(s), "
        f"{len(graph.edges)} edge(s), {len(cycles)} cycle(s)"
        + ("" if out == "-" else f" -> {out}")
        + "\n"
    )
    return 0
