"""Rule registry: the single list the CLI, the tests, and the docs share."""

from __future__ import annotations

from .rules.flx001_host_sync import HostSyncRule
from .rules.flx002_recompile import RecompileTrapRule
from .rules.flx003_dtype import DtypePolicyRule
from .rules.flx004_version import VersionGatedApiRule
from .rules.flx005_api import UntypedPublicApiRule
from .rules.flx006_swallow import SwallowedRetryExceptionRule
from .rules.flx007_logging import EagerLoggingRule
from .rules.flx008_cache_registry import CacheRegistryRule
from .rules.flx009_donation import DonationAfterUseRule
from .rules.flx010_options_drift import OptionsEnvDriftRule
from .rules.flx011_helper_sync import HelperHostSyncRule
from .rules.flx012_serve_except import ServeBroadExceptRule
from .rules.flx013_unlocked_shared_write import UnlockedSharedWriteRule
from .rules.flx014_lock_order import LockOrderInversionRule
from .rules.flx015_async_blocking import AsyncBlockingRule
from .rules.flx016_signal_unsafe import SignalUnsafeRule
from .rules.flx017_contract_docs import ContractDocsDriftRule
from .rules.flx018_metric_drift import MetricDriftRule
from .rules.flx019_response_shape import ResponseShapeDriftRule
from .rules.flx020_untyped_escape import UntypedEscapeRule

#: id -> rule instance, in id order
RULES = {
    rule.id: rule
    for rule in (
        HostSyncRule(),
        RecompileTrapRule(),
        DtypePolicyRule(),
        VersionGatedApiRule(),
        UntypedPublicApiRule(),
        SwallowedRetryExceptionRule(),
        EagerLoggingRule(),
        CacheRegistryRule(),
        DonationAfterUseRule(),
        OptionsEnvDriftRule(),
        HelperHostSyncRule(),
        ServeBroadExceptRule(),
        UnlockedSharedWriteRule(),
        LockOrderInversionRule(),
        AsyncBlockingRule(),
        SignalUnsafeRule(),
        ContractDocsDriftRule(),
        MetricDriftRule(),
        ResponseShapeDriftRule(),
        UntypedEscapeRule(),
    )
}


def explain_rule(rule_id: str) -> str:
    """The ``--explain`` payload for one rule, assembled from the registry
    itself (id, name, one-line description, the rule module's docstring,
    and — where the rule carries them — an example finding and fix
    pattern), so the explanation can never drift from the implementation."""
    rule = RULES.get(rule_id.upper())
    if rule is None:
        raise KeyError(
            f"unknown rule id: {rule_id} (known: {rule_id_range()})"
        )
    import inspect
    import sys

    lines = [f"{rule.id} — {rule.name}", "", rule.description, ""]
    doc = inspect.getdoc(sys.modules[type(rule).__module__])
    if doc:
        lines += [doc.strip(), ""]
    example = getattr(rule, "example", None)
    if example:
        lines += ["Example finding:", "", _indent(example), ""]
    fix_hint = getattr(rule, "fix_hint", None)
    if fix_hint:
        lines += ["Fix pattern:", "", _indent(fix_hint), ""]
    return "\n".join(lines).rstrip() + "\n"


def _indent(text: str) -> str:
    return "\n".join(f"    {line}" for line in text.splitlines())


def rule_id_range() -> str:
    """Human-readable id span ("FLX001-FLX011"), derived — never hardcoded —
    so the CLI description can't drift from the registry."""
    ids = sorted(RULES)
    return f"{ids[0]}-{ids[-1]}" if len(ids) > 1 else ids[0]


def get_rules(select: list[str] | None = None, ignore: list[str] | None = None) -> list:
    """Resolve ``--select`` / ``--ignore`` id lists to rule instances."""
    chosen = dict(RULES)
    if select:
        unknown = [r for r in select if r.upper() not in RULES]
        if unknown:
            raise KeyError(f"unknown rule id(s): {', '.join(unknown)}")
        chosen = {r.upper(): RULES[r.upper()] for r in select}
    for r in ignore or ():
        chosen.pop(r.upper(), None)
    return list(chosen.values())
