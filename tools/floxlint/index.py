"""Project index: the whole lint tree parsed once.

Where the per-file rules (FLX001–FLX007) see one ``ast.Module`` at a time,
the semantic rules (FLX008–FLX011) need whole-program facts: which module
defines ``clear_all``, what ``from .pipeline import maybe_donate`` resolves
to, which helper a traced function is really calling. :class:`ProjectIndex`
parses every file under a lint root once and exposes

* a module table (dotted name -> :class:`ModuleInfo` with source, tree,
  imports, top-level definitions),
* a symbol table with resolved imports — ``from x import y as z`` and
  package re-exports are followed to the defining module, and
* per-function records (:class:`FunctionInfo`) the call graph builds on.

The index is pure AST — nothing is imported — so it is safe to build over
fixture corpora that would crash at import time. It pickles cleanly;
:func:`load_cached` / :func:`save_cache` give the CLI's ``--index-cache``
a content-hashed reuse path so CI builds the index once per tree state.
"""

from __future__ import annotations

import ast
import hashlib
import pickle
from dataclasses import dataclass, field
from pathlib import Path
from typing import Sequence

from .rules.common import ImportMap


def module_name_for(path: Path) -> str:
    """Dotted module name, derived from the filesystem package structure
    (climb while ``__init__.py`` exists). Loose files resolve to their stem."""
    path = path.resolve()
    parts: list[str] = [] if path.name == "__init__.py" else [path.stem]
    d = path.parent
    while (d / "__init__.py").exists() and d.name:
        parts.insert(0, d.name)
        parent = d.parent
        if parent == d:
            break
        d = parent
    return ".".join(parts) if parts else path.stem


@dataclass
class FunctionInfo:
    """One function or method definition, addressable by canonical name."""

    qualname: str  #: canonical, e.g. "flox_tpu.cache.clear_all" / "mod.Cls.fn"
    name: str
    module: str
    path: Path
    node: ast.FunctionDef | ast.AsyncFunctionDef


#: import-alias kinds: a name bound to a module vs to a symbol in a module
_MODULE, _SYMBOL = "module", "symbol"


@dataclass
class ModuleInfo:
    name: str
    path: Path
    source: str
    tree: ast.Module
    #: the per-file alias map the file rules already use (absolute imports)
    imports: ImportMap
    #: canonical-name -> function/method defined here (any nesting level)
    functions: dict[str, FunctionInfo] = field(default_factory=dict)
    #: top-level names defined here (functions, classes, assignments)
    definitions: dict[str, ast.AST] = field(default_factory=dict)
    #: local alias -> (kind, target module, original symbol name or "")
    aliases: dict[str, tuple[str, str, str]] = field(default_factory=dict)

    @property
    def package(self) -> str:
        """Top-level package component ("flox_tpu" for flox_tpu.cache)."""
        return self.name.partition(".")[0]


class ProjectIndex:
    """Symbol-resolved view of every module under one lint root."""

    def __init__(self, root: Path) -> None:
        self.root = root
        self.modules: dict[str, ModuleInfo] = {}
        self.by_path: dict[str, ModuleInfo] = {}

    # -- construction -------------------------------------------------------

    @classmethod
    def build(cls, files: Sequence[Path], root: Path) -> "ProjectIndex":
        index = cls(Path(root))
        for path in sorted(set(Path(f) for f in files)):
            try:
                source = path.read_text()
                tree = ast.parse(source, filename=str(path))
            except (OSError, SyntaxError):
                continue  # the driver reports these as FLX000 per file
            index._add_module(path, source, tree)
        # aliases resolve against the full module table, so second pass
        for mod in index.modules.values():
            index._collect_aliases(mod)
        return index

    def _add_module(self, path: Path, source: str, tree: ast.Module) -> None:
        name = module_name_for(path)
        mod = ModuleInfo(
            name=name, path=path, source=source, tree=tree,
            imports=ImportMap.from_tree(tree),
        )
        self._collect_definitions(mod)
        self._collect_functions(mod)
        self.modules[name] = mod
        self.by_path[str(path)] = mod

    def _collect_definitions(self, mod: ModuleInfo) -> None:
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                mod.definitions[node.name] = node
            elif isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        mod.definitions[target.id] = node
            elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                mod.definitions[node.target.id] = node

    def _collect_aliases(self, mod: ModuleInfo) -> None:
        """Alias table covering relative imports and function-local imports
        (``clear_all`` imports its caches inside its own body)."""
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.partition(".")[0]
                    target = a.name if a.asname else a.name.partition(".")[0]
                    mod.aliases.setdefault(local, (_MODULE, target, ""))
            elif isinstance(node, ast.ImportFrom):
                base = self._import_base(mod, node)
                if base is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    candidate = f"{base}.{a.name}"
                    if self._is_known_module(candidate):
                        mod.aliases.setdefault(local, (_MODULE, candidate, ""))
                    else:
                        mod.aliases.setdefault(local, (_SYMBOL, base, a.name))

    def _is_known_module(self, dotted: str) -> bool:
        if dotted in self.modules:
            return True
        # modules outside the lint set but inside the source tree (a single
        # linted file importing a sibling) resolve via the filesystem
        rel = Path(*dotted.split("."))
        for base in (self.root, self.root.parent):
            if (base / rel).with_suffix(".py").exists():
                return True
            if (base / rel / "__init__.py").exists():
                return True
        return False

    @staticmethod
    def _import_base(mod: ModuleInfo, node: ast.ImportFrom) -> str | None:
        """Absolute module path an ImportFrom pulls from (relative imports
        resolved against the importing module's package)."""
        if node.level == 0:
            return node.module
        parts = mod.name.split(".")
        if mod.path.name != "__init__.py":
            parts = parts[:-1]  # a plain module's package drops the leaf
        climb = node.level - 1  # level 1 = current package
        if climb > len(parts):
            return None
        if climb:
            parts = parts[:-climb]
        if node.module:
            parts = parts + node.module.split(".")
        return ".".join(parts) or None

    def _collect_functions(self, mod: ModuleInfo) -> None:
        def visit(node: ast.AST, stack: list[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = ".".join([mod.name, *stack, child.name])
                    mod.functions[qual] = FunctionInfo(
                        qualname=qual, name=child.name, module=mod.name,
                        path=mod.path, node=child,
                    )
                    visit(child, stack + [child.name])
                elif isinstance(child, ast.ClassDef):
                    visit(child, stack + [child.name])
                else:
                    visit(child, stack)

        visit(mod.tree, [])

    # -- resolution ---------------------------------------------------------

    def function(self, canonical: str) -> FunctionInfo | None:
        for mod in self.modules.values():
            fi = mod.functions.get(canonical)
            if fi is not None:
                return fi
        return None

    def resolve_symbol(
        self, module: str, dotted: str, _seen: frozenset[tuple[str, str]] = frozenset()
    ) -> str | None:
        """Canonical "defining_module.symbol" for a dotted name as written in
        ``module``; follows from-import chains (package re-exports) to the
        definition site. None for names outside the project (jax, numpy,
        builtins)."""
        mod = self.modules.get(module)
        if mod is None:
            return None
        head, _, rest = dotted.partition(".")
        if (module, head) in _seen:
            return None
        if head in mod.definitions:
            return f"{module}.{dotted}" if rest else f"{module}.{head}"
        alias = mod.aliases.get(head)
        if alias is None:
            return None
        kind, target, orig = alias
        if kind == _MODULE:
            if not rest:
                return target if target in self.modules else None
            if target in self.modules:
                return self.resolve_symbol(target, rest, _seen | {(module, head)})
            return None
        resolved = self.resolve_symbol(target, orig, _seen | {(module, head)})
        if resolved is None:
            return None
        return f"{resolved}.{rest}" if rest else resolved


# -- pickle cache (--index-cache / CI reuse) --------------------------------


def tree_fingerprint(files: Sequence[Path]) -> str:
    """Content hash over the sorted file set — any edit invalidates it."""
    h = hashlib.sha256()
    for path in sorted(set(Path(f) for f in files)):
        h.update(str(path).encode())
        try:
            h.update(path.read_bytes())
        except OSError:
            h.update(b"<unreadable>")
    return h.hexdigest()


def load_cached(
    cache_path: Path, files: Sequence[Path], root: Path
) -> ProjectIndex | None:
    """Cached index for ``root`` if the tree is byte-identical, else None."""
    try:
        with open(cache_path, "rb") as f:
            payload = pickle.load(f)
    except (OSError, pickle.UnpicklingError, EOFError, AttributeError, ImportError):
        return None
    entry = payload.get(str(root)) if isinstance(payload, dict) else None
    if not entry or entry.get("fingerprint") != tree_fingerprint(files):
        return None
    index = entry.get("index")
    return index if isinstance(index, ProjectIndex) else None


def save_cache(cache_path: Path, index: ProjectIndex, files: Sequence[Path]) -> None:
    """Merge this root's index into the cache file (best-effort: an
    unwritable cache never fails the lint)."""
    payload: dict = {}
    try:
        with open(cache_path, "rb") as f:
            existing = pickle.load(f)
        if isinstance(existing, dict):
            payload = existing
    except (OSError, pickle.UnpicklingError, EOFError, AttributeError, ImportError):
        pass
    payload[str(index.root)] = {
        "fingerprint": tree_fingerprint(files),
        "index": index,
    }
    try:
        with open(cache_path, "wb") as f:
            pickle.dump(payload, f)
    except OSError:
        pass
