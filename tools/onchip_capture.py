"""Opportunistic on-chip evidence capture (VERDICT r3 #1).

The TPU behind the axon tunnel flaps; rounds 1-3 only probed at capture
time and never caught it up, so no hardware artifact was ever committed.
This tool inverts that: run it in the background for the WHOLE session
(``--loop``); every cycle it probes device init in a subprocess (a wedged
tunnel blocks forever in C), and the moment the chip answers it

  1. runs the full ``bench.py`` sweep — which persists
     ``BENCH_TPU_LAST.json`` (impl_sweep_gbps, quantile_gbps) by itself;
  2. runs ``tests_tpu/`` on the hardware and writes
     ``TESTS_TPU_LAST.json`` {commit, timestamp_utc, passed, failed,
     skipped, duration_s};
  3. runs the on-chip accuracy certification (``bench_accuracy.py
     --json``) and writes ``ACCURACY_TPU_LAST.json``;

then exits 0 so the driver/operator can commit the artifacts. Exits 1
only if the deadline passes with the chip never reachable.

Usage:
    python tools/onchip_capture.py --loop [--interval 300] [--deadline-h 11]
    python tools/onchip_capture.py          # single probe+capture attempt
"""

from __future__ import annotations

import argparse
import calendar
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, ".onchip_capture.log")
sys.path.insert(0, REPO)

from bench import _probe_once  # noqa: E402 — single probe implementation


def log(msg: str) -> None:
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    line = f"[{stamp}] {msg}"
    # the capture tool IS a CLI: stdout is its live progress channel,
    # mirrored to the logfile below
    print(line, flush=True)  # floxlint: disable=FLX007
    try:
        with open(LOG, "a") as f:
            f.write(line + "\n")
    except OSError:
        pass


def probe(timeout_s: float = 75.0) -> bool:
    """True iff a non-CPU jax device initializes within the timeout."""
    return _probe_once(
        "import jax; assert jax.devices()[0].platform != 'cpu'", timeout_s
    )


def _head_sha() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=REPO, capture_output=True,
            text=True, timeout=10,
        ).stdout.strip()
    except Exception:
        return "unknown"


def run_bench(timeout_s: float = 3600.0) -> bool:
    """Full sweep; bench.py persists BENCH_TPU_LAST.json itself on accel."""
    log("bench: starting full on-chip sweep")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")], cwd=REPO,
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        log("bench: TIMED OUT")
        return False
    tail = (proc.stderr or "").strip().splitlines()[-3:]
    log(f"bench: rc={proc.returncode} stderr_tail={tail}")
    if proc.stdout.strip():
        log(f"bench: stdout={proc.stdout.strip().splitlines()[-1]}")
    # success = the persisted record is fresh (bench may have fallen back
    # to CPU if the tunnel dropped between probe and run)
    try:
        with open(os.path.join(REPO, "BENCH_TPU_LAST.json")) as f:
            rec = json.load(f)
        fresh = time.time() - calendar.timegm(
            time.strptime(rec["timestamp_utc"], "%Y-%m-%dT%H:%M:%SZ")
        ) < timeout_s + 600
        log(f"bench: BENCH_TPU_LAST.json platform={rec.get('platform')} "
            f"fresh={fresh}")
        return fresh
    except (OSError, ValueError, KeyError):
        log("bench: no BENCH_TPU_LAST.json written — run was not on-chip")
        return False


def run_tests_tpu(timeout_s: float = 3600.0) -> bool:
    log("tests_tpu: starting hardware run")
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "tests_tpu/", "-q",
             "--tb=line", "-p", "no:cacheprovider"],
            cwd=REPO, capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        log("tests_tpu: TIMED OUT")
        return False
    out = proc.stdout + proc.stderr
    counts = {k: 0 for k in ("passed", "failed", "skipped", "error")}
    for n, word in re.findall(r"(\d+) (passed|failed|skipped|error)", out):
        counts[word] = int(n)
    summary_tail = out.strip().splitlines()[-5:]
    log(f"tests_tpu: rc={proc.returncode} counts={counts}")
    if counts["passed"] == 0:
        # all-skipped means the probe raced a tunnel drop — not evidence
        log(f"tests_tpu: no tests ran on hardware; tail={summary_tail}")
        return False
    record = {
        "commit": _head_sha(),
        "timestamp_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "duration_s": round(time.time() - t0, 1),
        "returncode": proc.returncode,
        **counts,
        "tail": summary_tail,
    }
    with open(os.path.join(REPO, "TESTS_TPU_LAST.json"), "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    log(f"tests_tpu: wrote TESTS_TPU_LAST.json ({counts['passed']} passed, "
        f"{counts['failed']} failed)")
    return proc.returncode == 0 and counts["failed"] == 0


def run_accuracy(timeout_s: float = 1800.0) -> bool:
    script = os.path.join(REPO, "bench_accuracy.py")
    if not os.path.exists(script):
        log("accuracy: bench_accuracy.py not present yet; skipping")
        return True
    log("accuracy: starting on-chip error certification")
    try:
        proc = subprocess.run(
            [sys.executable, script, "--json"], cwd=REPO,
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        log("accuracy: TIMED OUT")
        return False
    if proc.returncode != 0:
        log(f"accuracy: rc={proc.returncode} "
            f"tail={(proc.stderr or '').strip().splitlines()[-3:]}")
        return False
    try:
        rec = json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        log("accuracy: unparseable output")
        return False
    if rec.get("platform") == "cpu":
        # bench_accuracy's own probe lost the tunnel and fell back — an
        # interpret-mode run must never be persisted as the hardware
        # certificate
        log("accuracy: run fell back to CPU; not persisting as on-chip")
        return False
    rec["commit"] = _head_sha()
    with open(os.path.join(REPO, "ACCURACY_TPU_LAST.json"), "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    log("accuracy: wrote ACCURACY_TPU_LAST.json")
    return True


def run_history_sweep(timeout_s: float = 3600.0) -> bool:
    """Best-effort: record the asv-workload sweep as the round's TPU
    history leg, activating the [tpu] regression gate for later rounds.
    Never raises — a crash here must not kill the --loop supervisor."""
    try:
        return _run_history_sweep(timeout_s)
    except Exception as exc:  # noqa: BLE001 — best-effort step
        log(f"history: failed: {type(exc).__name__}: {exc}")
        return False


def _current_round() -> int:
    """The driver commits BENCH_r{N}.json at the END of round N, so during
    round N the newest such file is N-1 — infer from that, never from the
    round's own (possibly not-yet-recorded) history files."""
    import glob

    rounds = []
    for p in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.match(r".*BENCH_r(\d+)\.json$", p)
        if m:
            rounds.append(int(m.group(1)))
    return max(rounds, default=0) + 1


def _run_history_sweep(timeout_s: float) -> bool:
    n = _current_round()
    out_path = os.path.join(REPO, "BENCH_HISTORY", f"r{n:02d}_tpu.jsonl")
    log(f"history: recording TPU sweep to {os.path.basename(out_path)}")
    try:
        # --engine both: the regression gate's sensitive tier is the
        # jax-vs-numpy quotient WITHIN one record (BENCH_HISTORY/README.md)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "benchmarks.py"),
             "--scale", "full", "--engine", "both"],
            cwd=REPO, capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        log("history: TIMED OUT")
        return False
    rows = []
    for ln in proc.stdout.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            rows.append(json.loads(ln))
        except ValueError:
            continue
    platform = next(
        (r.get("value") for r in rows if r.get("bench") == "platform"), None
    )
    if proc.returncode != 0 or len(rows) < 5:
        log(f"history: rc={proc.returncode} rows={len(rows)}; not recorded")
        return False
    if platform in (None, "cpu"):
        # the tunnel dropped between probe and run: CPU timings must never
        # be persisted as the TPU history leg (same guard as run_accuracy)
        log(f"history: sweep ran on {platform!r}, not hardware; not recorded")
        return False
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        # record the backend the sweep ACTUALLY ran on, not an assumption
        f.write(json.dumps(
            {"bench": "platform", "value": platform, "unit": "config"}
        ) + "\n")
        for rec in rows:
            if rec.get("bench") != "platform":
                f.write(json.dumps(rec) + "\n")
    os.replace(tmp, out_path)
    log(f"history: wrote {os.path.basename(out_path)} ({len(rows)} rows, "
        f"backend {platform})")
    return True


_DONE: dict = {}  # per-step success across retry cycles


_PROFILE_SNIPPET = r"""
import json, os, time
import numpy as np
import flox_tpu
from flox_tpu import costmodel, profiling
from flox_tpu.core import groupby_reduce

with flox_tpu.set_options(
    telemetry=True, costmodel=True, profile_dir=os.environ["FLOX_PROFILE_OUT"]
):
    cap = profiling.start_capture(seconds=3.0)
    vals = np.random.default_rng(0).normal(size=(256, 4096)).astype("float32")
    codes = np.arange(4096) % 12
    np.asarray(groupby_reduce(vals, codes, func="nanmean")[0])
    time.sleep(4.0)  # past the capture window so the stop+stamp ran
    stamp = os.path.join(cap, "programs.json")
    payload = {"capture": cap, "stamped": os.path.exists(stamp)}
    if payload["stamped"]:
        payload["programs"] = json.load(open(stamp))["programs"]
    print(json.dumps(payload))
"""


def run_profile(timeout_s: float = 600.0) -> bool:
    """Short stamped profiler capture into ``PROFILE_TPU_LAST/``: the
    capture dir's ``programs.json`` carries the program labels + card
    digests dispatched inside the window (costmodel.stamp_capture), so the
    committed xprof evidence is joinable back to /debug/costs and
    /debug/programs rows — the capture-runbook contract."""
    log("profile: starting stamped on-chip capture")
    out_dir = os.path.join(REPO, "PROFILE_TPU_LAST")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROFILE_SNIPPET], cwd=REPO,
            env={**os.environ, "FLOX_PROFILE_OUT": out_dir},
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        log("profile: TIMED OUT")
        return False
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        log(f"profile: rc={proc.returncode} stderr_tail={tail}")
        return False
    try:
        rec = json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        log("profile: no stamped-capture record on stdout")
        return False
    log(
        f"profile: capture={rec.get('capture')} stamped={rec.get('stamped')} "
        f"programs={sorted(rec.get('programs') or {})}"
    )
    return bool(rec.get("stamped"))


def capture_once() -> bool:
    """One full capture attempt. True iff bench AND tests evidence landed.
    Steps that already succeeded this session are not re-run on retries —
    tunnel-up time is scarce and each sweep costs up to an hour."""
    for name, fn in (
        ("bench", run_bench),
        ("tests", run_tests_tpu),
        ("accuracy", run_accuracy),
        ("history", run_history_sweep),
        ("profile", run_profile),
    ):
        if _DONE.get(name):
            log(f"{name}: already captured this session; skipping")
            continue
        _DONE[name] = fn()
    return bool(_DONE.get("bench") and _DONE.get("tests"))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--loop", action="store_true")
    ap.add_argument("--interval", type=float, default=300.0)
    ap.add_argument("--deadline-h", type=float, default=11.0)
    args = ap.parse_args()

    deadline = time.time() + args.deadline_h * 3600
    attempt = 0
    while True:
        attempt += 1
        if probe():
            log(f"probe #{attempt}: accelerator UP — capturing")
            if capture_once():
                log("capture complete: on-chip artifacts written; exiting")
                return 0
            log("capture incomplete; will retry next cycle")
        else:
            log(f"probe #{attempt}: accelerator unreachable")
        if not args.loop or time.time() > deadline:
            break
        time.sleep(args.interval)
    log("deadline passed with no complete capture")
    return 1


if __name__ == "__main__":
    sys.exit(main())
