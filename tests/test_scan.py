"""groupby_scan tests vs per-group numpy oracles (reference:
test_properties.py:227-287 scans-vs-loop invariants + scan.py behavior)."""

import numpy as np
import pytest

from flox_tpu.scan import groupby_scan

RNG = np.random.default_rng(11)


def oracle_scan(func, values, codes):
    out = np.full(values.shape, np.nan, dtype=np.float64)
    for g in np.unique(codes[codes >= 0]):
        sel = codes == g
        seg = values[..., sel].astype(np.float64)
        if func == "cumsum":
            res = np.cumsum(seg, axis=-1)
        elif func == "nancumsum":
            res = np.nancumsum(seg, axis=-1)
        elif func in ("ffill", "bfill"):
            s = seg if func == "ffill" else seg[..., ::-1]
            res = np.copy(s)
            for idx in np.ndindex(s.shape[:-1]):
                last = np.nan
                for i in range(s.shape[-1]):
                    if np.isnan(res[idx + (i,)]):
                        res[idx + (i,)] = last
                    else:
                        last = res[idx + (i,)]
            if func == "bfill":
                res = res[..., ::-1]
        out[..., sel] = res
    return out


@pytest.mark.parametrize("func", ["cumsum", "nancumsum", "ffill", "bfill"])
@pytest.mark.parametrize("shape", ["1d", "2d"])
@pytest.mark.parametrize("add_nan", [False, True])
def test_groupby_scan(engine, func, shape, add_nan):
    n, size = 50, 4
    codes = RNG.integers(0, size, n)
    values = np.round(RNG.normal(size=(3, n) if shape == "2d" else (n,)), 1)
    if add_nan:
        values[..., RNG.random(n) < 0.3] = np.nan
    out = np.asarray(groupby_scan(values, codes, func=func, engine=engine))
    expected = oracle_scan(func, values, codes)
    np.testing.assert_allclose(out, expected, rtol=1e-12, atol=1e-12, equal_nan=True)


def test_scan_nan_labels(engine):
    codes = np.array([0.0, np.nan, 0.0])
    values = np.array([1.0, 2.0, 3.0])
    out = np.asarray(groupby_scan(values, codes, func="cumsum", engine=engine))
    np.testing.assert_allclose(out, [1.0, np.nan, 4.0], equal_nan=True)


def test_scan_axis(engine):
    # scan along axis 0 (not the last): labels span both dims
    codes = np.array([[0, 1, 0], [0, 1, 0]])
    values = np.arange(6.0).reshape(2, 3)
    out = np.asarray(groupby_scan(values, codes, func="cumsum", engine=engine, axis=0))
    np.testing.assert_allclose(out, [[0, 1, 2], [3, 5, 7]])


def test_scan_int_promotion(engine):
    codes = np.array([0, 0, 0])
    values = np.array([1, 2, 3], dtype=np.int32)
    out = groupby_scan(values, codes, func="cumsum", engine=engine)
    assert np.asarray(out).dtype.kind == "i"
    np.testing.assert_array_equal(np.asarray(out), [1, 3, 6])


def test_scan_2d_labels(engine):
    # labels vary over both dims; scan along the last axis per row
    codes = np.array([[0, 0, 1], [1, 0, 1]])
    values = np.arange(6.0).reshape(2, 3)
    out = np.asarray(groupby_scan(values, codes, func="cumsum", engine=engine))
    np.testing.assert_allclose(out, [[0, 1, 2], [3, 4, 8]])


def test_ffill_bfill_reversal(engine):
    # bfill(x) == reverse(ffill(reverse(x))) (reference test_properties.py:269-287)
    codes = RNG.integers(0, 3, 30)
    values = np.round(RNG.normal(size=30), 1)
    values[RNG.random(30) < 0.4] = np.nan
    b = np.asarray(groupby_scan(values, codes, func="bfill", engine=engine))
    f_rev = np.asarray(groupby_scan(values[::-1], codes[::-1], func="ffill", engine=engine))[::-1]
    np.testing.assert_allclose(b, f_rev, equal_nan=True)


class TestScanMethodSelection:
    """_choose_scan_method parity (reference scan.py:48-78) + the mesh
    blockwise scan (VERDICT #6)."""

    def _mesh(self):
        from flox_tpu.parallel import make_mesh

        return make_mesh(8)

    def test_auto_blockwise_when_shard_local(self):
        from flox_tpu import groupby_scan

        n = 96
        vals = np.random.default_rng(3).normal(size=n)
        labels = np.arange(n) // 12  # one group per shard
        out_mesh = groupby_scan(vals, labels, func="nancumsum", mesh=self._mesh())
        out_eager = groupby_scan(vals, labels, func="nancumsum")
        np.testing.assert_allclose(
            np.asarray(out_mesh), np.asarray(out_eager), rtol=1e-12, equal_nan=True
        )

    def test_auto_blelloch_when_spread(self):
        from flox_tpu import groupby_scan

        n = 96
        vals = np.random.default_rng(4).normal(size=n)
        labels = np.arange(n) % 5
        out_mesh = groupby_scan(vals, labels, func="cumsum", mesh=self._mesh())
        out_eager = groupby_scan(vals, labels, func="cumsum")
        np.testing.assert_allclose(
            np.asarray(out_mesh), np.asarray(out_eager), rtol=1e-12
        )

    @pytest.mark.parametrize("func", ["cumsum", "nancumsum", "ffill", "bfill"])
    def test_forced_blockwise_matches_eager(self, func):
        from flox_tpu import groupby_scan

        n = 96
        vals = np.random.default_rng(5).normal(size=n)
        vals[::7] = np.nan
        labels = np.arange(n) // 12
        out_bw = groupby_scan(vals, labels, func=func, method="blockwise", mesh=self._mesh())
        out_eager = groupby_scan(vals, labels, func=func)
        np.testing.assert_allclose(
            np.asarray(out_bw), np.asarray(out_eager), rtol=1e-12, equal_nan=True
        )

    def test_forced_blockwise_invalid_layout_raises(self):
        from flox_tpu import groupby_scan

        n = 96
        vals = np.random.default_rng(6).normal(size=n)
        labels = np.arange(n) % 5  # every group spans every shard
        with pytest.raises(ValueError, match="spans shards"):
            groupby_scan(vals, labels, func="cumsum", method="blockwise", mesh=self._mesh())


class TestDatetimeScans:
    """datetime64/timedelta64 scans on the exact int64 view (the reference's
    numpy kernels handle NaT natively; float64 would lose ns precision)."""

    T = np.array(
        ["2001-01-01T00:00:00.000000001", "NaT", "2001-01-03", "NaT", "NaT", "2001-01-06"],
        dtype="datetime64[ns]",
    )
    LABELS = np.array([0, 0, 1, 0, 1, 1])

    def test_ffill_datetime(self, engine):
        out = groupby_scan(self.T, self.LABELS, func="ffill", engine=engine)
        expected = self.T.copy()
        expected[1] = self.T[0]  # group 0: carries the ns-exact first stamp
        expected[3] = self.T[0]
        expected[4] = self.T[2]  # group 1
        assert out.dtype == self.T.dtype
        np.testing.assert_array_equal(out, expected)

    def test_bfill_datetime(self, engine):
        out = groupby_scan(self.T, self.LABELS, func="bfill", engine=engine)
        expected = self.T.copy()
        expected[1] = self.T[3]  # NaT: group 0 has nothing after -> stays NaT
        expected[4] = self.T[5]
        np.testing.assert_array_equal(out, expected)

    def test_ffill_datetime_on_mesh(self):
        from flox_tpu.parallel import make_mesh

        t = np.tile(self.T, 8)
        labels = np.tile(self.LABELS, 8)
        eager = groupby_scan(t, labels, func="ffill")
        mesh_r = groupby_scan(t, labels, func="ffill", mesh=make_mesh(8))
        np.testing.assert_array_equal(np.asarray(mesh_r), np.asarray(eager))

    def test_cumsum_timedelta(self, engine):
        td = np.array([1, 2, 4, 8], dtype="timedelta64[ns]")
        labels = np.array([0, 1, 0, 1])
        out = groupby_scan(td, labels, func="cumsum", engine=engine)
        np.testing.assert_array_equal(
            out, np.array([1, 2, 5, 10], dtype="timedelta64[ns]")
        )

    def test_cumsum_timedelta_nat_propagates(self, engine):
        td = np.array([1, 2, "NaT", 8, 16], dtype="timedelta64[ns]")
        labels = np.array([0, 1, 0, 0, 1])
        out = groupby_scan(td, labels, func="cumsum", engine=engine)
        expected = np.array([1, 2, "NaT", "NaT", 18], dtype="timedelta64[ns]")
        np.testing.assert_array_equal(out, expected)
        out_skip = groupby_scan(td, labels, func="nancumsum", engine=engine)
        np.testing.assert_array_equal(
            out_skip, np.array([1, 2, 1, 9, 18], dtype="timedelta64[ns]")
        )

    def test_cumsum_datetime_rejected(self):
        with pytest.raises(TypeError, match="cumsum of datetime64"):
            groupby_scan(self.T, self.LABELS, func="cumsum")

    def test_dtype_kwarg_rejected(self):
        # a float dtype would silently lose sub-float64 nanoseconds
        td = np.array([1, 2], dtype="timedelta64[ns]")
        with pytest.raises(TypeError, match="dtype= is not supported"):
            groupby_scan(td, np.array([0, 0]), func="nancumsum", dtype=np.float64)

    def test_nan_label_yields_nat(self, engine):
        labels = np.array([0.0, np.nan, 0.0])
        t = self.T[:3]
        out = groupby_scan(t, labels, func="ffill", engine=engine)
        assert np.isnat(out[1])
        np.testing.assert_array_equal(out[[0, 2]], t[[0, 2]])
