"""Autotuner test suite (ISSUE 6).

The contract under test: with the tuner OFF (the default, record-only mode)
every ``auto`` dispatch decision is bit-identical to the static heuristics
and recording still accretes; with it ON, decisions come from the store's
observed winners (nearest measured band), a persisted store serves a fresh
process without re-sweeping (the two-process smoke, asserted by the
sweep/hit counters), ``cache.clear_all`` resets the in-memory store, and a
corrupt or partial cache file falls back to heuristics with a warning.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import flox_tpu
from flox_tpu import autotune, cache
from flox_tpu.core import groupby_reduce

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_store():
    """Every test starts from an empty in-memory store with the tuner OFF
    and no persistence path — even under the CI FLOX_TPU_AUTOTUNE=1 leg,
    so the off-mode assertions test the option, not the environment."""
    with flox_tpu.set_options(autotune=False, autotune_cache_path=None):
        cache.clear_all()
        yield
        cache.clear_all()


def _seed_segment_sum(winner="matmul", loser="scatter", **kw):
    keykw = dict(dtype="float32", ngroups=12, nelems=1 << 20)
    keykw.update(kw)
    autotune.record("segment_sum", winner, 50.0, **keykw)
    autotune.record("segment_sum", loser, 10.0, **keykw)
    return keykw


# ---------------------------------------------------------------------------
# key schema + store mechanics
# ---------------------------------------------------------------------------


class TestStore:
    def test_make_key_bands(self):
        k = autotune.make_key(
            "segment_sum", dtype="float32", ngroups=12, nelems=1 << 20,
            platform="cpu",
        )
        assert k == "segment_sum|cpu|float32|g4|e11"
        # ngroups/nelems in the same band share the key; a decade apart differ
        same = autotune.make_key(
            "segment_sum", dtype="float32", ngroups=15, nelems=(1 << 20) + 7,
            platform="cpu",
        )
        assert same == k
        far = autotune.make_key(
            "segment_sum", dtype="float32", ngroups=12, nelems=1 << 28,
            platform="cpu",
        )
        assert far != k

    def test_record_then_decide(self):
        kw = _seed_segment_sum()
        # off: fallback always wins (record-only mode)
        assert (
            autotune.decide("segment_sum", "scatter", ["scatter", "matmul"], **kw)
            == "scatter"
        )
        with flox_tpu.set_options(autotune=True):
            assert (
                autotune.decide("segment_sum", "scatter", ["scatter", "matmul"], **kw)
                == "matmul"
            )

    def test_decide_restricted_to_eligible_candidates(self):
        kw = _seed_segment_sum()
        with flox_tpu.set_options(autotune=True):
            # the winner is not eligible on this call -> next best eligible
            assert (
                autotune.decide("segment_sum", "scatter", ["scatter"], **kw)
                == "scatter"
            )

    def test_nearest_band_lookup_with_tolerance(self):
        _seed_segment_sum(nelems=1 << 20)
        with flox_tpu.set_options(autotune=True):
            # 4x away in elements: within the kernel-family tolerance
            assert (
                autotune.decide(
                    "segment_sum", "scatter", ["scatter", "matmul"],
                    dtype="float32", ngroups=12, nelems=1 << 22,
                )
                == "matmul"
            )
            # the engine family is strict: records must not stretch bands
            autotune.record("engine", "numpy", 99.0, dtype="float64", nelems=1 << 8)
            autotune.record("engine", "jax", 1.0, dtype="float64", nelems=1 << 8)
            assert (
                autotune.decide(
                    "engine", "jax", ["numpy", "jax"],
                    dtype="float64", nelems=1 << 24,
                )
                == "jax"
            )

    def test_ewma_flips_winner_and_bumps_version(self):
        kw = dict(dtype="float32", ngroups=12, nelems=1 << 20)
        autotune.record("segment_sum", "scatter", 10.0, **kw)
        with flox_tpu.set_options(autotune=True):
            v0 = autotune.decision_fingerprint()
            autotune.record("segment_sum", "matmul", 50.0, **kw)
            v1 = autotune.decision_fingerprint()
            assert v1 != v0  # the flip must invalidate compiled programs
            autotune.record("segment_sum", "matmul", 60.0, **kw)
            assert autotune.decision_fingerprint() == v1  # no flip, no bump

    def test_fingerprint_constant_when_disabled(self):
        fp0 = autotune.decision_fingerprint()
        _seed_segment_sum()
        assert autotune.decision_fingerprint() == fp0 == (False,)
        from flox_tpu.options import trace_fingerprint

        assert trace_fingerprint()[-1] == (False,)

    def test_clear_all_resets_in_memory_store(self):
        kw = _seed_segment_sum()
        assert cache.stats()["autotune"] > 0
        cache.clear_all()
        assert cache.stats()["autotune"] == 0
        with flox_tpu.set_options(autotune=True):
            assert (
                autotune.decide("segment_sum", "scatter", ["scatter", "matmul"], **kw)
                == "scatter"
            )
        assert autotune.decision_record()["sweeps"] == 0


# ---------------------------------------------------------------------------
# persistence
# ---------------------------------------------------------------------------


class TestPersistence:
    def test_round_trip_same_decision(self, tmp_path):
        kw = _seed_segment_sum()
        path = str(tmp_path / "autotune.json")
        assert autotune.save(path) == path
        cache.clear_all()
        with flox_tpu.set_options(autotune=True, autotune_cache_path=path):
            # lazy reload at first consult: same decision as before the clear
            assert (
                autotune.decide("segment_sum", "scatter", ["scatter", "matmul"], **kw)
                == "matmul"
            )

    def test_save_is_atomic_no_tmp_left_behind(self, tmp_path):
        _seed_segment_sum()
        path = str(tmp_path / "store" / "autotune.json")
        autotune.save(path)
        assert json.load(open(path))["records"]
        leftovers = [f for f in os.listdir(tmp_path / "store") if f != "autotune.json"]
        assert leftovers == []

    @pytest.mark.parametrize(
        "content",
        ["{truncated", '{"version": 999, "records": {}}', '{"version": 1}', "[1, 2]"],
        ids=["corrupt", "alien-version", "partial", "wrong-type"],
    )
    def test_corrupt_cache_falls_back_with_warning(self, tmp_path, content):
        path = str(tmp_path / "autotune.json")
        with open(path, "w") as f:
            f.write(content)
        with flox_tpu.set_options(autotune=True, autotune_cache_path=path):
            with pytest.warns(RuntimeWarning, match="falling back to heuristics"):
                chosen = autotune.decide(
                    "segment_sum", "scatter", ["scatter", "matmul"],
                    dtype="float32", ngroups=12, nelems=1 << 20,
                )
        assert chosen == "scatter"

    def test_missing_cache_file_is_silent(self, tmp_path):
        import warnings

        path = str(tmp_path / "never-written.json")
        with flox_tpu.set_options(autotune=True, autotune_cache_path=path):
            with warnings.catch_warnings():
                warnings.simplefilter("error")
                autotune.decide(
                    "segment_sum", "scatter", ["scatter"],
                    dtype="float32", ngroups=12, nelems=1 << 20,
                )

    def test_second_process_decides_without_resweeping(self, tmp_path):
        """The acceptance criterion's two-process contract, in-process: a
        fresh store (post clear_all) with a persisted cache path makes the
        measured decision with ZERO sweeps and a counted cache hit."""
        kw = _seed_segment_sum()
        path = str(tmp_path / "autotune.json")
        autotune.save(path)
        cache.clear_all()  # "process 2": empty store, unloaded state
        with flox_tpu.set_options(autotune=True, autotune_cache_path=path):
            chosen = autotune.decide(
                "segment_sum", "scatter", ["scatter", "matmul"], **kw
            )
            rec = autotune.decision_record()
        assert chosen == "matmul"
        assert rec["sweeps"] == 0
        assert rec["cache_hits"] >= 1

    def test_cross_process_cache_hits(self, tmp_path):
        """A REAL second process: run the same tiny reduction twice in
        subprocesses sharing one cache file; the first sweeps, the second
        serves every measured decision from disk (sweeps == 0)."""
        path = str(tmp_path / "autotune.json")
        code = (
            "import json, numpy as np\n"
            "import flox_tpu\n"
            "from flox_tpu import autotune\n"
            "rng = np.random.default_rng(0)\n"
            "v = rng.normal(size=(4, 3000)).astype(np.float32)\n"
            "l = np.repeat(np.arange(5), 600)\n"
            "flox_tpu.groupby_reduce(v, l, func='nanmean', engine='jax')\n"
            "autotune.save()\n"
            "rec = autotune.decision_record()\n"
            "print(json.dumps({'sweeps': rec['sweeps'], 'hits': rec['cache_hits'],"
            " 'entries': rec['entries']}))\n"
        )
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            FLOX_TPU_AUTOTUNE="1", FLOX_TPU_AUTOTUNE_CACHE_PATH=path,
        )
        env.pop("FLOX_TPU_TELEMETRY", None)
        outs = []
        for _ in range(2):
            proc = subprocess.run(
                [sys.executable, "-c", code], cwd=REPO, env=env,
                capture_output=True, text=True, timeout=240,
            )
            assert proc.returncode == 0, proc.stderr
            outs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        assert outs[0]["sweeps"] >= 1  # first process measured candidates
        assert outs[1]["sweeps"] == 0  # second served from the persisted cache
        assert outs[1]["entries"] >= outs[0]["entries"]
        assert outs[1]["hits"] >= 1

    def test_save_merges_existing_disk_store(self, tmp_path):
        """save() folds the on-disk store in first: a record-only process
        that never consulted the store (so the lazy load never ran) must
        not clobber another process's persisted measurements (regression:
        the atexit save wiped every record but its own)."""
        path = str(tmp_path / "autotune.json")
        _seed_segment_sum()
        autotune.save(path)
        cache.clear_all()  # fresh "process": empty store, never loaded
        autotune.record("stream_prefetch", "4", 10.0, nelems=1 << 20)
        autotune.save(path)
        payload = json.load(open(path))
        families = {k.split("|")[0] for k in payload["records"]}
        assert families == {"segment_sum", "stream_prefetch"}

    def test_seed_not_suppressed_by_partial_disk_store(self, tmp_path, monkeypatch):
        """A persisted store holding only OTHER families must not suppress
        history seeding (regression: seeding was gated on a fully empty
        store, so a stream-records-only file starved the quantile flip)."""
        autotune.record("stream_prefetch", "4", 10.0, nelems=1 << 20)
        path = str(tmp_path / "autotune.json")
        autotune.save(path)
        cache.clear_all()
        os.makedirs(tmp_path / "BENCH_HISTORY")
        with open(tmp_path / "BENCH_HISTORY" / "bench_runs.jsonl", "w") as f:
            f.write(json.dumps(
                {"platform": "cpu", "impl_sweep_gbps": {"matmul": 9.0}}
            ) + "\n")
        monkeypatch.setattr(autotune, "_repo_root", lambda: str(tmp_path))
        with flox_tpu.set_options(autotune=True, autotune_cache_path=path):
            rec = autotune.decision_record()  # triggers lazy load + seed
            assert any(k.startswith("stream_prefetch|") for k in rec["winners"])
            seeded = [
                v for v in rec["winners"].values() if v["source"] == "seed"
            ]
            assert seeded, "history seeding was suppressed by the disk store"

    def test_seed_defers_to_real_observations(self):
        """A measured record outranks committed evidence for the same key."""
        kw = dict(dtype="float32", ngroups=12, nelems=1 << 20, platform="tpu")
        autotune.record("quantile", "sort", 5.0, source="observed", **kw)
        autotune.record("quantile", "select", 99.0, source="seed", **kw)
        rec = autotune.lookup("quantile", **kw)
        assert list(rec["candidates"]) == ["sort"]  # seed skipped the key


# ---------------------------------------------------------------------------
# record-only bit-identity + wired decision points
# ---------------------------------------------------------------------------


class TestDispatchWiring:
    def test_record_only_is_bit_identical(self):
        """With the tuner off, a store full of would-flip records must not
        change a single bit of any result (the FLOX_TPU_AUTOTUNE=0
        acceptance criterion)."""
        rng = np.random.default_rng(0)
        vals = rng.normal(size=(3, 4096)).astype(np.float32)
        codes = np.arange(4096) % 7
        calls = [
            ("nansum", {}),
            ("nanmean", {}),
            ("nanquantile", {"finalize_kwargs": {"q": 0.9}}),
        ]
        baseline = [
            np.asarray(groupby_reduce(vals, codes, func=f, engine="jax", **kw)[0])
            for f, kw in calls
        ]
        # would-flip records for every wired family
        autotune.record("segment_sum", "matmul", 99.0, dtype="float32",
                        ngroups=7, nelems=vals.size)
        autotune.record("quantile", "select", 99.0, dtype="float32",
                        ngroups=7, nelems=vals.size)
        autotune.record("engine", "numpy", 99.0, dtype="float64", nelems=vals.size)
        again = [
            np.asarray(groupby_reduce(vals, codes, func=f, engine="jax", **kw)[0])
            for f, kw in calls
        ]
        for a, b in zip(baseline, again):
            np.testing.assert_array_equal(a, b)

    def test_segment_sum_impl_consults_store(self):
        from flox_tpu.kernels import _segment_sum_impl

        import jax

        proxy = jax.ShapeDtypeStruct((4096, 8), np.float32)
        assert _segment_sum_impl(proxy, 12) == "scatter"  # CPU heuristic
        autotune.record("segment_sum", "matmul", 99.0, dtype="float32",
                        ngroups=12, nelems=4096 * 8)
        assert _segment_sum_impl(proxy, 12) == "scatter"  # still off
        with flox_tpu.set_options(autotune=True):
            assert _segment_sum_impl(proxy, 12) == "matmul"

    def test_quantile_choice_consults_store(self):
        from flox_tpu.kernels import _quantile_impl_choice

        import jax

        proxy = jax.ShapeDtypeStruct((4096, 8), np.float32)
        assert _quantile_impl_choice(proxy, 12) == "sort"
        autotune.record("quantile", "select", 99.0, dtype="float32",
                        ngroups=12, nelems=4096 * 8)
        with flox_tpu.set_options(autotune=True):
            assert _quantile_impl_choice(proxy, 12) == "select"
            # an explicit policy always wins over the tuner
            with flox_tpu.set_options(quantile_impl="sort"):
                assert _quantile_impl_choice(proxy, 12) == "sort"

    def test_engine_choice_consults_store(self):
        from flox_tpu.core import _choose_engine

        arr = np.zeros(512, dtype=np.float64)
        assert _choose_engine(None, arr, False) == "numpy"  # small-host heuristic
        autotune.record("engine", "jax", 99.0, dtype="float64", nelems=512)
        autotune.record("engine", "numpy", 1.0, dtype="float64", nelems=512)
        with flox_tpu.set_options(autotune=True):
            assert _choose_engine(None, arr, False) == "jax"
        # explicit engine= always wins
        with flox_tpu.set_options(autotune=True):
            assert _choose_engine("numpy", arr, False) == "numpy"

    def test_numpy_engine_max_elems_option(self):
        from flox_tpu.core import _choose_engine

        arr = np.zeros(512, dtype=np.float64)
        assert _choose_engine(None, arr, False) == "numpy"
        with flox_tpu.set_options(numpy_engine_max_elems=256):
            assert _choose_engine(None, arr, False) == "jax"
        with flox_tpu.set_options(numpy_engine_max_elems=0):
            assert _choose_engine(None, arr, False) == "jax"

    def test_autotuned_run_matches_heuristic_run_numerically(self):
        """With the tuner ON and a store that flips the segment-sum path,
        results stay numerically equivalent (different lowerings may differ
        in last-bit summation order, never beyond fp tolerance)."""
        rng = np.random.default_rng(1)
        vals = rng.normal(size=(4, 2048)).astype(np.float32)
        codes = np.arange(2048) % 5
        base, _ = groupby_reduce(vals, codes, func="nanmean", engine="jax")
        autotune.record("segment_sum", "matmul", 99.0, dtype="float32",
                        ngroups=5, nelems=vals.size)
        with flox_tpu.set_options(autotune=True):
            tuned, _ = groupby_reduce(vals, codes, func="nanmean", engine="jax")
        np.testing.assert_allclose(
            np.asarray(base), np.asarray(tuned), rtol=1e-5, atol=1e-6
        )

    def test_engine_sweep_records_under_swept_band(self):
        """The engine micro-sweep caps its workload; the measurement must
        land under the SWEPT size's band (regression: a small-array numpy
        win filed under a 10M-element band would route large host arrays
        to the numpy engine against the measured crossover)."""
        with flox_tpu.set_options(autotune=True):
            nelems = 10_000_000
            autotune.prime_engine("float64", nelems)
            # far beyond the cap band: no sweep, no mislabeled record
            assert autotune.lookup("engine", dtype="float64", nelems=nelems) is None
            in_band = autotune._SWEEP_ENGINE_N_MAX
            autotune.prime_engine("float64", in_band)
            rec = autotune.lookup("engine", dtype="float64", nelems=in_band)
            if rec is not None:  # sweep budget permitting
                key = autotune.make_key(
                    "engine", dtype="float64", nelems=in_band
                )
                assert key in autotune._AUTOTUNE_CACHE

    def test_prime_reduce_sweeps_once_per_key(self):
        with flox_tpu.set_options(autotune=True):
            rng = np.random.default_rng(0)
            vals = rng.normal(size=(4, 3000)).astype(np.float32)
            codes = np.repeat(np.arange(5), 600)
            groupby_reduce(vals, codes, func="nanmean", engine="jax")
            s1 = autotune.decision_record()["sweeps"]
            assert s1 >= 1
            groupby_reduce(vals, codes, func="nanmean", engine="jax")
            assert autotune.decision_record()["sweeps"] == s1  # memoized


# ---------------------------------------------------------------------------
# streaming observations + decisions
# ---------------------------------------------------------------------------


class TestStreaming:
    def test_stream_reports_feed_the_store_record_only(self):
        from flox_tpu.streaming import streaming_groupby_reduce

        rng = np.random.default_rng(0)
        data = rng.normal(size=(8, 4000)).astype(np.float32)
        month = (np.arange(4000) // 300) % 12
        streaming_groupby_reduce(data, month, func="nanmean", batch_len=1000)
        rec = autotune.decision_record()
        prefixes = {k.split("|")[0] for k in rec["winners"]}
        assert "stream_prefetch" in prefixes
        assert "stream_slab" in prefixes

    def test_streaming_autotuned_matches_heuristic(self):
        from flox_tpu.streaming import streaming_groupby_reduce

        rng = np.random.default_rng(0)
        data = rng.normal(size=(8, 4000)).astype(np.float32)
        month = (np.arange(4000) // 300) % 12
        base = np.asarray(
            streaming_groupby_reduce(data, month, func="nanmean")[0]
        )
        with flox_tpu.set_options(autotune=True):
            tuned = np.asarray(
                streaming_groupby_reduce(data, month, func="nanmean")[0]
            )
        np.testing.assert_allclose(base, tuned, rtol=1e-5, atol=1e-6)

    def test_pick_stream_prefetch_identity_without_records(self):
        with flox_tpu.set_options(autotune=True):
            assert autotune.pick_stream_prefetch(2, nelems=1 << 20) == 2

    def test_pick_stream_batch_bytes_identity_without_records(self):
        with flox_tpu.set_options(autotune=True):
            assert (
                autotune.pick_stream_batch_bytes(256 * 2**20, nelems=1 << 30)
                == 256 * 2**20
            )

    def test_pick_stream_prefetch_serves_recorded_winner(self):
        autotune.record("stream_prefetch", "4", 10.0, nelems=1 << 20)
        autotune.record("stream_prefetch", "2", 1.0, nelems=1 << 20)
        with flox_tpu.set_options(autotune=True):
            assert autotune.pick_stream_prefetch(2, nelems=1 << 20) == 4

    def test_explicit_stream_prefetch_is_never_adapted(self):
        """An explicit set_options(stream_prefetch=...) pins the depth even
        with the tuner on and a contrary record (regression: the tuner once
        overrode the pinned depth with an observed depth-0 win)."""
        from flox_tpu import profiling
        from flox_tpu.streaming import streaming_groupby_reduce

        rng = np.random.default_rng(0)
        data = rng.normal(size=(4, 4000)).astype(np.float32)
        month = (np.arange(4000) // 300) % 12
        nelems = data.size
        autotune.record("stream_prefetch", "0", 99.0, nelems=nelems)
        with flox_tpu.set_options(autotune=True, stream_prefetch=2):
            with profiling.stream_monitor() as reports:
                streaming_groupby_reduce(data, month, func="nanmean", batch_len=997)
        assert reports[0].prefetch == 2

    def test_explicit_batch_bytes_is_never_adapted(self):
        """batch_bytes= is a device-memory cap: the tuner adapts slab
        sizing only when the caller specified neither batch_len nor
        batch_bytes (regression: a recorded small-slab winner overrode an
        explicit byte budget)."""
        from flox_tpu import profiling
        from flox_tpu.streaming import streaming_groupby_reduce

        rng = np.random.default_rng(0)
        data = rng.normal(size=(64, 20000)).astype(np.float32)
        month = (np.arange(20000) // 300) % 12
        autotune.record("stream_slab", "2^16", 99.0, nelems=data.size)
        with flox_tpu.set_options(autotune=True):
            with profiling.stream_monitor() as reports:
                streaming_groupby_reduce(
                    data, month, func="nanmean", batch_bytes=256 * 2**20
                )
        # the explicit 256 MiB budget covers the whole array in one slab;
        # the recorded 64 KiB winner would have split it into dozens
        assert len(reports[0].slabs) == 1

    def test_checkpoint_path_pins_stream_slab_sizing(self, tmp_path):
        """Autotuned batch sizing is off under a checkpoint path: the
        derived batch_len is part of the checkpoint identity key and must
        be reproducible by the process that resumes the stream."""
        from flox_tpu import profiling
        from flox_tpu.streaming import streaming_groupby_reduce

        rng = np.random.default_rng(0)
        data = rng.normal(size=(64, 20000)).astype(np.float32)
        month = (np.arange(20000) // 300) % 12
        # a recorded small-slab winner that WOULD flip the derived batch_len
        autotune.record("stream_slab", "2^16", 99.0, nelems=data.size)
        with flox_tpu.set_options(autotune=True):
            with profiling.stream_monitor() as adapted:
                streaming_groupby_reduce(data, month, func="nanmean")
        with flox_tpu.set_options(
            autotune=True, stream_checkpoint_path=str(tmp_path / "ckpt.npz")
        ):
            with profiling.stream_monitor() as pinned:
                streaming_groupby_reduce(data, month, func="nanmean")
        assert len(adapted[0].slabs) > len(pinned[0].slabs)


# ---------------------------------------------------------------------------
# seeding + regression sentinel
# ---------------------------------------------------------------------------


class TestSeedAndSentinel:
    def _bench_record(self, platform="tpu"):
        return {
            "platform": platform,
            "value": 800.0,
            "impl_sweep_gbps": {"scatter": 120.0, "matmul": 700.0, "pallas": 800.0},
            "quantile_gbps": {"sort": 90.0, "select": 300.0},
            "streaming": {"gbps_sync": 10.0, "gbps_prefetch": 20.0},
            "workload": {"nlat": 181, "nlon": 360, "ntime": 26304, "ngroups": 12},
        }

    def test_seed_from_bench_files(self, tmp_path):
        with open(tmp_path / "BENCH_TPU_LAST.json", "w") as f:
            json.dump(self._bench_record(), f)
        os.makedirs(tmp_path / "BENCH_HISTORY")
        with open(tmp_path / "BENCH_HISTORY" / "bench_runs.jsonl", "w") as f:
            f.write(json.dumps(self._bench_record("cpu")) + "\n")
        assert autotune.seed(str(tmp_path)) > 0
        # the seeded on-chip numbers resolve the open quantile decision for
        # the tpu platform key (this CPU process keys decide() by its own
        # platform, so assert through the platform-explicit lookup)
        rec = autotune.lookup(
            "quantile", dtype="float32", ngroups=12,
            nelems=181 * 360 * 26304, platform="tpu",
        )
        assert rec is not None
        assert max(rec["candidates"], key=lambda c: rec["candidates"][c]["gbps"]) == "select"

    def test_sentinel_flags_regression(self, tmp_path):
        hist = tmp_path / "bench_runs.jsonl"
        with open(hist, "w") as f:
            f.write(json.dumps({"platform": "cpu", "value": 10.0,
                                "impl_sweep_gbps": {"scatter": 10.0}}) + "\n")
        verdict = autotune.regression_sentinel(
            {"headline": 8.0, "segment_sum[scatter]": 9.9},
            history_path=str(hist), platform="cpu",
        )
        assert verdict["status"] == "regression"
        assert verdict["regressed"] == ["headline"]
        assert verdict["families"]["headline"]["regressed"] is True
        assert verdict["families"]["segment_sum[scatter]"]["regressed"] is False

    def test_sentinel_ok_within_threshold(self, tmp_path):
        hist = tmp_path / "bench_runs.jsonl"
        with open(hist, "w") as f:
            f.write(json.dumps({"platform": "cpu", "value": 10.0}) + "\n")
        verdict = autotune.regression_sentinel(
            {"headline": 9.0}, history_path=str(hist), platform="cpu"
        )
        assert verdict["status"] == "ok"

    def test_sentinel_ignores_other_platform_rounds(self, tmp_path):
        hist = tmp_path / "bench_runs.jsonl"
        with open(hist, "w") as f:
            f.write(json.dumps({"platform": "tpu", "value": 1000.0}) + "\n")
        verdict = autotune.regression_sentinel(
            {"headline": 5.0}, history_path=str(hist), platform="cpu"
        )
        assert verdict["status"] == "ok"
        assert verdict["compared_against"] is None

    def test_sentinel_matches_workload(self, tmp_path):
        """A sub-scale smoke round is never compared against a full-size
        round: workload-recording rounds only diff against their own shape
        (regression: CI's bounded bench smoke read as a chronic >15%
        'regression' against the committed full-scale round)."""
        hist = tmp_path / "bench_runs.jsonl"
        full = {"nlat": 181, "nlon": 360, "ntime": 26304, "ngroups": 12}
        tiny = {"nlat": 4, "nlon": 16, "ntime": 2000, "ngroups": 12}
        with open(hist, "w") as f:
            f.write(json.dumps(
                {"platform": "cpu", "value": 10.0, "workload": full}
            ) + "\n")
        verdict = autotune.regression_sentinel(
            {"headline": 0.5}, history_path=str(hist), platform="cpu",
            workload=tiny,
        )
        assert verdict["status"] == "ok"
        assert verdict["compared_against"] is None
        verdict = autotune.regression_sentinel(
            {"headline": 0.5}, history_path=str(hist), platform="cpu",
            workload=full,
        )
        assert verdict["status"] == "regression"

    def test_sentinel_missing_history_is_ok(self, tmp_path):
        verdict = autotune.regression_sentinel(
            {"headline": 5.0}, history_path=str(tmp_path / "nope.jsonl"),
            platform="cpu",
        )
        assert verdict["status"] == "ok"

    def test_sentinel_cli_report_only(self, capsys):
        rc = autotune.main(["sentinel"])
        assert rc == 0  # report-only even when the verdict is "regression"
        out = json.loads(capsys.readouterr().out)
        assert out["status"] in ("ok", "regression")

    def test_report_cli(self, capsys):
        _seed_segment_sum()
        rc = autotune.main(["report"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["entries"] == 1


# ---------------------------------------------------------------------------
# bench integration
# ---------------------------------------------------------------------------


class TestBenchIntegration:
    def test_benchmarks_sentinel_row_shape(self):
        import benchmarks

        rows = [
            {"bench": "era5_dayofyear[jax]", "value": 5.0, "unit": "GB/s"},
            {"bench": "time_reduce[1d-sum-jax]", "value": 0.5, "unit": "ms"},
        ]
        row = benchmarks.sentinel_row(rows, "cpu")
        assert row["bench"] == "regression_sentinel"
        assert row["unit"] == "verdict"
        assert row["value"]["status"] in ("ok", "regression")
        assert "era5_dayofyear[jax]" in row["value"]["families"]
        assert "time_reduce[1d-sum-jax]" not in row["value"]["families"]


# ---------------------------------------------------------------------------
# option plumbing
# ---------------------------------------------------------------------------


class TestOptions:
    def test_validated_at_set_time(self):
        with pytest.raises(ValueError):
            flox_tpu.set_options(autotune=1)  # bool only, 1 is a bug
        with pytest.raises(ValueError):
            flox_tpu.set_options(autotune_cache_path="")
        with pytest.raises(ValueError):
            flox_tpu.set_options(numpy_engine_max_elems=-1)
        with pytest.raises(ValueError):
            flox_tpu.set_options(numpy_engine_max_elems=True)

    def test_env_mirrors_seed_defaults(self):
        code = (
            "from flox_tpu.options import OPTIONS\n"
            "assert OPTIONS['autotune'] is True\n"
            "assert OPTIONS['autotune_cache_path'] == '/tmp/at.json'\n"
            "assert OPTIONS['numpy_engine_max_elems'] == 1234\n"
            "print('ok')\n"
        )
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", FLOX_TPU_AUTOTUNE="1",
            FLOX_TPU_AUTOTUNE_CACHE_PATH="/tmp/at.json",
            FLOX_TPU_NUMPY_ENGINE_MAX_ELEMS="1234",
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], cwd=REPO, env=env,
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert "ok" in proc.stdout

    def test_malformed_env_falls_back(self):
        code = (
            "from flox_tpu.options import OPTIONS\n"
            "assert OPTIONS['autotune'] is False\n"
            "assert OPTIONS['numpy_engine_max_elems'] == 32768\n"
            "print('ok')\n"
        )
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", FLOX_TPU_AUTOTUNE="banana",
            FLOX_TPU_NUMPY_ENGINE_MAX_ELEMS="-5",
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], cwd=REPO, env=env,
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert "ok" in proc.stdout

    def test_context_exit_restores_explicit_pin(self):
        """The context-manager form unpins on exit along with restoring the
        value: once a knob rides its built-in default again it is back on
        the tuner's auto surface. Plain-setter pins stay for the session."""
        from flox_tpu.options import explicitly_set

        if "FLOX_TPU_STREAM_PREFETCH" in os.environ:
            pytest.skip("depth pinned by the environment")
        assert not explicitly_set("stream_prefetch")
        with flox_tpu.set_options(stream_prefetch=4):
            assert explicitly_set("stream_prefetch")
        assert not explicitly_set("stream_prefetch")
