"""Distributed correctness: the mesh path must equal the eager path.

The reference tests distributed behavior with the synchronous dask scheduler
(test_core.py:65); here the analogue is a virtual 8-device CPU mesh — the
same SPMD program that runs over ICI on real chips executes across host
devices, collectives included.
"""

import numpy as np
import pytest

import jax

from flox_tpu.core import groupby_reduce
from flox_tpu.scan import groupby_scan
from flox_tpu.parallel import make_mesh

RNG = np.random.default_rng(99)

MESH_FUNCS = [
    "sum", "nansum", "prod", "nanprod", "mean", "nanmean", "var", "nanvar",
    "std", "nanstd", "max", "nanmax", "min", "nanmin", "count", "all", "any",
    "argmax", "nanargmax", "argmin", "nanargmin",
    "first", "last", "nanfirst", "nanlast",
]


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _data(shape, add_nan, n):
    values = np.round(RNG.normal(size=shape), 1)
    if add_nan:
        values[..., RNG.random(n) < 0.25] = np.nan
    return values


@pytest.mark.parametrize("method", ["map-reduce", "cohorts"])
@pytest.mark.parametrize("add_nan", [False, True])
@pytest.mark.parametrize("func", MESH_FUNCS)
def test_sharded_matches_eager(mesh, func, add_nan, method):
    n, size = 111, 5  # deliberately not divisible by 8 (padding path)
    codes = RNG.integers(0, size, n).astype(np.int64)
    values = _data((n,), add_nan, n)
    fkw = {"ddof": 1} if "var" in func or "std" in func else {}

    eager, _ = groupby_reduce(values, codes, func=func, engine="jax", finalize_kwargs=fkw)
    sharded, _ = groupby_reduce(
        values, codes, func=func, method=method, mesh=mesh, finalize_kwargs=fkw
    )
    np.testing.assert_allclose(
        np.asarray(sharded).astype(np.float64),
        np.asarray(eager).astype(np.float64),
        rtol=1e-12,
        atol=1e-12,
        equal_nan=True,
    )


@pytest.mark.parametrize("func", ["sum", "nanmean", "var", "max", "first", "nanargmax"])
def test_sharded_2d(mesh, func):
    n, size = 64, 4
    codes = RNG.integers(0, size, n).astype(np.int64)
    values = _data((3, n), True, n)
    eager, _ = groupby_reduce(values, codes, func=func, engine="jax")
    sharded, _ = groupby_reduce(values, codes, func=func, method="map-reduce", mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(sharded).astype(np.float64),
        np.asarray(eager).astype(np.float64),
        rtol=1e-12, atol=1e-12, equal_nan=True,
    )


def test_sharded_expected_groups(mesh):
    labels = np.array([1, 1, 3, 3, 5] * 10)
    vals = np.arange(50.0)
    sharded, groups = groupby_reduce(
        vals, labels, func="nanmean", method="map-reduce", mesh=mesh,
        expected_groups=np.array([1, 2, 3, 4, 5]),
    )
    eager, _ = groupby_reduce(
        vals, labels, func="nanmean", expected_groups=np.array([1, 2, 3, 4, 5])
    )
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(eager), equal_nan=True)


def test_blockwise_shard_local_groups(mesh):
    # groups aligned with shards: each shard owns whole groups
    ndev = len(jax.devices())
    per = 16
    n = ndev * per
    codes = np.repeat(np.arange(ndev), per).astype(np.int64)  # group d on shard d
    values = np.round(RNG.normal(size=n), 1)
    sharded, _ = groupby_reduce(values, codes, func="sum", method="blockwise", mesh=mesh)
    eager, _ = groupby_reduce(values, codes, func="sum", engine="jax")
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(eager), rtol=1e-12)


def test_blockwise_order_stats(mesh):
    # median/quantile on the mesh via blockwise (whole groups per shard)
    ndev = len(jax.devices())
    per = 16
    n = ndev * per
    codes = np.repeat(np.arange(ndev), per).astype(np.int64)
    values = np.round(RNG.normal(size=n), 1)
    sharded, _ = groupby_reduce(values, codes, func="nanmedian", method="blockwise", mesh=mesh)
    eager, _ = groupby_reduce(values, codes, func="nanmedian", engine="jax")
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(eager), rtol=1e-12, atol=1e-12)


class TestDistributedOrderStats:
    """Quantile/median run method='map-reduce' on the mesh: the radix-select
    counting passes psum across shards, so no shard ever holds a whole
    group — a capability the reference does NOT have (it forces blockwise
    for order statistics, reference core.py:685-709). The SELECTED order
    statistics are bit-identical to eager (same global counts -> same
    bit-by-bit reconstruction); the final interpolated value may differ by
    an ULP because XLA contracts the lerp's mul+add into an FMA differently
    under shard_map than under the eager jit — hence allclose at ~1 ULP,
    not array_equal."""

    @pytest.mark.parametrize("func,fkw", [
        ("nanmedian", {}),
        ("median", {}),
        ("nanquantile", {"q": 0.9}),
        ("quantile", {"q": [0.25, 0.5, 0.75]}),
        ("nanquantile", {"q": 0.3, "method": "nearest"}),
        ("nanquantile", {"q": 0.7, "method": "midpoint"}),
        ("quantile", {"q": 0.5, "method": "median_unbiased"}),
    ])
    def test_bit_identical_to_eager(self, mesh, func, fkw):
        n = 4096
        labels = RNG.integers(0, 11, n)
        vals = RNG.normal(size=(3, n))
        vals[:, ::7] = np.nan  # groups span every shard; NaNs everywhere
        eager, _ = groupby_reduce(vals, labels, func=func, finalize_kwargs=fkw or None)
        sharded, _ = groupby_reduce(
            vals, labels, func=func, finalize_kwargs=fkw or None,
            method="map-reduce", mesh=mesh,
        )
        np.testing.assert_allclose(
            np.asarray(sharded), np.asarray(eager), rtol=5e-16, atol=0, equal_nan=True
        )

    def test_cohorts_coerces_to_mapreduce(self, mesh):
        labels = RNG.integers(0, 5, 512)
        vals = RNG.normal(size=512)
        eager, _ = groupby_reduce(vals, labels, func="nanmedian")
        # the reroute is a UserWarning, not a debug log (ADVICE r5): the
        # caller asked for cohorts BY NAME and must hear it ran map-reduce
        with pytest.warns(UserWarning, match="no ownership win for order statistics"):
            sharded, _ = groupby_reduce(
                vals, labels, func="nanmedian", method="cohorts", mesh=mesh
            )
        np.testing.assert_array_equal(np.asarray(sharded), np.asarray(eager))

    def test_int_dtype(self, mesh):
        labels = RNG.integers(0, 7, 1024)
        vals = RNG.integers(-50, 50, size=1024)
        eager, _ = groupby_reduce(vals, labels, func="median")
        sharded, _ = groupby_reduce(
            vals, labels, func="median", method="map-reduce", mesh=mesh
        )
        np.testing.assert_array_equal(np.asarray(sharded), np.asarray(eager))

    def test_mode_still_requires_blockwise(self, mesh):
        # mode's run-length structure needs contiguous sorted groups; it
        # keeps the actionable blockwise error
        with pytest.raises(NotImplementedError, match="blockwise"):
            groupby_reduce(
                np.arange(8.0), np.array([0, 1] * 4), func="mode",
                method="map-reduce", mesh=mesh,
            )


def test_sharded_min_count(mesh):
    labels = np.array([0, 0, 1] * 8)
    vals = np.array([1.0, np.nan, np.nan] * 8)
    sharded, _ = groupby_reduce(
        vals, labels, func="nansum", min_count=20, method="map-reduce", mesh=mesh
    )
    np.testing.assert_allclose(np.asarray(sharded), [np.nan, np.nan], equal_nan=True)


@pytest.mark.parametrize("func", ["cumsum", "nancumsum", "ffill", "bfill"])
@pytest.mark.parametrize("add_nan", [False, True])
def test_sharded_scan_matches_eager(mesh, func, add_nan):
    n = 117  # non-divisible: padding path
    codes = RNG.integers(0, 5, n).astype(np.int64)
    values = _data((n,), add_nan, n)
    eager = np.asarray(groupby_scan(values, codes, func=func, engine="jax"))
    sharded = np.asarray(groupby_scan(values, codes, func=func, method="blelloch"))
    np.testing.assert_allclose(sharded, eager, rtol=1e-12, atol=1e-12, equal_nan=True)


def test_sharded_scan_2d(mesh):
    n = 64
    codes = RNG.integers(0, 4, n).astype(np.int64)
    values = _data((3, n), True, n)
    eager = np.asarray(groupby_scan(values, codes, func="nancumsum", engine="jax"))
    sharded = np.asarray(groupby_scan(values, codes, func="nancumsum", method="blelloch"))
    np.testing.assert_allclose(sharded, eager, rtol=1e-12, atol=1e-12, equal_nan=True)


@pytest.mark.parametrize("func", ["cumsum", "nancumsum"])
def test_sharded_timedelta_cumsum_matches_eager(mesh, func):
    # VERDICT r3 #5: the Blelloch carry threads a had-NaT channel, so
    # non-skipna NaT poisoning crosses shard boundaries exactly as eagerly
    n = 117
    codes = RNG.integers(0, 5, n).astype(np.int64)
    td = RNG.integers(1, 1000, n).astype("timedelta64[ns]")
    td[RNG.random(n) < 0.2] = np.timedelta64("NaT")
    eager = np.asarray(groupby_scan(td, codes, func=func, engine="jax"))
    sharded = np.asarray(groupby_scan(td, codes, func=func, method="blelloch"))
    np.testing.assert_array_equal(sharded, eager)


def test_sharded_timedelta_cumsum_nat_only_before_boundary(mesh):
    # a NaT in shard 0 must poison the SAME group on every later shard
    # (cumsum), and count as zero for nancumsum
    ndev = len(jax.devices())
    per = 8
    n = ndev * per
    codes = np.tile([0, 1], n // 2).astype(np.int64)
    td = np.ones(n).astype("timedelta64[ns]")
    td[2] = np.timedelta64("NaT")  # group 0, first shard
    got = np.asarray(groupby_scan(td, codes, func="cumsum", method="blelloch"))
    assert np.isnat(got[2:][codes[2:] == 0]).all()
    assert not np.isnat(got[codes == 1]).any()
    got_skip = np.asarray(groupby_scan(td, codes, func="nancumsum", method="blelloch"))
    assert not np.isnat(got_skip).any()
    eager_skip = np.asarray(groupby_scan(td, codes, func="nancumsum", engine="jax"))
    np.testing.assert_array_equal(got_skip, eager_skip)


def test_reshard_for_blockwise_order_stats(mesh):
    # arbitrary (interleaved) labels -> resharded -> blockwise median works
    from flox_tpu.rechunk import reshard_for_blockwise

    n = 200
    codes = RNG.integers(0, 7, n).astype(np.int64)
    values = np.round(RNG.normal(size=n), 1)
    layout = reshard_for_blockwise(codes, len(jax.devices()))
    arr2 = np.asarray(layout.apply(values))
    sharded, _ = groupby_reduce(
        arr2, layout.codes, func="nanmedian", method="blockwise", mesh=mesh,
        expected_groups=np.arange(7),
    )
    eager, _ = groupby_reduce(values, codes, func="nanmedian", engine="jax")
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(eager), rtol=1e-12, atol=1e-12)


def test_sharded_min_count_with_finalize(mesh):
    # min_count must not leak the appended count into agg.finalize (mean/var)
    labels = np.tile(np.array([0, 0, 1]), 8)
    vals = np.tile(np.array([1.0, 3.0, np.nan]), 8)
    for func, fkw in [("nanmean", {}), ("nanvar", {"ddof": 1}), ("nanargmax", {})]:
        sharded, _ = groupby_reduce(
            vals, labels, func=func, min_count=2, method="map-reduce", mesh=mesh,
            finalize_kwargs=fkw,
        )
        eager, _ = groupby_reduce(
            vals, labels, func=func, min_count=2, engine="jax", finalize_kwargs=fkw
        )
        np.testing.assert_allclose(
            np.asarray(sharded).astype(float), np.asarray(eager).astype(float),
            equal_nan=True, err_msg=func,
        )


def test_sharded_datetime_minmax(mesh):
    # empty-shard fill must not masquerade as NaT (few elements, many shards)
    dt = np.array(["2020-01-03", "2020-01-01", "NaT", "2020-01-05"], dtype="datetime64[ns]")
    labels = np.array([0, 0, 1, 1])
    for func in ["max", "nanmax", "min", "nanmin"]:
        sharded, _ = groupby_reduce(dt, labels, func=func, method="map-reduce", mesh=mesh)
        eager, _ = groupby_reduce(dt, labels, func=func, engine="numpy")
        np.testing.assert_array_equal(np.asarray(sharded), np.asarray(eager), err_msg=func)


def test_sharded_program_cache(mesh):
    from flox_tpu.parallel.mapreduce import _PROGRAM_CACHE

    _PROGRAM_CACHE.clear()
    labels = np.arange(64) % 4
    vals = np.arange(64.0)
    for _ in range(3):
        groupby_reduce(vals, labels, func="nanmean", method="map-reduce", mesh=mesh)
    assert len(_PROGRAM_CACHE) == 1


@pytest.mark.parametrize("func", ["nanmean", "nanvar", "max", "nanargmax", "first"])
def test_two_axis_mesh(func):
    # 2-D (dcn, ici)-style mesh: the reduced axis shards over both axes
    mesh2 = make_mesh(shape=(2, 4), axis_names=("dcn", "ici"))
    n = 103
    codes = RNG.integers(0, 5, n).astype(np.int64)
    values = _data((n,), True, n)
    eager, _ = groupby_reduce(values, codes, func=func, engine="jax")
    sharded, _ = groupby_reduce(
        values, codes, func=func, method="map-reduce", mesh=mesh2,
        axis_name=("dcn", "ici"),
    )
    np.testing.assert_allclose(
        np.asarray(sharded).astype(np.float64),
        np.asarray(eager).astype(np.float64),
        rtol=1e-12, atol=1e-12, equal_nan=True,
    )


def test_two_axis_mesh_cohorts_and_scan():
    mesh2 = make_mesh(shape=(2, 4), axis_names=("dcn", "ici"))
    n = 96
    codes = RNG.integers(0, 6, n).astype(np.int64)
    values = _data((n,), False, n)
    eager, _ = groupby_reduce(values, codes, func="nansum", engine="jax")
    sharded, _ = groupby_reduce(
        values, codes, func="nansum", method="cohorts", mesh=mesh2,
        axis_name=("dcn", "ici"),
    )
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(eager), rtol=1e-12, atol=1e-14)
    # distributed scan over the 2-D mesh
    from flox_tpu.parallel.scan import sharded_groupby_scan
    from flox_tpu.aggregations import SCANS

    out = np.asarray(
        sharded_groupby_scan(values, codes, SCANS["cumsum"], size=6, mesh=mesh2,
                             axis_name=("dcn", "ici"))
    )
    eager_s = np.asarray(groupby_scan(values, codes, func="cumsum", engine="jax"))
    np.testing.assert_allclose(out, eager_s, rtol=1e-12, atol=1e-14)


def test_mesh_missing_axis_errors():
    mesh2 = make_mesh(shape=(2, 4), axis_names=("dcn", "ici"))
    with pytest.raises(ValueError, match="no axes"):
        groupby_reduce(
            np.arange(16.0), np.arange(16) % 2, func="sum",
            method="map-reduce", mesh=mesh2, axis_name="bogus",
        )


def test_pre_sharded_input(mesh):
    # a user array already placed with a NamedSharding flows through the
    # mesh path without a host round-trip
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = 128
    codes = RNG.integers(0, 4, n).astype(np.int64)
    values = _data((n,), False, n)
    sharded_vals = jax.device_put(jnp.asarray(values), NamedSharding(mesh, P("data")))
    out, _ = groupby_reduce(sharded_vals, codes, func="nanmean", method="map-reduce", mesh=mesh)
    eager, _ = groupby_reduce(values, codes, func="nanmean", engine="jax")
    np.testing.assert_allclose(np.asarray(out), np.asarray(eager), rtol=1e-12)


def test_partial_axis_on_mesh(mesh):
    # offset codes (per-row group spaces) shard over the flat span correctly
    labels = np.array([[0, 1, 0, 1] * 8, [1, 1, 0, 0] * 8])  # (2, 32)
    vals = np.round(RNG.normal(size=(2, 32)), 1)
    eager, _ = groupby_reduce(vals, labels, func="sum", engine="jax", axis=-1)
    sharded, _ = groupby_reduce(vals, labels, func="sum", axis=-1,
                                method="map-reduce", mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(sharded).astype(float), np.asarray(eager).astype(float),
        rtol=1e-12, atol=1e-12,
    )


def test_blockwise_multi_q_quantile(mesh):
    # vector q adds a leading dim; the blockwise owner-selection must
    # broadcast through it
    ndev = len(jax.devices())
    per = 16
    codes = np.repeat(np.arange(ndev), per).astype(np.int64)
    values = np.round(RNG.normal(size=ndev * per), 1)
    sharded, _ = groupby_reduce(
        values, codes, func="quantile", method="blockwise", mesh=mesh,
        finalize_kwargs={"q": [0.25, 0.5, 0.75]},
    )
    eager, _ = groupby_reduce(
        values, codes, func="quantile", engine="jax",
        finalize_kwargs={"q": [0.25, 0.5, 0.75]},
    )
    assert np.asarray(sharded).shape == (3, ndev)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(eager), rtol=1e-12, atol=1e-12)


def test_sharded_datetime_firstlast(mesh):
    dt = np.array(
        ["2020-01-03", "NaT", "2020-01-01", "2020-01-05", "NaT", "2020-01-02"],
        dtype="datetime64[ns]",
    )
    labels = np.array([0, 0, 0, 1, 1, 1])
    for func in ["first", "last", "nanfirst", "nanlast"]:
        sharded, _ = groupby_reduce(dt, labels, func=func, method="map-reduce", mesh=mesh)
        eager, _ = groupby_reduce(dt, labels, func=func, engine="numpy")
        np.testing.assert_array_equal(np.asarray(sharded), np.asarray(eager), err_msg=func)


def test_custom_aggregation_on_mesh():
    """User Aggregation with callable chunk/combine/finalize produces
    identical results eager vs every mesh method (VERDICT #4; the collective
    analogue of the reference's _grouped_combine, dask.py:233-317)."""
    import jax.numpy as jnp

    from flox_tpu import Aggregation, groupby_reduce
    from flox_tpu.parallel import make_mesh

    def sq_sum(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
        from flox_tpu.kernels import generic_kernel

        a = jnp.asarray(array)
        return generic_kernel("nansum", group_idx, a * a, size=size, fill_value=0.0)

    def cnt(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
        from flox_tpu.kernels import generic_kernel

        return generic_kernel("nanlen", group_idx, array, size=size)

    rms = Aggregation(
        "rms", numpy=(sq_sum, cnt), chunk=(sq_sum, cnt),
        combine=(lambda s: s.sum(0), lambda s: s.sum(0)),
        finalize=lambda ss, n, **kw: (ss / n) ** 0.5,
        fill_value={"intermediate": (0.0, 0)}, final_fill_value=np.nan,
    )

    rng = np.random.default_rng(0)
    vals = rng.normal(size=96)
    labels = np.arange(96) % 5
    oracle = np.array([np.sqrt((vals[labels == g] ** 2).mean()) for g in range(5)])
    mesh = make_mesh(8)

    res_eager, _ = groupby_reduce(vals, labels, func=rms)
    np.testing.assert_allclose(np.asarray(res_eager, dtype=float), oracle, rtol=1e-12)
    for method in ["map-reduce", "cohorts"]:
        res, _ = groupby_reduce(vals, labels, func=rms, method=method, mesh=mesh)
        np.testing.assert_allclose(np.asarray(res, dtype=float), oracle, rtol=1e-12)
    # blockwise: shard-aligned labels
    labels_b = np.arange(96) // 12
    oracle_b = np.array([np.sqrt((vals[labels_b == g] ** 2).mean()) for g in range(8)])
    res, _ = groupby_reduce(vals, labels_b, func=rms, method="blockwise", mesh=mesh)
    np.testing.assert_allclose(np.asarray(res, dtype=float), oracle_b, rtol=1e-12)


def test_cohort_aligned_ownership():
    """Interleaved-months layout: psum_scatter ownership tiles follow the
    detected cohorts, and the permuted program matches eager (VERDICT #5)."""
    from flox_tpu import groupby_reduce
    from flox_tpu.cohorts import (
        chunks_from_shards,
        find_group_cohorts,
        ownership_permutation,
    )
    from flox_tpu.parallel import make_mesh

    # shard s (of 4) holds months {s, s+4, s+8}: cohorts are shard-local but
    # positionally interleaved across the group axis
    labels = np.concatenate([np.tile([s, s + 4, s + 8], 8) for s in range(4)])
    n = labels.shape[0]
    method, mapping = find_group_cohorts(
        labels, chunks_from_shards(n, 4), expected_groups=range(12)
    )
    assert method in ("cohorts", "blockwise")
    perm = ownership_permutation(mapping, 12, 4)
    assert perm is not None
    for s in range(4):  # device s's tile holds exactly its months
        assert set(perm[3 * s : 3 * s + 3]) == {s, s + 4, s + 8}

    vals = np.random.default_rng(1).normal(size=(5, n))
    mesh = make_mesh(4)
    for func, tol in [("nanmean", 1e-12), ("nanvar", 1e-10), ("nansum", 1e-12)]:
        r_eager, _ = groupby_reduce(vals, labels, func=func)
        r_coh, _ = groupby_reduce(vals, labels, func=func, method="cohorts", mesh=mesh)
        np.testing.assert_allclose(np.asarray(r_coh), np.asarray(r_eager), rtol=tol)


def test_ownership_permutation_edge_cases():
    from flox_tpu.cohorts import ownership_permutation

    assert ownership_permutation({}, 12, 4) is None
    # already-contiguous cohorts: identity -> None (no gather inserted)
    mapping = {(0,): [0, 1, 2], (1,): [3, 4, 5], (2,): [6, 7, 8], (3,): [9, 10, 11]}
    assert ownership_permutation(mapping, 12, 4) is None
    # non-divisible size pads with the sentinel column
    mapping = {(0,): [0, 4], (1,): [1, 3], (2,): [2]}
    perm = ownership_permutation(mapping, 5, 3)
    assert perm is not None and perm.shape == (6,)
    assert sorted(p for p in perm if p < 5) == [0, 1, 2, 3, 4]
    assert (perm >= 5).sum() == 1


def test_2d_mesh_single_axis_automethod():
    """Auto-method heuristic sizes by the *named* sharded axes, not the whole
    mesh (VERDICT Weak #4's second half)."""
    from flox_tpu import groupby_reduce
    from flox_tpu.parallel import make_mesh

    n = 96
    vals = np.random.default_rng(2).normal(size=(5, n))
    labels = np.arange(n) // 24
    mesh = make_mesh(shape=(2, 4), axis_names=("dcn", "ici"))
    r_eager, _ = groupby_reduce(vals, labels, func="nansum")
    r, _ = groupby_reduce(vals, labels, func="nansum", mesh=mesh, axis_name="ici")
    np.testing.assert_allclose(np.asarray(r), np.asarray(r_eager), rtol=1e-12)
    r2, _ = groupby_reduce(vals, labels, func="nanmean", mesh=mesh, axis_name=("dcn", "ici"))
    re2, _ = groupby_reduce(vals, labels, func="nanmean")
    np.testing.assert_allclose(np.asarray(r2), np.asarray(re2), rtol=1e-12)


MESH_SWEEP_FUNCS = [
    "sum", "nansum", "prod", "nanprod", "mean", "nanmean", "var", "nanvar",
    "std", "nanstd", "max", "nanmax", "min", "nanmin", "count", "all", "any",
    "first", "last", "nanfirst", "nanlast",
    "argmax", "argmin", "nanargmax", "nanargmin",
]


@pytest.mark.parametrize("method", ["map-reduce", "cohorts"])
@pytest.mark.parametrize("nby", [1, 2])
@pytest.mark.parametrize("nan_by", [False, True])
@pytest.mark.parametrize("func", MESH_SWEEP_FUNCS)
def test_mesh_sweep_all_funcs(func, nby, nan_by, method):
    """The reference's test_groupby_reduce_all product, on the mesh: every
    combinable func × nby 1-2 × NaN-in-by × method, against the eager result
    (reference tests/test_core.py:222-388; VERDICT #8)."""
    from flox_tpu.parallel import make_mesh

    import zlib

    rng = np.random.default_rng(zlib.crc32(f"{func}-{nby}-{nan_by}-{method}".encode()))
    n = 64
    vals = np.round(rng.normal(size=n), 1)
    vals[rng.random(n) < 0.2] = np.nan
    bys = [rng.integers(0, 3, n).astype(np.float64) for _ in range(nby)]
    if nan_by:
        for b in bys:
            b[rng.random(n) < 0.15] = np.nan

    eager, *ge = groupby_reduce(vals, *bys, func=func, engine="jax")
    mesh_r, *gm = groupby_reduce(vals, *bys, func=func, method=method, mesh=make_mesh(8))
    for a, b in zip(ge, gm):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(
        np.asarray(mesh_r).astype(np.float64), np.asarray(eager).astype(np.float64),
        rtol=1e-10, atol=1e-10, equal_nan=True,
    )


def test_complex_on_mesh():
    # complex128 intermediates ride psum/all_gather unchanged
    rng = np.random.default_rng(0)
    vals = rng.normal(size=96) + 1j * rng.normal(size=96)
    labels = np.arange(96) % 5
    for func in ["sum", "nansum", "mean", "nanmean", "count", "first", "last"]:
        eager, _ = groupby_reduce(vals, labels, func=func, engine="jax")
        mesh_r, _ = groupby_reduce(vals, labels, func=func, method="map-reduce", mesh=make_mesh(8))
        np.testing.assert_allclose(np.asarray(mesh_r), np.asarray(eager), rtol=1e-12, err_msg=func)


class TestHugeLabelSpace:
    """VERDICT r3 #6: 10^6-label runs work sharded via the blocked
    owner-by-owner program, or fail with an actionable ceiling error."""

    def test_blocked_program_matches_eager(self, mesh, caplog):
        # force blocking with a small ceiling — chosen so est (48G..96G
        # bytes across these funcs at 3x G x f64) exceeds it while the
        # blocked per-device peak (result + est/8) stays under — and verify
        # via the debug log that the blocked program actually ran
        import logging

        import flox_tpu
        from flox_tpu import groupby_reduce

        size = 30_000
        ceiling = 40 * size  # 1.2e6: in [36G, 48G) for lead=3, f64
        n = 240
        codes = RNG.integers(0, 37, n).astype(np.int64)
        vals = np.round(RNG.normal(size=(3, n)), 3)
        vals[:, RNG.random(n) < 0.2] = np.nan
        for func in ("nansum", "nanmean", "nanvar", "count"):
            eager, _ = groupby_reduce(
                vals, codes, func=func, expected_groups=np.arange(size),
                engine="jax",
            )
            caplog.clear()
            with flox_tpu.set_options(dense_intermediate_bytes_max=ceiling):
                with caplog.at_level(logging.DEBUG, logger="flox_tpu"):
                    blocked, _ = groupby_reduce(
                        vals, codes, func=func, expected_groups=np.arange(size),
                        method="map-reduce", mesh=mesh,
                    )
            assert "blocked owner-by-owner" in caplog.text, func
            np.testing.assert_allclose(
                np.asarray(blocked), np.asarray(eager), rtol=1e-12, atol=1e-12,
                equal_nan=True, err_msg=func,
            )

    def test_blocked_min_count_and_fill(self, mesh):
        import flox_tpu
        from flox_tpu import groupby_reduce

        size = 100_000
        labels = np.array([0, 0, 1] * 8)
        vals = np.array([1.0, np.nan, np.nan] * 8)
        with flox_tpu.set_options(dense_intermediate_bytes_max=1_200_000):
            got, _ = groupby_reduce(
                vals, labels, func="nansum", min_count=20, method="map-reduce",
                mesh=mesh, expected_groups=np.arange(size),
            )
        assert np.isnan(np.asarray(got)).all()

    def test_million_labels_sharded(self, mesh):
        # the headline scenario: 10^6 expected groups. With the default
        # 8 GiB ceiling this 1-D case stays dense; shrink the ceiling so the
        # run exercises the blocked program at true scale (est 16 MB > 12 MiB
        # ceiling >= 10 MB blocked peak).
        import flox_tpu
        from flox_tpu import groupby_reduce

        size = 1_000_000
        n = 4096
        codes = RNG.integers(0, size, n).astype(np.int64)
        vals = np.ones(n)
        with flox_tpu.set_options(dense_intermediate_bytes_max=12 * 2**20):
            got, groups = groupby_reduce(
                vals, codes, func="sum", expected_groups=np.arange(size),
                method="map-reduce", mesh=mesh,
            )
        got = np.asarray(got)
        want = np.bincount(codes, minlength=size)
        np.testing.assert_array_equal(got, want)

    def test_blocked_peak_still_over_ceiling_raises(self, mesh):
        # additive, but even the blocked per-device peak (the replicated
        # dense result alone) exceeds the ceiling: must raise, not OOM
        import flox_tpu
        from flox_tpu import groupby_reduce

        with flox_tpu.set_options(dense_intermediate_bytes_max=2**20):
            with pytest.raises(ValueError, match="even the blocked"):
                groupby_reduce(
                    np.ones(64), np.arange(64) % 8, func="sum",
                    expected_groups=np.arange(1_000_000),
                    method="map-reduce", mesh=mesh,
                )

    def test_non_additive_over_ceiling_raises(self, mesh):
        import flox_tpu
        from flox_tpu import groupby_reduce

        n = 96
        codes = RNG.integers(0, 12, n).astype(np.int64)
        vals = RNG.normal(size=n)
        with flox_tpu.set_options(dense_intermediate_bytes_max=2**20):
            with pytest.raises(ValueError, match="dense_intermediate_bytes_max"):
                groupby_reduce(
                    vals, codes, func="nanfirst",
                    expected_groups=np.arange(200_000),
                    method="map-reduce", mesh=mesh,
                )

    def test_eager_over_ceiling_raises_actionably(self):
        import flox_tpu
        from flox_tpu import groupby_reduce

        vals = np.ones((4, 64))
        codes = np.arange(64) % 8
        with flox_tpu.set_options(dense_intermediate_bytes_max=2**20):
            with pytest.raises(ValueError, match="mesh="):
                groupby_reduce(
                    vals, codes, func="sum",
                    expected_groups=np.arange(300_000), engine="jax",
                )
