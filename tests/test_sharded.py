"""Distributed correctness: the mesh path must equal the eager path.

The reference tests distributed behavior with the synchronous dask scheduler
(test_core.py:65); here the analogue is a virtual 8-device CPU mesh — the
same SPMD program that runs over ICI on real chips executes across host
devices, collectives included.
"""

import numpy as np
import pytest

import jax

from flox_tpu.core import groupby_reduce
from flox_tpu.scan import groupby_scan
from flox_tpu.parallel import make_mesh

RNG = np.random.default_rng(99)

MESH_FUNCS = [
    "sum", "nansum", "prod", "nanprod", "mean", "nanmean", "var", "nanvar",
    "std", "nanstd", "max", "nanmax", "min", "nanmin", "count", "all", "any",
    "argmax", "nanargmax", "argmin", "nanargmin",
    "first", "last", "nanfirst", "nanlast",
]


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _data(shape, add_nan, n):
    values = np.round(RNG.normal(size=shape), 1)
    if add_nan:
        values[..., RNG.random(n) < 0.25] = np.nan
    return values


@pytest.mark.parametrize("method", ["map-reduce", "cohorts"])
@pytest.mark.parametrize("add_nan", [False, True])
@pytest.mark.parametrize("func", MESH_FUNCS)
def test_sharded_matches_eager(mesh, func, add_nan, method):
    n, size = 111, 5  # deliberately not divisible by 8 (padding path)
    codes = RNG.integers(0, size, n).astype(np.int64)
    values = _data((n,), add_nan, n)
    fkw = {"ddof": 1} if "var" in func or "std" in func else {}

    eager, _ = groupby_reduce(values, codes, func=func, engine="jax", finalize_kwargs=fkw)
    sharded, _ = groupby_reduce(
        values, codes, func=func, method=method, mesh=mesh, finalize_kwargs=fkw
    )
    np.testing.assert_allclose(
        np.asarray(sharded).astype(np.float64),
        np.asarray(eager).astype(np.float64),
        rtol=1e-12,
        atol=1e-12,
        equal_nan=True,
    )


@pytest.mark.parametrize("func", ["sum", "nanmean", "var", "max", "first", "nanargmax"])
def test_sharded_2d(mesh, func):
    n, size = 64, 4
    codes = RNG.integers(0, size, n).astype(np.int64)
    values = _data((3, n), True, n)
    eager, _ = groupby_reduce(values, codes, func=func, engine="jax")
    sharded, _ = groupby_reduce(values, codes, func=func, method="map-reduce", mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(sharded).astype(np.float64),
        np.asarray(eager).astype(np.float64),
        rtol=1e-12, atol=1e-12, equal_nan=True,
    )


def test_sharded_expected_groups(mesh):
    labels = np.array([1, 1, 3, 3, 5] * 10)
    vals = np.arange(50.0)
    sharded, groups = groupby_reduce(
        vals, labels, func="nanmean", method="map-reduce", mesh=mesh,
        expected_groups=np.array([1, 2, 3, 4, 5]),
    )
    eager, _ = groupby_reduce(
        vals, labels, func="nanmean", expected_groups=np.array([1, 2, 3, 4, 5])
    )
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(eager), equal_nan=True)


def test_blockwise_shard_local_groups(mesh):
    # groups aligned with shards: each shard owns whole groups
    ndev = len(jax.devices())
    per = 16
    n = ndev * per
    codes = np.repeat(np.arange(ndev), per).astype(np.int64)  # group d on shard d
    values = np.round(RNG.normal(size=n), 1)
    sharded, _ = groupby_reduce(values, codes, func="sum", method="blockwise", mesh=mesh)
    eager, _ = groupby_reduce(values, codes, func="sum", engine="jax")
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(eager), rtol=1e-12)


def test_blockwise_order_stats(mesh):
    # median/quantile on the mesh via blockwise (whole groups per shard)
    ndev = len(jax.devices())
    per = 16
    n = ndev * per
    codes = np.repeat(np.arange(ndev), per).astype(np.int64)
    values = np.round(RNG.normal(size=n), 1)
    sharded, _ = groupby_reduce(values, codes, func="nanmedian", method="blockwise", mesh=mesh)
    eager, _ = groupby_reduce(values, codes, func="nanmedian", engine="jax")
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(eager), rtol=1e-12, atol=1e-12)


def test_order_stats_mapreduce_raises(mesh):
    with pytest.raises(NotImplementedError, match="blockwise"):
        groupby_reduce(
            np.arange(8.0), np.array([0, 1] * 4), func="median",
            method="map-reduce", mesh=mesh,
        )


def test_sharded_min_count(mesh):
    labels = np.array([0, 0, 1] * 8)
    vals = np.array([1.0, np.nan, np.nan] * 8)
    sharded, _ = groupby_reduce(
        vals, labels, func="nansum", min_count=20, method="map-reduce", mesh=mesh
    )
    np.testing.assert_allclose(np.asarray(sharded), [np.nan, np.nan], equal_nan=True)


@pytest.mark.parametrize("func", ["cumsum", "nancumsum", "ffill", "bfill"])
@pytest.mark.parametrize("add_nan", [False, True])
def test_sharded_scan_matches_eager(mesh, func, add_nan):
    n = 117  # non-divisible: padding path
    codes = RNG.integers(0, 5, n).astype(np.int64)
    values = _data((n,), add_nan, n)
    eager = np.asarray(groupby_scan(values, codes, func=func, engine="jax"))
    sharded = np.asarray(groupby_scan(values, codes, func=func, method="blelloch"))
    np.testing.assert_allclose(sharded, eager, rtol=1e-12, atol=1e-12, equal_nan=True)


def test_sharded_scan_2d(mesh):
    n = 64
    codes = RNG.integers(0, 4, n).astype(np.int64)
    values = _data((3, n), True, n)
    eager = np.asarray(groupby_scan(values, codes, func="nancumsum", engine="jax"))
    sharded = np.asarray(groupby_scan(values, codes, func="nancumsum", method="blelloch"))
    np.testing.assert_allclose(sharded, eager, rtol=1e-12, atol=1e-12, equal_nan=True)


def test_reshard_for_blockwise_order_stats(mesh):
    # arbitrary (interleaved) labels -> resharded -> blockwise median works
    from flox_tpu.rechunk import reshard_for_blockwise

    n = 200
    codes = RNG.integers(0, 7, n).astype(np.int64)
    values = np.round(RNG.normal(size=n), 1)
    layout = reshard_for_blockwise(codes, len(jax.devices()))
    arr2 = np.asarray(layout.apply(values))
    sharded, _ = groupby_reduce(
        arr2, layout.codes, func="nanmedian", method="blockwise", mesh=mesh,
        expected_groups=np.arange(7),
    )
    eager, _ = groupby_reduce(values, codes, func="nanmedian", engine="jax")
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(eager), rtol=1e-12, atol=1e-12)


def test_sharded_min_count_with_finalize(mesh):
    # min_count must not leak the appended count into agg.finalize (mean/var)
    labels = np.tile(np.array([0, 0, 1]), 8)
    vals = np.tile(np.array([1.0, 3.0, np.nan]), 8)
    for func, fkw in [("nanmean", {}), ("nanvar", {"ddof": 1}), ("nanargmax", {})]:
        sharded, _ = groupby_reduce(
            vals, labels, func=func, min_count=2, method="map-reduce", mesh=mesh,
            finalize_kwargs=fkw,
        )
        eager, _ = groupby_reduce(
            vals, labels, func=func, min_count=2, engine="jax", finalize_kwargs=fkw
        )
        np.testing.assert_allclose(
            np.asarray(sharded).astype(float), np.asarray(eager).astype(float),
            equal_nan=True, err_msg=func,
        )


def test_sharded_datetime_minmax(mesh):
    # empty-shard fill must not masquerade as NaT (few elements, many shards)
    dt = np.array(["2020-01-03", "2020-01-01", "NaT", "2020-01-05"], dtype="datetime64[ns]")
    labels = np.array([0, 0, 1, 1])
    for func in ["max", "nanmax", "min", "nanmin"]:
        sharded, _ = groupby_reduce(dt, labels, func=func, method="map-reduce", mesh=mesh)
        eager, _ = groupby_reduce(dt, labels, func=func, engine="numpy")
        np.testing.assert_array_equal(np.asarray(sharded), np.asarray(eager), err_msg=func)


def test_sharded_program_cache(mesh):
    from flox_tpu.parallel.mapreduce import _PROGRAM_CACHE

    _PROGRAM_CACHE.clear()
    labels = np.arange(64) % 4
    vals = np.arange(64.0)
    for _ in range(3):
        groupby_reduce(vals, labels, func="nanmean", method="map-reduce", mesh=mesh)
    assert len(_PROGRAM_CACHE) == 1


@pytest.mark.parametrize("func", ["nanmean", "nanvar", "max", "nanargmax", "first"])
def test_two_axis_mesh(func):
    # 2-D (dcn, ici)-style mesh: the reduced axis shards over both axes
    mesh2 = make_mesh(shape=(2, 4), axis_names=("dcn", "ici"))
    n = 103
    codes = RNG.integers(0, 5, n).astype(np.int64)
    values = _data((n,), True, n)
    eager, _ = groupby_reduce(values, codes, func=func, engine="jax")
    sharded, _ = groupby_reduce(
        values, codes, func=func, method="map-reduce", mesh=mesh2,
        axis_name=("dcn", "ici"),
    )
    np.testing.assert_allclose(
        np.asarray(sharded).astype(np.float64),
        np.asarray(eager).astype(np.float64),
        rtol=1e-12, atol=1e-12, equal_nan=True,
    )


def test_two_axis_mesh_cohorts_and_scan():
    mesh2 = make_mesh(shape=(2, 4), axis_names=("dcn", "ici"))
    n = 96
    codes = RNG.integers(0, 6, n).astype(np.int64)
    values = _data((n,), False, n)
    eager, _ = groupby_reduce(values, codes, func="nansum", engine="jax")
    sharded, _ = groupby_reduce(
        values, codes, func="nansum", method="cohorts", mesh=mesh2,
        axis_name=("dcn", "ici"),
    )
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(eager), rtol=1e-12, atol=1e-14)
    # distributed scan over the 2-D mesh
    from flox_tpu.parallel.scan import sharded_groupby_scan
    from flox_tpu.aggregations import SCANS

    out = np.asarray(
        sharded_groupby_scan(values, codes, SCANS["cumsum"], size=6, mesh=mesh2,
                             axis_name=("dcn", "ici"))
    )
    eager_s = np.asarray(groupby_scan(values, codes, func="cumsum", engine="jax"))
    np.testing.assert_allclose(out, eager_s, rtol=1e-12, atol=1e-14)


def test_mesh_missing_axis_errors():
    mesh2 = make_mesh(shape=(2, 4), axis_names=("dcn", "ici"))
    with pytest.raises(ValueError, match="no axes"):
        groupby_reduce(
            np.arange(16.0), np.arange(16) % 2, func="sum",
            method="map-reduce", mesh=mesh2, axis_name="bogus",
        )


def test_pre_sharded_input(mesh):
    # a user array already placed with a NamedSharding flows through the
    # mesh path without a host round-trip
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = 128
    codes = RNG.integers(0, 4, n).astype(np.int64)
    values = _data((n,), False, n)
    sharded_vals = jax.device_put(jnp.asarray(values), NamedSharding(mesh, P("data")))
    out, _ = groupby_reduce(sharded_vals, codes, func="nanmean", method="map-reduce", mesh=mesh)
    eager, _ = groupby_reduce(values, codes, func="nanmean", engine="jax")
    np.testing.assert_allclose(np.asarray(out), np.asarray(eager), rtol=1e-12)


def test_partial_axis_on_mesh(mesh):
    # offset codes (per-row group spaces) shard over the flat span correctly
    labels = np.array([[0, 1, 0, 1] * 8, [1, 1, 0, 0] * 8])  # (2, 32)
    vals = np.round(RNG.normal(size=(2, 32)), 1)
    eager, _ = groupby_reduce(vals, labels, func="sum", engine="jax", axis=-1)
    sharded, _ = groupby_reduce(vals, labels, func="sum", axis=-1,
                                method="map-reduce", mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(sharded).astype(float), np.asarray(eager).astype(float),
        rtol=1e-12, atol=1e-12,
    )


def test_blockwise_multi_q_quantile(mesh):
    # vector q adds a leading dim; the blockwise owner-selection must
    # broadcast through it
    ndev = len(jax.devices())
    per = 16
    codes = np.repeat(np.arange(ndev), per).astype(np.int64)
    values = np.round(RNG.normal(size=ndev * per), 1)
    sharded, _ = groupby_reduce(
        values, codes, func="quantile", method="blockwise", mesh=mesh,
        finalize_kwargs={"q": [0.25, 0.5, 0.75]},
    )
    eager, _ = groupby_reduce(
        values, codes, func="quantile", engine="jax",
        finalize_kwargs={"q": [0.25, 0.5, 0.75]},
    )
    assert np.asarray(sharded).shape == (3, ndev)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(eager), rtol=1e-12, atol=1e-12)


def test_sharded_datetime_firstlast(mesh):
    dt = np.array(
        ["2020-01-03", "NaT", "2020-01-01", "2020-01-05", "NaT", "2020-01-02"],
        dtype="datetime64[ns]",
    )
    labels = np.array([0, 0, 0, 1, 1, 1])
    for func in ["first", "last", "nanfirst", "nanlast"]:
        sharded, _ = groupby_reduce(dt, labels, func=func, method="map-reduce", mesh=mesh)
        eager, _ = groupby_reduce(dt, labels, func=func, engine="numpy")
        np.testing.assert_array_equal(np.asarray(sharded), np.asarray(eager), err_msg=func)
