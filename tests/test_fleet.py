"""Fleet-observability test suite (ISSUE 13).

The contracts under test:

* **trace propagation** — a request carrying a W3C ``traceparent`` runs
  under THAT trace id with the remote parent span linked (``trace_parent``
  on root records), and the response echoes a ``traceparent`` with the
  same trace id — router→replica hops join into one trace;
* **replica identity** — generated request ids are replica-prefixed (two
  spawned processes never collide — the satellite regression), and with
  ``replica_id`` set every ``/metrics`` series and ``/debug/costs``
  payload carries ``replica``/``host`` labels (unset: byte-identical to
  the single-replica plane);
* **federation math** — merging two registries' histograms over the
  shared ``HIST_EDGES_MS`` edges preserves total count, sum, and a p99
  within one bucket of observing everything in one registry; mismatched
  edges reject loudly; counters sum; cost ledgers union;
* **mesh trace joining** — per-process jsonl exports merge into one
  Perfetto trace with a distinct named track per process, wall-anchored
  timestamps, and cross-process flow arrows for shared trace ids;
* **neutrality** — results are bit-identical with the whole fleet plane
  (replica id + propagation + telemetry) on.
"""

from __future__ import annotations

import asyncio
import http.server
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import flox_tpu
from flox_tpu import cache, exposition, fleet, telemetry
from flox_tpu.core import groupby_reduce
from flox_tpu.serve import AggregationRequest, Dispatcher
from flox_tpu.telemetry import HIST_EDGES_MS, METRICS
from tools import trace_join

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACE32 = "ab" * 16
SPAN16 = "cd" * 8
TRACEPARENT = f"00-{TRACE32}-{SPAN16}-01"


@pytest.fixture(autouse=True)
def _clean_plane():
    with flox_tpu.set_options(
        telemetry=False, telemetry_export_path=None, flight_recorder_path=None,
        replica_id=None, serve_aot_dir=None, autotune=False,
    ):
        cache.clear_all()
        telemetry.reset()  # clear_all leaves the span buffer to reset()
        exposition.set_ready(False)
        yield
        cache.clear_all()
        telemetry.reset()
    exposition.stop_metrics_server()
    exposition.set_ready(False)


def _payload(n=48, ngroups=5, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=n).astype(np.float64), rng.integers(0, ngroups, size=n)


# ---------------------------------------------------------------------------
# W3C trace-context helpers
# ---------------------------------------------------------------------------


class TestTraceparent:
    def test_parse_valid(self):
        assert telemetry.parse_traceparent(TRACEPARENT) == (TRACE32, SPAN16)

    @pytest.mark.parametrize(
        "bad",
        [
            None, 7, "", "garbage", TRACEPARENT.upper(),
            f"ff-{TRACE32}-{SPAN16}-01",            # forbidden version
            f"00-{'0' * 32}-{SPAN16}-01",           # all-zero trace id
            f"00-{TRACE32}-{'0' * 16}-01",          # all-zero parent
            f"00-{TRACE32}-{SPAN16}",               # missing flags
            f"00-{TRACE32[:-2]}-{SPAN16}-01",       # short trace id
        ],
    )
    def test_parse_rejects_malformed(self, bad):
        assert telemetry.parse_traceparent(bad) is None

    def test_format_round_trips(self):
        out = telemetry.format_traceparent(TRACE32, SPAN16)
        assert out == TRACEPARENT
        assert telemetry.parse_traceparent(out) == (TRACE32, SPAN16)

    def test_format_hashes_non_hex_ids(self):
        out = telemetry.format_traceparent("req-7")
        parsed = telemetry.parse_traceparent(out)
        assert parsed is not None
        # stable: the same request id always lands on the same trace id
        assert out.split("-")[1] == telemetry.format_traceparent("req-7").split("-")[1]

    def test_new_span_hex_unique(self):
        ids = {telemetry.new_span_hex() for _ in range(64)}
        assert len(ids) == 64
        assert all(len(s) == 16 for s in ids)

    def test_trace_parent_rides_root_records(self):
        with flox_tpu.set_options(telemetry=True):
            with telemetry.trace(TRACE32, parent=SPAN16):
                with telemetry.span("outer"):
                    with telemetry.span("inner"):
                        pass
            records = telemetry.drain()
        outer = next(r for r in records if r["name"] == "outer")
        inner = next(r for r in records if r["name"] == "inner")
        assert outer["trace"] == inner["trace"] == TRACE32
        # root-level record links the REMOTE parent; the child is already
        # linked locally through its span parent
        assert outer.get("trace_parent") == SPAN16
        assert "trace_parent" not in inner
        assert telemetry.current_trace_parent() is None


# ---------------------------------------------------------------------------
# dispatcher propagation + replica-prefixed request ids
# ---------------------------------------------------------------------------


class TestDispatcherPropagation:
    def _submit(self, **kw):
        values, labels = _payload()

        async def go():
            d = Dispatcher()
            result = await d.submit(
                AggregationRequest(func="sum", array=values, by=labels, **kw)
            )
            await d.close()
            return result

        return asyncio.run(go())

    def test_traceparent_runs_and_echoes_same_trace_id(self):
        with flox_tpu.set_options(telemetry=True):
            result = self._submit(traceparent=TRACEPARENT, request_id="r1")
            records = telemetry.drain()
        assert result.trace_id == TRACE32
        parsed = telemetry.parse_traceparent(result.traceparent)
        assert parsed is not None and parsed[0] == TRACE32
        # the echoed parent span is THIS replica's hop, not the caller's
        assert parsed[1] != SPAN16
        spans = [r for r in records if r.get("type") == "span"]
        assert spans and all(r.get("trace") == TRACE32 for r in spans)
        roots = [r for r in spans if r.get("parent") is None]
        assert roots and all(r.get("trace_parent") == SPAN16 for r in roots)

    def test_without_traceparent_request_id_roots_the_trace(self):
        with flox_tpu.set_options(telemetry=True):
            result = self._submit(request_id="solo-1")
        assert result.trace_id == "solo-1"
        assert result.traceparent is None

    def test_malformed_traceparent_ignored_and_counted(self):
        with flox_tpu.set_options(telemetry=True):
            result = self._submit(traceparent="not-a-traceparent", request_id="m1")
        assert result.trace_id == "m1"
        assert result.traceparent is None
        assert METRICS.get("serve.bad_traceparent") == 1

    def test_failed_traced_request_keeps_trace_context(self):
        """Fault path: a traced request whose execution fails still emits
        its records under the propagated trace id (the error is exactly
        when the joined trace matters), and the failure surfaces typed."""
        values, labels = _payload()

        async def go():
            d = Dispatcher()
            with pytest.raises(Exception, match="no_such_agg"):
                await d.submit(
                    AggregationRequest(
                        func="no_such_agg", array=values, by=labels,
                        traceparent=TRACEPARENT,
                    )
                )
            await d.close()

        with flox_tpu.set_options(telemetry=True):
            asyncio.run(go())
            records = telemetry.drain()
        traced = [r for r in records if r.get("trace") == TRACE32]
        assert traced, records
        roots = [r for r in traced if r.get("parent") is None]
        assert roots and all(r.get("trace_parent") == SPAN16 for r in roots)

    def test_generated_ids_are_replica_prefixed(self):
        with flox_tpu.set_options(replica_id="rep-a"):
            result = self._submit()
        assert result.request_id.startswith("rep-a:req-")
        # unconfigured replicas fall back to a per-process prefix
        result = self._submit()
        assert result.request_id.startswith(f"p{os.getpid()}:req-")

    def test_generated_ids_unique_across_two_spawned_processes(self, tmp_path):
        """The satellite regression: two replicas behind one router must
        never emit colliding generated request ids, even when nobody set
        a replica_id."""
        script = (
            "import asyncio, json, sys\n"
            "import numpy as np\n"
            "from flox_tpu.serve import AggregationRequest, Dispatcher\n"
            "async def go():\n"
            "    d = Dispatcher()\n"
            "    ids = []\n"
            "    for _ in range(3):\n"
            "        r = await d.submit(AggregationRequest(\n"
            "            func='sum', array=np.arange(4.0), by=np.array([0, 0, 1, 1])))\n"
            "        ids.append(r.request_id)\n"
            "    await d.close()\n"
            "    return ids\n"
            "print(json.dumps(asyncio.run(go())))\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        for var in (
            "FLOX_TPU_REPLICA_ID", "FLOX_TPU_TELEMETRY",
            "FLOX_TPU_TELEMETRY_EXPORT_PATH",
        ):
            env.pop(var, None)
        id_sets = []
        for _ in range(2):
            proc = subprocess.run(
                [sys.executable, "-c", script], cwd=REPO, env=env,
                capture_output=True, text=True, timeout=240,
            )
            assert proc.returncode == 0, proc.stderr
            id_sets.append(set(json.loads(proc.stdout.strip().splitlines()[-1])))
        assert len(id_sets[0]) == len(id_sets[1]) == 3
        assert not (id_sets[0] & id_sets[1]), id_sets


# ---------------------------------------------------------------------------
# replica identity on the exposition surfaces
# ---------------------------------------------------------------------------


class TestReplicaIdentity:
    def test_metrics_series_carry_replica_and_host_labels(self):
        with flox_tpu.set_options(telemetry=True, replica_id="rep-a"):
            METRICS.inc("serve.requests")
            METRICS.set_gauge("serve.queue_depth", 1)
            METRICS.observe("serve.request_ms", 0.5)
            text = exposition.prometheus_text()
        host = telemetry.host_name()
        assert f'flox_tpu_serve_requests_total{{replica="rep-a",host="{host}"}} 1' in text
        assert f'flox_tpu_serve_queue_depth{{replica="rep-a",host="{host}"}} 1' in text
        assert f'replica="rep-a",host="{host}",le="+Inf"' in text
        assert f'flox_tpu_serve_request_ms_sum{{replica="rep-a",host="{host}"}}' in text

    def test_identity_merges_ahead_of_tenant_labels(self):
        with flox_tpu.set_options(telemetry=True, replica_id="rep-a"):
            METRICS.observe("serve.request_ms|tenant=acme", 0.5)
            text = exposition.prometheus_text()
        assert 'replica="rep-a"' in text and 'tenant="acme"' in text
        line = next(l for l in text.splitlines() if "tenant=" in l)
        assert line.index("replica=") < line.index("tenant=")

    def test_unset_replica_keeps_output_unlabeled(self):
        with flox_tpu.set_options(telemetry=True):
            METRICS.inc("serve.requests")
            text = exposition.prometheus_text()
        assert "flox_tpu_serve_requests_total 1" in text
        assert "replica=" not in text

    def test_costs_payload_carries_identity(self):
        with flox_tpu.set_options(telemetry=True, replica_id="rep-a"):
            body, status = exposition._Handler._costs("")
        assert status == 200
        payload = json.loads(body)
        assert payload["replica"] == "rep-a"
        assert payload["host"] == telemetry.host_name()

    def test_records_stamped_with_replica(self):
        with flox_tpu.set_options(telemetry=True, replica_id="rep-a"):
            with telemetry.span("stamped"):
                pass
            records = telemetry.drain()
        assert all(r.get("replica") == "rep-a" for r in records)

    @pytest.mark.parametrize(
        "bad", ['inject"l', "a replica", "x" * 65, "", 7]
    )
    def test_replica_id_validated_at_set_time(self, bad):
        with pytest.raises(ValueError):
            flox_tpu.set_options(replica_id=bad)

    def test_new_options_have_env_mirrors_and_validators(self):
        from flox_tpu import options as opt

        for name, env in (
            ("replica_id", "FLOX_TPU_REPLICA_ID"),
            ("fleet_scrape_interval", "FLOX_TPU_FLEET_SCRAPE_INTERVAL"),
            ("fleet_port", "FLOX_TPU_FLEET_PORT"),
            ("fleet_replicas", "FLOX_TPU_FLEET_REPLICAS"),
        ):
            assert name in opt.OPTIONS
            assert name in opt._VALIDATORS
            # the env constant appears in the source (FLX010's contract)
            src = open(os.path.join(REPO, "flox_tpu", "options.py")).read()
            assert env in src
        with pytest.raises(ValueError):
            flox_tpu.set_options(fleet_scrape_interval=-1)
        with pytest.raises(ValueError):
            flox_tpu.set_options(fleet_port=70000)
        with pytest.raises(ValueError):
            flox_tpu.set_options(fleet_replicas="")


# ---------------------------------------------------------------------------
# /debug/costs query filters (satellite)
# ---------------------------------------------------------------------------


class TestCostsFilters:
    def _seed_ledger(self):
        telemetry.observe_cost("prog-hot", device_ms=50.0, nbytes=100)
        telemetry.observe_cost("prog-warm", device_ms=5.0, nbytes=10)
        telemetry.observe_cost("prog-cold", device_ms=0.5, nbytes=1)
        telemetry.observe_cost(tenant=telemetry.tenant_label("acme"), device_ms=9.0)
        telemetry.observe_cost(tenant=telemetry.tenant_label("globex"), device_ms=1.0)

    def test_top_keeps_k_most_expensive_rows(self):
        with flox_tpu.set_options(telemetry=True):
            self._seed_ledger()
            body, status = exposition._Handler._costs("top=2")
        assert status == 200
        payload = json.loads(body)
        assert sorted(payload["cost_by_program"]) == ["prog-hot", "prog-warm"]
        assert len(payload["cost_by_tenant"]) <= 2

    def test_tenant_filter_narrows_tenant_axis(self):
        with flox_tpu.set_options(telemetry=True):
            self._seed_ledger()
            body, status = exposition._Handler._costs("tenant=acme")
        payload = json.loads(body)
        assert list(payload["cost_by_tenant"]) == ["acme"]
        # read-side filtering never burns a cardinality slot
        assert "no-such-tenant" not in telemetry._TENANT_LABELS
        body, _ = exposition._Handler._costs("tenant=no-such-tenant")
        assert json.loads(body)["cost_by_tenant"] == {}
        assert "no-such-tenant" not in telemetry._TENANT_LABELS

    def test_malformed_top_is_400(self):
        with flox_tpu.set_options(telemetry=True):
            body, status = exposition._Handler._costs("top=banana")
            assert status == 400
            body, status = exposition._Handler._costs("top=0")
            assert status == 400

    def test_costs_cli_reads_filtered_scrape(self, tmp_path, capsys):
        with flox_tpu.set_options(telemetry=True, replica_id="rep-a"):
            self._seed_ledger()
            body, _ = exposition._Handler._costs("top=1")
        scrape = tmp_path / "costs.json"
        scrape.write_text(body.decode())
        assert telemetry.main(["costs", str(scrape)]) == 0
        out = capsys.readouterr().out
        assert "prog-hot" in out and "prog-warm" not in out
        assert "(replica rep-a)" in out


# ---------------------------------------------------------------------------
# flight-recorder header snapshot (satellite)
# ---------------------------------------------------------------------------


class TestFlightHeaderSnapshot:
    def test_header_carries_breakers_and_saturation(self, tmp_path):
        path = tmp_path / "flight.jsonl"
        with flox_tpu.set_options(
            telemetry=True, flight_recorder_path=str(path), replica_id="rep-a"
        ):
            METRICS.set_gauge("serve.queue_depth", 7)
            with telemetry.span("work"):
                pass
            assert telemetry.flight_dump(reason="test") == str(path)
        header = json.loads(path.read_text().splitlines()[0])
        attrs = header["attrs"]
        assert attrs["replica"] == "rep-a"
        assert attrs["host"] == telemetry.host_name()
        assert attrs["breakers"]["total"] == 0 and "tripped" in attrs["breakers"]
        assert attrs["saturation"]["serve.queue_depth"] == 7
        assert set(attrs["saturation"]) == set(telemetry.SATURATION_GAUGES)

    def test_header_breakers_reflect_open_state(self, tmp_path):
        from flox_tpu.serve import breaker

        path = tmp_path / "flight.jsonl"
        with flox_tpu.set_options(
            telemetry=True, flight_recorder_path=str(path),
            serve_breaker_threshold=1,
        ):
            breaker.record_failure(("pkey",), "sum#x")
            with telemetry.span("work"):
                pass
            telemetry.flight_dump(reason="test")
        header = json.loads(path.read_text().splitlines()[0])
        assert header["attrs"]["breakers"]["open"] == 1


# ---------------------------------------------------------------------------
# histogram merge math (satellite)
# ---------------------------------------------------------------------------


def _observe_registry(samples, name="serve.request_ms", exemplars=False):
    registry = telemetry.MetricsRegistry()
    for i, value in enumerate(samples):
        registry.observe(
            name, value, exemplar=f"req-{i}" if exemplars else None
        )
    return registry


def _parsed_hist(registry, name="serve.request_ms"):
    """A registry histogram in the fleet's parsed-scrape shape."""
    hist = registry.histograms()[name]
    return {
        "edges": list(HIST_EDGES_MS),
        "counts": list(hist["counts"]),
        "sum": hist["sum"],
        "count": hist["count"],
        "exemplars": {k: list(v) for k, v in hist["exemplars"].items()},
    }


class TestHistogramMergeMath:
    def test_merge_preserves_count_sum_and_p99_within_one_bucket(self):
        rng = np.random.default_rng(42)
        a = rng.lognormal(mean=0.0, sigma=1.5, size=400).tolist()
        b = rng.lognormal(mean=1.0, sigma=1.0, size=300).tolist()
        merged = fleet.merge_histograms(
            _parsed_hist(_observe_registry(a)), _parsed_hist(_observe_registry(b))
        )
        oracle = _observe_registry(a + b)
        assert merged["count"] == len(a) + len(b)
        assert merged["sum"] == pytest.approx(sum(a) + sum(b))
        assert merged["counts"] == list(oracle.histograms()["serve.request_ms"]["counts"])
        merged_p99 = fleet._hist_percentile(merged, 0.99)
        oracle_p99 = oracle.percentile("serve.request_ms", 0.99)
        # same bucket vector -> the merged p99 lands in the oracle's
        # holding bucket (the registry clamps to observed max, the scrape
        # path cannot — so compare at bucket granularity)
        bucket = next(
            i for i, e in enumerate(HIST_EDGES_MS) if merged_p99 <= e
        )
        lo = HIST_EDGES_MS[bucket - 1] if bucket else 0.0
        assert lo <= oracle_p99 <= HIST_EDGES_MS[bucket]

    def test_exemplars_max_merge_per_bucket(self):
        a = _parsed_hist(_observe_registry([0.5, 3.0], exemplars=True))
        b = _parsed_hist(_observe_registry([0.6, 2.5], exemplars=True))
        merged = fleet.merge_histograms(a, b)
        bucket = next(i for i, e in enumerate(HIST_EDGES_MS) if 0.6 <= e)
        # b's 0.6 beats a's 0.5 in the shared bucket
        assert merged["exemplars"][bucket][1] == 0.6
        bucket3 = next(i for i, e in enumerate(HIST_EDGES_MS) if 3.0 <= e)
        assert merged["exemplars"][bucket3][1] == 3.0

    def test_mismatched_edges_reject_loudly(self):
        a = _parsed_hist(_observe_registry([1.0]))
        b = _parsed_hist(_observe_registry([1.0]))
        b["edges"] = [e * 2 for e in b["edges"]]
        with pytest.raises(fleet.FleetMergeError, match="edges differ"):
            fleet.merge_histograms(a, b)
        b["edges"] = b["edges"][:-1]
        with pytest.raises(fleet.FleetMergeError):
            fleet.merge_histograms(a, b)

    def test_cost_rows_union(self):
        a = {"dispatches": 2, "device_ms": 10.0, "device_ms_max": 8.0,
             "bytes": 100, "compiles": 1, "compile_ms": 50.0,
             "hbm_peak": 1000.0, "last_slow_trace": "req-a"}
        b = {"dispatches": 3, "device_ms": 4.0, "device_ms_max": 3.0,
             "bytes": 50, "compiles": 0, "compile_ms": 0.0,
             "hbm_peak": 2000.0, "last_slow_trace": "req-b"}
        merged = fleet.merge_cost_rows(a, b)
        assert merged["dispatches"] == 5
        assert merged["device_ms"] == pytest.approx(14.0)
        assert merged["bytes"] == 150
        assert merged["hbm_peak"] == 2000.0
        # the slow-trace link follows the fleet-wide worst dispatch
        assert merged["device_ms_max"] == 8.0
        assert merged["last_slow_trace"] == "req-a"


# ---------------------------------------------------------------------------
# federation end to end (fake replicas over real HTTP)
# ---------------------------------------------------------------------------


class _FakeReplica:
    """A canned replica endpoint: /metrics + /debug/costs +
    /debug/programs + /readyz."""

    def __init__(self, metrics_text, costs=None, ready=True, reason="ready",
                 programs=None):
        self.metrics_text = metrics_text
        self.costs = costs or {}
        self.ready = ready
        self.reason = reason
        self.programs = programs
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.partition("?")[0]
                if path == "/metrics":
                    body, status = outer.metrics_text.encode(), 200
                elif path == "/debug/costs":
                    body, status = json.dumps(outer.costs).encode(), 200
                elif path == "/debug/programs":
                    if outer.programs is None:
                        # a replica running with the costmodel plane off
                        # (or an older build) simply has no endpoint
                        body, status = b"not found\n", 404
                    else:
                        body = json.dumps({"programs": outer.programs}).encode()
                        status = 200
                elif path == "/readyz":
                    body = outer.reason.encode() + b"\n"
                    status = 200 if outer.ready else 503
                else:
                    body, status = b"nope", 404
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _replica_text(replica, requests, latency_ms):
    with flox_tpu.set_options(telemetry=True, replica_id=replica):
        METRICS.inc("serve.requests", requests)
        METRICS.set_gauge("serve.queue_depth", 1)
        METRICS.observe("serve.request_ms", latency_ms, exemplar=f"{replica}:req-1")
        text = exposition.prometheus_text(exemplars=True)
    telemetry.reset()
    return text


class TestFederation:
    def test_scrape_and_merge_two_replicas(self):
        row = {"dispatches": 1, "device_ms": 2.0, "device_ms_max": 2.0,
               "bytes": 64, "compiles": 0, "compile_ms": 0.0,
               "hbm_peak": 0.0, "last_slow_trace": "a:req-1"}
        a = _FakeReplica(
            _replica_text("a", 3, 1.0),
            costs={"cost_by_program": {"sum#1": row}, "cost_by_tenant": {}},
        )
        b = _FakeReplica(
            _replica_text("b", 5, 4.0),
            costs={"cost_by_program": {"sum#1": dict(row, device_ms=6.0,
                                                     device_ms_max=6.0,
                                                     last_slow_trace="b:req-1")},
                   "cost_by_tenant": {}},
            ready=False, reason="draining",
        )
        try:
            federator = fleet.Federator([("a", a.url), ("b", b.url)], interval=60)
            view = federator.scrape_once()
            # counters: per-replica series + fleet sum
            slot = view["counters"][("flox_tpu_serve_requests_total", ())]
            assert slot["replicas"] == {"a": 3.0, "b": 5.0}
            assert slot["total"] == 8.0
            # histograms: bucket-summed
            merged = view["histograms"][("flox_tpu_serve_request_ms", ())]["merged"]
            assert merged["count"] == 2
            # ledgers: unioned, slow-trace follows the fleet-wide max
            fused = view["cost_by_program"]["sum#1"]
            assert fused["dispatches"] == 2
            assert fused["last_slow_trace"] == "b:req-1"
            # readiness table
            states = {r["replica"]: (r["ready"], r["reason"]) for r in view["replicas"]}
            assert states["a"] == (True, "ready")
            assert states["b"] == (False, "draining")
            # rendered text: distinct replica labels + the unlabeled sum
            text = fleet.render_prometheus(view)
            assert 'flox_tpu_serve_requests_total{replica="a"} 3' in text
            assert 'flox_tpu_serve_requests_total{replica="b"} 5' in text
            assert "\nflox_tpu_serve_requests_total 8" in text
            assert "flox_tpu_fleet_replicas 2" in text
            assert "flox_tpu_fleet_replicas_ready 1" in text
        finally:
            a.close()
            b.close()

    def test_unreachable_replica_is_a_row_not_a_crash(self):
        a = _FakeReplica(_replica_text("a", 1, 1.0))
        try:
            federator = fleet.Federator(
                [("a", a.url), ("dead", "http://127.0.0.1:1")],
                interval=60, timeout=1.0,
            )
            view = federator.scrape_once()
            by_name = {r["name"]: r for r in view["replicas"]}
            assert by_name["a"]["ok"] and not by_name["dead"]["ok"]
            assert by_name["dead"]["error"]
            text = fleet.render_prometheus(view)
            assert "flox_tpu_fleet_scrape_errors 1" in text
        finally:
            a.close()

    def test_federator_http_endpoints(self):
        a = _FakeReplica(_replica_text("a", 2, 1.0))
        federator = None
        try:
            federator = fleet.Federator([("a", a.url)], interval=60)
            federator.scrape_once()
            port = federator.serve(port=0)
            import urllib.request

            def get(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5
                ) as resp:
                    return resp.status, resp.read().decode()

            status, text = get("/metrics")
            assert status == 200
            assert 'flox_tpu_serve_requests_total{replica="a"} 2' in text
            status, body = get("/debug/costs")
            assert status == 200
            assert json.loads(body)["replica"] == "_fleet"
            status, body = get("/replicas")
            assert json.loads(body)[0]["replica"] == "a"
            status, _ = get("/readyz")
            assert status == 200
        finally:
            if federator is not None:
                federator.stop()
            a.close()

    def test_fleet_readyz_503_when_no_replica_ready(self):
        a = _FakeReplica(_replica_text("a", 1, 1.0), ready=False, reason="warming")
        federator = None
        try:
            federator = fleet.Federator([("a", a.url)], interval=60)
            federator.scrape_once()
            port = federator.serve(port=0)
            import urllib.error
            import urllib.request

            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"http://127.0.0.1:{port}/readyz", timeout=5)
            assert err.value.code == 503
        finally:
            if federator is not None:
                federator.stop()
            a.close()

    def test_rendered_metrics_have_one_type_line_per_metric(self):
        """A tenant-labeled series must not duplicate its base metric's
        TYPE line — a spec-compliant scraper drops the whole scrape on a
        second one."""
        with flox_tpu.set_options(telemetry=True, replica_id="a"):
            METRICS.observe("serve.request_ms", 1.0)
            METRICS.observe("serve.request_ms|tenant=acme", 0.5)
            text = exposition.prometheus_text(exemplars=True)
        telemetry.reset()
        snap = fleet.ReplicaSnapshot(
            name="a", url="http://x", ok=True,
            metrics=fleet.parse_metrics_text(text),
        )
        rendered = fleet.render_prometheus(fleet.federate([snap]))
        type_lines = [l for l in rendered.splitlines() if l.startswith("# TYPE")]
        assert len(type_lines) == len(set(type_lines)), type_lines

    def test_merge_error_poisons_every_label_set_of_the_metric(self):
        """After one label set's edges mismatch, sibling label sets of the
        same metric must not publish a stale partial merge as the fleet
        aggregate."""
        def snap(name, edges):
            hist = {"edges": edges, "counts": [1] * len(edges),
                    "sum": 1.0, "count": len(edges), "exemplars": {}}
            return fleet.ReplicaSnapshot(
                name=name, url=f"http://{name}", ok=True,
                metrics={
                    "counters": {}, "gauges": {}, "replica": name,
                    "histograms": {
                        ("m_ms", ()): dict(hist, counts=list(hist["counts"])),
                        ("m_ms", (("tenant", "acme"),)): dict(
                            hist, counts=list(hist["counts"])
                        ),
                    },
                },
            )

        view = fleet.federate([snap("a", [1.0, 2.0]), snap("b", [1.0, 4.0])])
        assert "m_ms" in view["merge_errors"]
        for slot in view["histograms"].values():
            assert slot["merged"] is None
        assert "m_ms_bucket{le=" not in fleet.render_prometheus(view).replace(
            'replica="a"', ""
        ).replace('replica="b"', "")

    def test_unescape_round_trips_escaped_backslash_n(self):
        raw = "a\\nb"  # literal backslash + n, NOT a newline
        with flox_tpu.set_options(telemetry=True, replica_id="a"):
            METRICS.observe("demo_ms", 0.5, exemplar=raw)
            text = exposition.prometheus_text(exemplars=True)
        telemetry.reset()
        parsed = fleet.parse_metrics_text(text)
        hist = parsed["histograms"][("flox_tpu_demo_ms", ())]
        (slot,) = hist["exemplars"].values()
        assert slot[0] == raw
        with flox_tpu.set_options(telemetry=True, replica_id="a"):
            METRICS.observe("demo2_ms", 0.5, exemplar="new\nline")
            text = exposition.prometheus_text(exemplars=True)
        telemetry.reset()
        hist = fleet.parse_metrics_text(text)["histograms"][("flox_tpu_demo2_ms", ())]
        (slot,) = hist["exemplars"].values()
        assert slot[0] == "new\nline"

    def test_multi_replica_scrape_rejected(self):
        merged_like = (
            "# TYPE flox_tpu_serve_requests_total counter\n"
            'flox_tpu_serve_requests_total{replica="a"} 3\n'
            'flox_tpu_serve_requests_total{replica="b"} 5\n'
        )
        with pytest.raises(ValueError, match="more than one replica"):
            fleet.parse_metrics_text(merged_like)

    def test_parse_replica_targets(self):
        targets = fleet.parse_replica_targets(
            "a=http://h:1, b=http://h:2 ,http://h:3"
        )
        assert targets == [
            ("a", "http://h:1"), ("b", "http://h:2"), ("h:3", "http://h:3")
        ]
        with pytest.raises(ValueError):
            fleet.parse_replica_targets(None)
        with pytest.raises(ValueError):
            fleet.parse_replica_targets("a=not-a-url")

    def test_render_top_frame(self):
        a = _FakeReplica(_replica_text("a", 4, 2.0))
        try:
            federator = fleet.Federator([("a", a.url)], interval=60)
            view = federator.scrape_once()
            frame = fleet.render_top(view, top=3)
            assert "a" in frame and "ready" in frame
            assert "top 3 cost rows" in frame
        finally:
            a.close()


def _program_row(digest, label, *, dispatches, device_ms, compile_ms=0.0,
                 predicted_ms=1.0):
    net = max(0.0, device_ms - compile_ms)
    return {
        "label": label, "digest": digest, "platform": "cpu",
        "flops": 100.0, "bytes_accessed": 800.0, "analysis": "ok",
        "predicted_ms": predicted_ms, "model_ms": 25.0,
        "hlo_hash": "cafe" * 4,
        "observed": {
            "dispatches": dispatches, "device_ms": device_ms,
            "device_ms_max": device_ms, "bytes": 64 * dispatches,
            "compiles": 0, "compile_ms": compile_ms, "hbm_peak": 0.0,
            "last_slow_trace": None,
        },
        "utilization": predicted_ms * dispatches / net if net else 0.0,
        "observed_ms_per_dispatch": net / dispatches if dispatches else None,
        "drift_ratio": (net / dispatches) / 25.0 if dispatches else None,
    }


class TestProgramCardFederation:
    def test_cards_union_by_digest_and_observed_merges(self):
        # ISSUE 14: two replicas serving the same compiled program (same
        # digest) union into one card whose observed rows merge like cost
        # rows and whose utilization recomputes from the merged totals
        a = _FakeReplica(
            _replica_text("a", 1, 1.0),
            programs={"bundle[sum]": _program_row(
                "d1", "bundle[sum]", dispatches=2, device_ms=10.0
            )},
        )
        b = _FakeReplica(
            _replica_text("b", 1, 1.0),
            programs={
                "bundle[sum]": _program_row(
                    "d1", "bundle[sum]", dispatches=3, device_ms=30.0
                ),
                "serve[sum#ab]": _program_row(
                    "d1", "serve[sum#ab]", dispatches=1, device_ms=5.0
                ),
            },
        )
        try:
            federator = fleet.Federator([("a", a.url), ("b", b.url)], interval=60)
            view = federator.scrape_once()
            progs = view["programs"]
            assert set(progs) == {"d1"}
            card = progs["d1"]
            assert sorted(card["labels"]) == ["bundle[sum]", "serve[sum#ab]"]
            assert card["observed"]["dispatches"] == 6
            assert card["observed"]["device_ms"] == pytest.approx(45.0)
            assert card["utilization"] == pytest.approx(1.0 * 6 / 45.0, abs=1e-6)
            # the console joins utilization onto the cost rows
            frame = fleet.render_top(view, top=3)
            assert "util" in frame
        finally:
            a.close()
            b.close()

    def test_planeless_replica_is_an_empty_table_not_an_error(self):
        a = _FakeReplica(_replica_text("a", 1, 1.0))  # 404s /debug/programs
        try:
            federator = fleet.Federator([("a", a.url)], interval=60)
            view = federator.scrape_once()
            assert view["programs"] == {}
            assert view["replicas"][0]["ok"]
        finally:
            a.close()


class TestTopJson:
    def test_render_top_json_is_machine_readable(self):
        a = _FakeReplica(
            _replica_text("a", 4, 2.0),
            programs={"bundle[sum]": _program_row(
                "d1", "bundle[sum]", dispatches=2, device_ms=10.0
            )},
        )
        try:
            federator = fleet.Federator([("a", a.url)], interval=60)
            view = federator.scrape_once()
            frame = fleet.render_top_json(view, top=3)
            text = json.dumps(frame)  # must be JSON-safe as-is
            parsed = json.loads(text)
            row = parsed["replicas"][0]
            assert row["replica"] == "a"
            assert row["state"] == "ready"
            assert row["queue_depth"] == 1
            assert row["qps"] is None  # first frame: nothing to diff
            assert parsed["programs"][0]["digest"] == "d1"
            assert isinstance(parsed["top_costs"], list)
        finally:
            a.close()

    def test_top_json_cli_once(self, capsys):
        # the satellite end to end: `fleet top --json --once` prints one
        # JSON document an alerting script can consume without scraping
        # the ANSI frame
        a = _FakeReplica(_replica_text("a", 2, 1.5))
        try:
            rc = fleet.main([
                "top", "--replicas", f"a={a.url}", "--json", "--once",
                "--interval", "60",
            ])
            assert rc == 0
            out = capsys.readouterr().out
            parsed = json.loads(out)
            assert parsed["replicas"][0]["replica"] == "a"
            assert "\x1b[2J" not in out  # --json implies no screen clear
        finally:
            a.close()


# ---------------------------------------------------------------------------
# trace joining across processes
# ---------------------------------------------------------------------------


def _export_process(tmp_path, replica, trace_id, parent=None, wall_skew=0.0):
    """Write one per-process-style jsonl export (in-process, using the
    real telemetry plumbing, then reset)."""
    path = tmp_path / f"{replica}.jsonl"
    with flox_tpu.set_options(
        telemetry=True, replica_id=replica, telemetry_export_path=None
    ):
        telemetry.anchor_event()
        with telemetry.trace(trace_id, parent=parent):
            with telemetry.span("serve.request"):
                with telemetry.span("dispatch"):
                    pass
        records = telemetry.drain()
        tail = telemetry._counters_record()
    if wall_skew:
        tail = dict(tail, anchor=dict(tail["anchor"], wall=tail["anchor"]["wall"] + wall_skew))
        for rec in records:
            if rec.get("name") == "clock-anchor":
                rec["attrs"]["wall"] += wall_skew
    with open(path, "w") as f:
        for rec in [*records, tail]:
            f.write(json.dumps(rec) + "\n")
    telemetry.reset()
    return path


class TestTraceJoin:
    def test_two_files_two_tracks_with_flow(self, tmp_path, capsys):
        pa = _export_process(tmp_path, "router", TRACE32)
        pb = _export_process(tmp_path, "rep-b", TRACE32, parent=SPAN16)
        out = tmp_path / "joined.json"
        assert trace_join.main([str(out), str(pa), str(pb)]) == 0
        assert "2 process track(s)" in capsys.readouterr().out
        payload = json.loads(out.read_text())
        events = payload["traceEvents"]
        names = {
            ev["args"]["name"]
            for ev in events
            if ev.get("ph") == "M" and ev["name"] == "process_name"
        }
        assert any("router" in n for n in names)
        assert any("rep-b" in n for n in names)
        pids = {ev["pid"] for ev in events if ev.get("ph") == "X"}
        assert len(pids) == 2
        # one cross-process flow for the shared trace id
        flows = [ev for ev in events if ev.get("ph") in ("s", "f")]
        assert {ev["ph"] for ev in flows} == {"s", "f"}
        assert all(ev["name"] == f"trace:{TRACE32}" for ev in flows)
        finish = next(ev for ev in flows if ev["ph"] == "f")
        assert finish["args"]["trace_parent"] == SPAN16
        # per-file identity rides floxTpuFleet
        assert {m["replica"] for m in payload["floxTpuFleet"]} == {"router", "rep-b"}

    def test_clock_alignment_orders_processes_by_wall(self, tmp_path):
        pa = _export_process(tmp_path, "early", "t-early")
        pb = _export_process(tmp_path, "late", "t-late", wall_skew=10.0)
        loaded = [
            (p.name, *trace_join.load_jsonl(str(p))) for p in (pa, pb)
        ]
        payload = trace_join.join_traces(loaded)
        spans = [ev for ev in payload["traceEvents"] if ev.get("ph") == "X"]
        early = [ev["ts"] for ev in spans if ev["pid"] == 1]
        late = [ev["ts"] for ev in spans if ev["pid"] == 2]
        # 10 s of wall skew separates the tracks on the shared timeline
        assert min(late) - min(early) > 9e6
        assert min(early) >= 0.0

    def test_duplicate_labels_rejected_and_deduped_by_cli(self, tmp_path):
        """Labels key the clock offsets: two inputs sharing a basename
        must get distinct labels (full paths), never one file's offset
        applied to the other's track."""
        (tmp_path / "a").mkdir()
        (tmp_path / "b").mkdir()
        pa = _export_process(tmp_path / "a", "export", "t-1")
        pb = _export_process(tmp_path / "b", "export", "t-2")
        with pytest.raises(ValueError, match="duplicate input labels"):
            trace_join.join_traces(
                [(p.name, *trace_join.load_jsonl(str(p))) for p in (pa, pb)]
            )
        labels = trace_join._unique_labels([str(pa), str(pb)])
        assert labels == [str(pa), str(pb)]
        out = tmp_path / "joined.json"
        assert trace_join.main([str(out), str(pa), str(pb)]) == 0
        payload = json.loads(out.read_text())
        assert len(payload["floxTpuFleet"]) == 2
        assert len({m["file"] for m in payload["floxTpuFleet"]}) == 2

    def test_malformed_jsonl_names_file_and_line(self, tmp_path):
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"type": "span"}\nnot json\n')
        with pytest.raises(ValueError, match=r"bad\.jsonl:2"):
            trace_join.load_jsonl(str(bad))

    def test_two_subprocess_exports_join(self, tmp_path):
        """Two real processes (no jax.distributed needed) export jsonl
        under distinct replica ids; the join carries both tracks."""
        script = (
            "import sys\n"
            "import flox_tpu\n"
            "from flox_tpu import telemetry\n"
            "from flox_tpu.core import groupby_reduce\n"
            "import numpy as np\n"
            "replica, out = sys.argv[1], sys.argv[2]\n"
            "flox_tpu.set_options(telemetry=True, replica_id=replica,\n"
            "                     telemetry_export_path=out)\n"
            "telemetry.anchor_event()\n"
            "with telemetry.trace('" + TRACE32 + "', parent='" + SPAN16 + "'):\n"
            "    groupby_reduce(np.arange(8.0), np.arange(8) % 2, func='sum')\n"
            "telemetry.flush()\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("FLOX_TPU_TELEMETRY_EXPORT_PATH", None)
        paths = []
        for replica in ("proc-a", "proc-b"):
            out = tmp_path / f"{replica}.jsonl"
            proc = subprocess.run(
                [sys.executable, "-c", script, replica, str(out)],
                cwd=REPO, env=env, capture_output=True, text=True, timeout=240,
            )
            assert proc.returncode == 0, proc.stderr
            paths.append(out)
        loaded = [(p.name, *trace_join.load_jsonl(str(p))) for p in paths]
        payload = trace_join.join_traces(loaded)
        assert {m["replica"] for m in payload["floxTpuFleet"]} == {"proc-a", "proc-b"}
        spans = [ev for ev in payload["traceEvents"] if ev.get("ph") == "X"]
        assert {ev["pid"] for ev in spans} == {1, 2}
        # the shared propagated trace id flows across both tracks
        flows = [ev for ev in payload["traceEvents"] if ev.get("ph") == "s"]
        assert len(flows) == 1

    @pytest.mark.slow
    def test_mesh_two_process_jax_distributed_smoke(self, tmp_path):
        """The first executable step of ROADMAP item 2's mesh harness: two
        CPU processes under one jax.distributed coordinator, each
        exporting a replica-stamped jsonl, joined into one trace with two
        ordered process tracks."""
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        script = (
            "import sys, os\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "import jax\n"
            "pid, port, out = int(sys.argv[1]), sys.argv[2], sys.argv[3]\n"
            "jax.distributed.initialize(\n"
            "    coordinator_address=f'127.0.0.1:{port}',\n"
            "    num_processes=2, process_id=pid)\n"
            "assert jax.process_count() == 2\n"
            "import flox_tpu\n"
            "from flox_tpu import telemetry\n"
            "from flox_tpu.core import groupby_reduce\n"
            "import numpy as np\n"
            "flox_tpu.set_options(telemetry=True, replica_id=f'mesh{pid}',\n"
            "                     telemetry_export_path=out)\n"
            "telemetry.anchor_event()\n"
            "with telemetry.trace('" + TRACE32 + "'):\n"
            "    groupby_reduce(np.arange(8.0), np.arange(8) % 2, func='sum')\n"
            "telemetry.flush()\n"
        )
        env = dict(os.environ)
        env.pop("FLOX_TPU_TELEMETRY_EXPORT_PATH", None)
        outs = [tmp_path / "mesh0.jsonl", tmp_path / "mesh1.jsonl"]
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", script, str(i), str(port), str(outs[i])],
                cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            for i in range(2)
        ]
        for proc in procs:
            try:
                _, err = proc.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                for p in procs:
                    p.kill()
                pytest.skip("jax.distributed coordinator did not converge")
            if proc.returncode != 0:
                pytest.skip(f"jax.distributed unavailable here: {err[-500:]}")
        loaded = [(p.name, *trace_join.load_jsonl(str(p))) for p in outs]
        payload = trace_join.join_traces(loaded)
        meta = {m["replica"]: m for m in payload["floxTpuFleet"]}
        assert set(meta) == {"mesh0", "mesh1"}
        # mesh identity recorded: distinct process indices, ordered tracks
        assert {meta[r]["process_index"] for r in meta} == {0, 1}
        spans = [ev for ev in payload["traceEvents"] if ev.get("ph") == "X"]
        assert {ev["pid"] for ev in spans} == {1, 2}


# ---------------------------------------------------------------------------
# neutrality: the whole fleet plane on changes no results
# ---------------------------------------------------------------------------


class TestFleetPlaneNeutrality:
    def test_bit_identity_with_fleet_plane_on(self):
        values, labels = _payload(seed=3)
        expect, groups_expect = groupby_reduce(values, labels, func="nanmean")
        with flox_tpu.set_options(telemetry=True, replica_id="rep-a"):
            with telemetry.trace(TRACE32, parent=SPAN16):
                got, groups = groupby_reduce(values, labels, func="nanmean")
        np.testing.assert_array_equal(np.asarray(expect), np.asarray(got))
        np.testing.assert_array_equal(np.asarray(groups_expect), np.asarray(groups))

    def test_serve_result_rows_identical_with_propagation(self):
        values, labels = _payload(seed=4)
        solo, _ = groupby_reduce(values, labels, func="sum")

        async def go():
            d = Dispatcher()
            result = await d.submit(
                AggregationRequest(
                    func="sum", array=values, by=labels, traceparent=TRACEPARENT
                )
            )
            await d.close()
            return result

        with flox_tpu.set_options(telemetry=True, replica_id="rep-a"):
            result = asyncio.run(go())
        np.testing.assert_array_equal(np.asarray(solo), np.asarray(result.result))
