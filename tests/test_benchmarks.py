"""Benchmarks-as-tests (parity: the reference's test_asv.py:1-22 runs its
asv classes in pytest so the suite cannot rot)."""

def test_benchmark_functions_run():
    import benchmarks

    out = []
    out += benchmarks.bench_reduce("numpy")
    out += benchmarks.bench_reduce_bare("numpy")
    out += benchmarks.bench_cohort_detection("small")
    assert all("bench" in r and "value" in r for r in out)
    methods = [r for r in out if r["bench"].startswith("track_method")]
    assert methods and methods[0]["value"] in ("cohorts", "map-reduce", "blockwise")


def test_headline_bench_shape():
    # bench.py must emit exactly one JSON line with the required keys
    import bench  # noqa: F401  (importable; full run needs the real chip)

    assert hasattr(bench, "main")


def test_accuracy_certification_runs_and_orders():
    # bench_accuracy.py (VERDICT r3 #2) at toy scale: the machinery must
    # stay runnable and the accumulation disciplines must keep their
    # ordering — dd correctly rounded, every path within f32 sanity bounds
    import bench_accuracy

    rec = bench_accuracy.run(cells=4, ntime=24 * 60, seed=0)
    t = rec["table"]
    assert set(t) == {
        "sum/scatter", "sum/matmul", "sum/pallas-plain", "sum/pallas-kahan",
        "sum/pallas-dd", "nanmean/auto", "nanvar/auto",
    }
    assert t["sum/pallas-dd"]["max_ulp"] == 0
    assert t["sum/pallas-kahan"]["max_ulp"] <= t["sum/pallas-plain"]["max_ulp"]
    for m in t.values():
        assert m["max_rel"] < 1e-4


def test_ulp_dist_f32():
    import numpy as np

    from bench_accuracy import ulp_dist_f32

    a = np.float32([1.0, -1.0, 0.0])
    assert ulp_dist_f32(a, a.astype(np.float64)).max() == 0
    one_up = np.nextafter(np.float32(1.0), np.float32(2.0))
    assert ulp_dist_f32(np.float32([one_up]), np.float64([1.0]))[0] == 1
    # sign-crossing distance counts through zero
    tiny = np.float32(1e-45)  # smallest subnormal
    assert ulp_dist_f32(np.float32([tiny]), np.float64([-1e-45]))[0] == 2
