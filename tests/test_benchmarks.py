"""Benchmarks-as-tests (parity: the reference's test_asv.py:1-22 runs its
asv classes in pytest so the suite cannot rot)."""

def test_benchmark_functions_run():
    import benchmarks

    out = []
    out += benchmarks.bench_reduce("numpy")
    out += benchmarks.bench_reduce_bare("numpy")
    out += benchmarks.bench_cohort_detection("small")
    assert all("bench" in r and "value" in r for r in out)
    methods = [r for r in out if r["bench"].startswith("track_method")]
    assert methods and methods[0]["value"] in ("cohorts", "map-reduce", "blockwise")


def test_headline_bench_shape():
    # bench.py must emit exactly one JSON line with the required keys
    import bench  # noqa: F401  (importable; full run needs the real chip)

    assert hasattr(bench, "main")
