"""Cross-engine parity: the numpy engine must agree with the jax engine.

Two independent implementations of the same plugin boundary — disagreement
flags a bug in one of them (the reference gets this coverage from its
engine-parametrized suite, conftest.py:22-32).
"""

import numpy as np
import pytest

from flox_tpu import engine_numpy, kernels

RNG = np.random.default_rng(7)

FUNCS = [
    "sum", "nansum", "prod", "nanprod", "max", "nanmax", "min", "nanmin",
    "mean", "nanmean", "var", "nanvar", "std", "nanstd", "nanlen", "len",
    "all", "any", "argmax", "argmin", "nanargmax", "nanargmin",
    "first", "last", "nanfirst", "nanlast", "median", "nanmedian",
    "mode", "nanmode", "sum_of_squares", "nansum_of_squares",
    "cumsum", "nancumsum", "ffill", "bfill",
]


@pytest.fixture(params=["1d", "2d", "nan", "nan-labels"])
def case(request):
    n, size = 41, 4
    codes = RNG.integers(0, size, n).astype(np.int64)
    values = RNG.normal(size=(n,))
    # quantize so mode has repeats and prod stays bounded
    values = np.round(values, 1)
    if request.param == "2d":
        values = np.round(RNG.normal(size=(2, n)), 1)
    elif request.param == "nan":
        values[RNG.random(n) < 0.3] = np.nan
    elif request.param == "nan-labels":
        codes[RNG.random(n) < 0.2] = -1
    return values, codes, size


@pytest.mark.parametrize("func", FUNCS)
def test_engine_parity(case, func):
    values, codes, size = case
    kwargs = dict(size=size, fill_value=np.nan)
    if func in ("argmax", "argmin", "nanargmax", "nanargmin"):
        kwargs["fill_value"] = -1
    if func in ("all", "any"):
        kwargs["fill_value"] = None
    a = np.asarray(kernels.generic_kernel(func, codes, values, **kwargs))
    b = np.asarray(engine_numpy.generic_kernel(func, codes, values, **kwargs))
    np.testing.assert_allclose(
        a.astype(np.float64), b.astype(np.float64), rtol=1e-10, atol=1e-10, equal_nan=True
    )


@pytest.mark.parametrize("q", [0.25, [0.1, 0.9]])
def test_engine_parity_quantile(case, q):
    values, codes, size = case
    a = np.asarray(kernels.generic_kernel("nanquantile", codes, values, size=size, q=q))
    b = np.asarray(engine_numpy.generic_kernel("nanquantile", codes, values, size=size, q=q))
    np.testing.assert_allclose(a, b, rtol=1e-10, atol=1e-10, equal_nan=True)


def test_engine_parity_var_chunk(case):
    values, codes, size = case
    a = kernels.generic_kernel("var_chunk", codes, values, size=size)
    b = engine_numpy.generic_kernel("var_chunk", codes, values, size=size)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-10, atol=1e-10)


def test_complex_dtype_parity():
    # reference property tests cover complex inputs (strategies.py:52-190)
    vals = np.array([1 + 2j, 3 - 1j, np.nan + 0j, 2 + 2j])
    codes = np.array([0, 0, 1, 1])
    for func in ["sum", "nansum", "mean", "nanmean", "count", "first", "last",
                 "nanfirst", "nanlast"]:
        a = np.asarray(kernels.generic_kernel(func, codes, vals, size=2))
        b = np.asarray(engine_numpy.generic_kernel(func, codes, vals, size=2))
        np.testing.assert_allclose(a, b, equal_nan=True, err_msg=func)


class TestF16Accumulation:
    """The numpy engine mirrors the jax engine's f32 accumulation for
    sub-f32 floats (f16 sums/counts saturate at the 11-bit mantissa)."""

    def _x(self):
        return np.linspace(0, 1, 2000).astype(np.float16), np.zeros(2000, np.int64)

    @pytest.mark.parametrize(
        "func,expect,tol",
        [("nanmean", 0.5, 1e-3), ("nansum", 999.5, 1.5),
         ("nanvar", 1 / 12, 1e-3), ("nanstd", (1 / 12) ** 0.5, 1e-3)],
    )
    def test_reductions(self, func, expect, tol):
        x, z = self._x()
        out = engine_numpy.generic_kernel(func, z, x, size=1)
        assert out.dtype == np.float16
        assert abs(float(out[0]) - expect) < tol

    def test_cumsum(self):
        x, z = self._x()
        out = engine_numpy.generic_kernel("nancumsum", z, x, size=1)
        assert out.dtype == np.float16
        assert abs(float(out[-1]) - 999.5) < 1.5


def test_bf16_accumulation_numpy_engine():
    # review regression: bfloat16 registers with numpy as kind 'V'; the
    # accumulation promotion must still catch it
    import ml_dtypes

    x = np.linspace(0, 1, 2000).astype(ml_dtypes.bfloat16)
    z = np.zeros(2000, np.int64)
    s = engine_numpy.generic_kernel("nansum", z, x, size=1)
    m = engine_numpy.generic_kernel("nanmean", z, x, size=1)
    assert abs(float(s[0]) - 1000) < 10
    assert abs(float(m[0]) - 0.5) < 0.01
