"""Durable incremental aggregation store (flox_tpu/store.py + serve/stores.py).

The contracts under test:

* **exactly-once** — a slab's fingerprint + generation are journaled
  before state mutates; replaying an already-ingested slab acks
  ``slab_already_ingested`` and changes nothing, including across a crash
  and reopen;
* **crash recovery** — a kill / torn write / bit flip at EVERY injected
  fault point (journal write, segment write, compaction swap) followed by
  reopen + re-append yields query results bit-identical to an
  uninterrupted run (``faults.store_inject`` drives the matrix);
* **corruption fault domain** — an unverifiable TAIL append rolls back
  (warn + quarantine + ``recovered``); unrecoverable MID-HISTORY damage
  raises :class:`StoreCorruptionError` naming the segment, after
  quarantining it as ``*.corrupt``;
* **compaction** — the merged segment lands and the journal flips before
  any replaced segment deletes; a kill anywhere leaves either the old
  stack or the new base fully live;
* **inline equivalence** — ``query`` matches ``groupby_aggregate_many``
  over the concatenated history across eager/mesh × dense/sort engines
  (exact for the additive/extrema family on integer-valued data, tight
  allclose for the variance family, whose pairwise merge order differs);
* **checkpoint hardening** — ``StreamCheckpointer`` spills ride the same
  checksummed format; a truncated or bit-flipped spill warns and restarts
  fresh instead of loading silently wrong state;
* **serve surface** — typed protocol errors (``unknown_store``,
  ``store_corruption``), ``restage_all`` device-loss recovery,
  ``cache.clear_all`` / ``cache.stats`` registration, ``/debug/stores``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import flox_tpu
from flox_tpu import cache, faults, telemetry
from flox_tpu import store as store_mod
from flox_tpu.fusion import groupby_aggregate_many
from flox_tpu.multiarray import PresentGroups, merge_present_var
from flox_tpu.store import (
    IncrementalAggregationStore,
    StoreCorruptionError,
    open_store,
    read_checksummed_npz,
    write_checksummed_npz,
)
from flox_tpu.telemetry import METRICS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FUNCS = ("sum", "count", "min", "max", "mean", "var", "nanstd")
#: exact equality holds for these on integer-valued float64 data: sums of
#: small integers are exact in binary64 regardless of association, so the
#: slab-merged carry reproduces the single-pass result bit for bit
EXACT = ("sum", "count", "min", "max", "mean")
SIZE = 23


@pytest.fixture(autouse=True)
def _clean_state():
    METRICS.reset()
    cache.clear_all()
    yield
    cache.clear_all()


def _slabs(nslabs=4, n=120, seed=7, integer=True):
    """Deterministic (codes, values) slabs; integer-valued floats keep the
    additive family exactly associative."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(nslabs):
        codes = rng.integers(0, SIZE, n)
        vals = (
            rng.integers(-50, 50, n).astype(np.float64)
            if integer
            else rng.normal(size=n)
        )
        out.append((codes, vals))
    return out


def _inline(slabs, funcs=FUNCS, **kw):
    codes = np.concatenate([c for c, _ in slabs])
    vals = np.concatenate([v for _, v in slabs])
    res, _ = groupby_aggregate_many(
        vals, codes, funcs=funcs, expected_groups=np.arange(SIZE), **kw
    )
    return {f: np.asarray(v) for f, v in res.items()}


def _check(store_res, oracle, funcs=FUNCS):
    for f in funcs:
        a, b = np.asarray(store_res[f]), np.asarray(oracle[f])
        if f in EXACT:
            np.testing.assert_array_equal(a, b, err_msg=f)
        else:
            np.testing.assert_allclose(a, b, rtol=1e-12, atol=1e-12, err_msg=f)


def _fill(path, slabs, funcs=FUNCS, **create_kw):
    s = IncrementalAggregationStore.create(path, funcs=funcs, size=SIZE, **create_kw)
    for codes, vals in slabs:
        s.append(codes, vals)
    return s


class TestChecksummedNpz:
    def test_round_trip(self, tmp_path):
        p = str(tmp_path / "x.npz")
        arrays = {"a": np.arange(5.0), "b": np.array([[1, 2], [3, 4]], dtype=np.int32)}
        write_checksummed_npz(p, arrays, {"kind": "t", "gen": 3})
        got, meta = read_checksummed_npz(p)
        assert meta["kind"] == "t" and meta["gen"] == 3
        for k in arrays:
            np.testing.assert_array_equal(got[k], arrays[k])
            assert got[k].dtype == arrays[k].dtype

    def test_bit_flip_detected(self, tmp_path):
        p = str(tmp_path / "x.npz")
        write_checksummed_npz(p, {"a": np.arange(100.0)}, {})
        data = bytearray(open(p, "rb").read())
        data[len(data) // 2] ^= 0x04
        open(p, "wb").write(bytes(data))
        with pytest.raises(StoreCorruptionError):
            read_checksummed_npz(p)

    def test_truncation_detected(self, tmp_path):
        p = str(tmp_path / "x.npz")
        write_checksummed_npz(p, {"a": np.arange(100.0)}, {})
        data = open(p, "rb").read()
        open(p, "wb").write(data[: len(data) // 2])
        with pytest.raises(StoreCorruptionError):
            read_checksummed_npz(p)

    def test_headerless_npz_rejected(self, tmp_path):
        p = str(tmp_path / "x.npz")
        np.savez(p[:-4], a=np.arange(3.0))
        with pytest.raises(StoreCorruptionError, match="header"):
            read_checksummed_npz(p)

    def test_future_format_rejected(self, tmp_path):
        p = str(tmp_path / "x.npz")
        write_checksummed_npz(p, {"a": np.arange(3.0)}, {})
        arrays, _ = read_checksummed_npz(p)
        header = json.dumps({"format": 99, "meta": {}, "digests": {}})
        np.savez(p[:-4], __header__=np.frombuffer(header.encode(), dtype=np.uint8))
        with pytest.raises(StoreCorruptionError, match="format"):
            read_checksummed_npz(p)

    def test_missing_file_is_filenotfound(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            read_checksummed_npz(str(tmp_path / "nope.npz"))


class TestMergePresentVar:
    def _oracle(self, codes, vals):
        res, _ = groupby_aggregate_many(
            vals, codes, funcs=("var", "mean", "count"),
            expected_groups=np.arange(SIZE), engine="numpy",
        )
        return res

    def _triple(self, codes, vals):
        """(m2, total, count) PresentGroups for one slab, built the same way
        the store builds its var leg."""
        present, cidx = np.unique(codes, return_inverse=True)
        cap = len(present) + 1
        m2 = np.zeros(cap)
        tot = np.zeros(cap)
        cnt = np.zeros(cap)
        for j, p in enumerate(present):
            x = vals[codes == p]
            cnt[j] = x.size
            tot[j] = x.sum()
            m2[j] = ((x - x.mean()) ** 2).sum()
        return tuple(
            PresentGroups(present, leaf, SIZE) for leaf in (m2, tot, cnt)
        )

    def test_matches_single_pass(self):
        rng = np.random.default_rng(3)
        ca, va = rng.integers(0, SIZE, 200), rng.normal(size=200)
        cb, vb = rng.integers(0, SIZE, 150), rng.normal(size=150)
        m2, tot, cnt = merge_present_var(self._triple(ca, va), self._triple(cb, vb))
        oracle = self._oracle(np.concatenate([ca, cb]), np.concatenate([va, vb]))
        dense_cnt = cnt.scatter_dense()
        dense_m2 = m2.scatter_dense()
        with np.errstate(invalid="ignore", divide="ignore"):
            var = np.where(dense_cnt > 0, dense_m2 / dense_cnt, np.nan)
        np.testing.assert_allclose(var, oracle["var"], rtol=1e-10, atol=1e-12)

    def test_disjoint_groups(self):
        a = self._triple(np.array([0, 0, 1]), np.array([1.0, 3.0, 5.0]))
        b = self._triple(np.array([4, 4]), np.array([2.0, 6.0]))
        m2, tot, cnt = merge_present_var(a, b)
        np.testing.assert_array_equal(m2.present, [0, 1, 4])
        dense = cnt.scatter_dense()
        assert dense[0] == 2 and dense[1] == 1 and dense[4] == 2
        # no cross-talk: singleton group 1 keeps zero m2
        assert m2.scatter_dense()[1] == 0.0


class TestStoreBasics:
    def test_direct_ctor_rejected(self, tmp_path):
        with pytest.raises(TypeError, match="create"):
            IncrementalAggregationStore(str(tmp_path / "s"))

    def test_create_twice_rejected(self, tmp_path):
        p = str(tmp_path / "s")
        IncrementalAggregationStore.create(p, funcs=("sum",), size=4)
        with pytest.raises(FileExistsError):
            IncrementalAggregationStore.create(p, funcs=("sum",), size=4)

    def test_open_missing_rejected(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            IncrementalAggregationStore.open(str(tmp_path / "nope"))

    def test_create_validation(self, tmp_path):
        with pytest.raises(ValueError, match="engine"):
            IncrementalAggregationStore.create(
                str(tmp_path / "a"), funcs=("sum",), size=4, engine="pallas"
            )
        with pytest.raises(ValueError, match="size"):
            IncrementalAggregationStore.create(
                str(tmp_path / "b"), funcs=("sum",), size=0
            )

    def test_append_query_matches_inline(self, tmp_path):
        slabs = _slabs()
        s = _fill(str(tmp_path / "s"), slabs)
        _check(s.query(), _inline(slabs, engine="numpy"))

    def test_plan_persisted_across_open(self, tmp_path):
        p = str(tmp_path / "s")
        s = IncrementalAggregationStore.create(
            p, funcs=("sum", "var"), size=9, array_dtype="float32",
            min_count=2, finalize_kwargs={"var": {"ddof": 1}},
        )
        s2 = IncrementalAggregationStore.open(p)
        assert s2.funcs == ("sum", "var")
        assert s2.size == 9
        assert s2.array_dtype == np.dtype("float32")
        assert s2.min_count == 2
        assert s2.finalize_kwargs == {"var": {"ddof": 1}}

    def test_reopen_bit_identical(self, tmp_path):
        slabs = _slabs()
        s = _fill(str(tmp_path / "s"), slabs)
        before = s.query()
        s2 = IncrementalAggregationStore.open(s.path)
        assert not s2.recovered
        after = s2.query()
        for f in FUNCS:
            np.testing.assert_array_equal(
                np.asarray(before[f]), np.asarray(after[f]), err_msg=f
            )

    def test_duplicate_slab_is_noop(self, tmp_path):
        slabs = _slabs(2)
        s = _fill(str(tmp_path / "s"), slabs)
        before = s.query()
        gen = s.gen
        ack = s.append(*slabs[0])
        assert ack["ack"] == "slab_already_ingested"
        assert s.gen == gen
        assert METRICS.counters()["store.duplicates"] == 1
        _check(s.query(), before)

    def test_slab_id_overrides_fingerprint(self, tmp_path):
        slabs = _slabs(2)
        s = IncrementalAggregationStore.create(
            str(tmp_path / "s"), funcs=FUNCS, size=SIZE
        )
        s.append(*slabs[0], slab_id="batch-0")
        # different content, same idempotency key: a retried producer that
        # re-reads its source must not double-ingest
        ack = s.append(*slabs[1], slab_id="batch-0")
        assert ack["ack"] == "slab_already_ingested"
        _check(s.query(), _inline(slabs[:1], engine="numpy"))

    def test_out_of_range_codes_dropped(self, tmp_path):
        s = IncrementalAggregationStore.create(
            str(tmp_path / "s"), funcs=("sum", "count"), size=4
        )
        s.append(np.array([0, -1, 2, 99]), np.array([1.0, 100.0, 3.0, 100.0]))
        res = s.query()
        np.testing.assert_array_equal(res["sum"], [1.0, 0.0, 3.0, 0.0])
        np.testing.assert_array_equal(res["count"], [1, 0, 1, 0])

    def test_all_invalid_slab_is_journal_only(self, tmp_path):
        s = IncrementalAggregationStore.create(
            str(tmp_path / "s"), funcs=("sum",), size=4
        )
        ack = s.append(np.array([-1, 77]), np.array([1.0, 2.0]))
        assert ack["ack"] == "ingested" and s.gen == 1
        assert not [f for f in os.listdir(s.path) if f.startswith("seg-")]
        # still exactly-once, and the generation survives reopen
        s2 = IncrementalAggregationStore.open(s.path)
        assert s2.gen == 1
        assert s2.append(np.array([-1, 77]), np.array([1.0, 2.0]))["ack"] == (
            "slab_already_ingested"
        )

    def test_empty_store_query(self, tmp_path):
        s = IncrementalAggregationStore.create(
            str(tmp_path / "s"), funcs=("sum", "count", "mean"), size=5
        )
        res = s.query()
        np.testing.assert_array_equal(res["sum"], np.zeros(5))
        np.testing.assert_array_equal(res["count"], np.zeros(5, dtype=np.int64))
        assert np.isnan(np.asarray(res["mean"])).all()

    def test_query_subset_and_unknown(self, tmp_path):
        s = _fill(str(tmp_path / "s"), _slabs(2))
        res = s.query(("mean", "max"))
        assert sorted(res) == ["max", "mean"]
        with pytest.raises(ValueError, match="median"):
            s.query(("median",))

    def test_shape_mismatch_rejected(self, tmp_path):
        s = IncrementalAggregationStore.create(
            str(tmp_path / "s"), funcs=("sum",), size=4
        )
        with pytest.raises(ValueError, match="trailing axis"):
            s.append(np.array([0, 1]), np.array([1.0, 2.0, 3.0]))

    def test_info_snapshot(self, tmp_path):
        s = _fill(str(tmp_path / "s"), _slabs(3))
        info = s.info()
        assert info["gen"] == 3 and info["slabs"] == 3
        assert info["segments"] == 3 and info["nbytes"] > 0
        json.dumps(info)  # JSON-able is part of the contract

    def test_open_store_convenience(self, tmp_path):
        p = str(tmp_path / "s")
        with pytest.raises(FileNotFoundError):
            open_store(p)
        s = open_store(p, create={"funcs": ("sum",), "size": 4})
        s.append(np.array([1]), np.array([5.0]))
        s2 = open_store(p, create={"funcs": ("sum",), "size": 4})
        assert s2.gen == 1


def _writes_per_append(tmp_path, slabs):
    """(first, last) 1-based durable-write ordinals of the FINAL append in
    a create + append-all run."""
    with faults.store_inject():
        s = IncrementalAggregationStore.create(
            str(tmp_path / "probe"), funcs=FUNCS, size=SIZE
        )
        for codes, vals in slabs[:-1]:
            s.append(codes, vals)
        before = faults._STORE_PLAN.writes
        s.append(*slabs[-1])
        after = faults._STORE_PLAN.writes
    return before + 1, after


class TestRecoveryMatrix:
    """Kill / tear / flip at EVERY durable-write ordinal of the final
    append, then reopen (= crash recovery) + re-append + query: must be
    bit-identical to the uninterrupted control run."""

    @pytest.fixture(scope="class")
    def control(self, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("ctrl")
        slabs = _slabs()
        s = _fill(str(tmp / "ctrl"), slabs)
        return slabs, {f: np.asarray(v) for f, v in s.query().items()}

    @pytest.mark.parametrize("action", ["kill", "torn", "flip"])
    @pytest.mark.parametrize("offset", [0, 1])  # journal write, segment write
    def test_crash_during_append(self, tmp_path, control, action, offset):
        slabs, ctrl = control
        first, last = _writes_per_append(tmp_path, slabs)
        assert last - first == 1, "append = one journal write + one segment write"
        ordinal = first + offset
        key = {"kill": "kill_at", "torn": "torn_at", "flip": "flip_at"}[action]
        p = str(tmp_path / "s")
        with faults.store_inject(**{key: (ordinal,)}):
            s = IncrementalAggregationStore.create(p, funcs=FUNCS, size=SIZE)
            for codes, vals in slabs[:-1]:
                s.append(codes, vals)
            try:
                s.append(*slabs[-1])
            except faults.StoreWriteKilled:
                pass
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            s2 = IncrementalAggregationStore.open(p)
        assert s2.gen in (len(slabs) - 1, len(slabs))
        # exactly-once re-delivery: a no-op if the append committed, an
        # ingest if it rolled back — either way the final state matches
        s2.append(*slabs[-1])
        assert s2.gen == len(slabs)
        res = s2.query()
        for f in FUNCS:
            np.testing.assert_array_equal(
                np.asarray(res[f]), ctrl[f], err_msg=f"{action}@{ordinal} {f}"
            )

    def test_torn_journal_tail_counts_recovery(self, tmp_path, control):
        slabs, ctrl = control
        first, _ = _writes_per_append(tmp_path, slabs)
        p = str(tmp_path / "s")
        with faults.store_inject(torn_at=(first,)):
            s = IncrementalAggregationStore.create(p, funcs=FUNCS, size=SIZE)
            for codes, vals in slabs[:-1]:
                s.append(codes, vals)
            with pytest.raises(faults.StoreWriteKilled):
                s.append(*slabs[-1])
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            s2 = IncrementalAggregationStore.open(p)
        assert s2.recovered
        assert METRICS.counters()["store.recoveries"] == 1
        assert s2.gen == len(slabs) - 1

    def test_torn_tail_is_truncated_so_reappend_survives_reopen(
        self, tmp_path, control
    ):
        """Regression: a torn journal tail must be REMOVED at open, not just
        skipped at parse. Otherwise the post-recovery append glues its record
        onto the half-written line and the NEXT open drops the glued line as
        a torn tail — silently rolling back an acked generation."""
        slabs, ctrl = control
        first, _ = _writes_per_append(tmp_path, slabs)
        p = str(tmp_path / "s")
        with faults.store_inject(torn_at=(first,)):
            s = IncrementalAggregationStore.create(p, funcs=FUNCS, size=SIZE)
            for codes, vals in slabs[:-1]:
                s.append(codes, vals)
            with pytest.raises(faults.StoreWriteKilled):
                s.append(*slabs[-1])
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            s2 = IncrementalAggregationStore.open(p)
        assert s2.append(*slabs[-1])["ack"] == "ingested"
        # the open AFTER the repair + re-append is the one the bug broke
        s3 = IncrementalAggregationStore.open(p)
        assert not s3.recovered
        assert s3.gen == len(slabs)
        assert s3.append(*slabs[-1])["ack"] == "slab_already_ingested"
        res = s3.query()
        for f in FUNCS:
            np.testing.assert_array_equal(np.asarray(res[f]), ctrl[f])

    def test_crash_before_any_append(self, tmp_path):
        p = str(tmp_path / "s")
        with faults.store_inject(kill_at=(2,)):  # first append's journal write
            s = IncrementalAggregationStore.create(p, funcs=("sum",), size=4)
            with pytest.raises(faults.StoreWriteKilled):
                s.append(np.array([0]), np.array([1.0]))
        s2 = IncrementalAggregationStore.open(p)
        assert s2.gen == 0
        assert s2.append(np.array([0]), np.array([1.0]))["ack"] == "ingested"

    def test_mid_history_corruption_typed_error(self, tmp_path, control):
        slabs, _ = control
        p = str(tmp_path / "s")
        _fill(p, slabs)
        segs = sorted(f for f in os.listdir(p) if f.startswith("seg-"))
        victim = os.path.join(p, segs[1])
        data = bytearray(open(victim, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(victim, "wb").write(bytes(data))
        with pytest.raises(StoreCorruptionError) as exc_info:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                IncrementalAggregationStore.open(p)
        assert exc_info.value.segment == segs[1]
        assert os.path.exists(victim + ".corrupt")
        assert not os.path.exists(victim)

    def test_deleted_tail_segment_rolls_back(self, tmp_path, control):
        slabs, ctrl = control
        p = str(tmp_path / "s")
        _fill(p, slabs)
        segs = sorted(f for f in os.listdir(p) if f.startswith("seg-"))
        os.unlink(os.path.join(p, segs[-1]))
        with pytest.warns(RuntimeWarning, match="rolling back"):
            s2 = IncrementalAggregationStore.open(p)
        assert s2.recovered and s2.gen == len(slabs) - 1
        s2.append(*slabs[-1])
        res = s2.query()
        for f in FUNCS:
            np.testing.assert_array_equal(np.asarray(res[f]), ctrl[f], err_msg=f)

    def test_orphan_tmp_cleaned_on_open(self, tmp_path):
        p = str(tmp_path / "s")
        s = _fill(p, _slabs(2))
        open(os.path.join(p, "seg-00000009.npz.tmp"), "wb").write(b"junk")
        open(os.path.join(p, "seg-00000009.npz"), "wb").write(b"junk")
        IncrementalAggregationStore.open(p)
        left = os.listdir(p)
        assert "seg-00000009.npz.tmp" not in left
        assert "seg-00000009.npz" not in left


class TestCompaction:
    def test_compact_preserves_results(self, tmp_path):
        slabs = _slabs()
        s = _fill(str(tmp_path / "s"), slabs)
        before = {f: np.asarray(v) for f, v in s.query().items()}
        out = s.compact()
        assert out["compacted"] and out["segments"] == 1
        assert len([f for f in os.listdir(s.path) if f.startswith("seg-")]) == 1
        for store in (s, IncrementalAggregationStore.open(s.path)):
            res = store.query()
            for f in FUNCS:
                np.testing.assert_array_equal(
                    np.asarray(res[f]), before[f], err_msg=f
                )

    def test_compact_then_append_then_compact(self, tmp_path):
        slabs = _slabs(6)
        s = _fill(str(tmp_path / "s"), slabs[:3])
        s.compact()
        for codes, vals in slabs[3:]:
            s.append(codes, vals)
        s.compact()
        s2 = IncrementalAggregationStore.open(s.path)
        assert s2.gen == 6 and s2.info()["segments"] == 1
        _check(s2.query(), _inline(slabs, engine="numpy"))

    def test_compact_noop_cases(self, tmp_path):
        s = IncrementalAggregationStore.create(
            str(tmp_path / "s"), funcs=("sum",), size=4
        )
        assert not s.compact()["compacted"]  # empty store
        s.append(np.array([0]), np.array([1.0]))
        assert not s.compact()["compacted"]  # single live segment

    @pytest.mark.parametrize("op,ordinal", [("segment", 1), ("journal", 1)])
    def test_crash_during_compact(self, tmp_path, op, ordinal):
        slabs = _slabs()
        ctrl = _inline(slabs, engine="numpy")
        p = str(tmp_path / "s")
        s = _fill(p, slabs)
        with faults.store_inject(kill_at=(ordinal,), op=op):
            with pytest.raises(faults.StoreWriteKilled):
                s.compact()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            s2 = IncrementalAggregationStore.open(p)
        assert s2.gen == len(slabs)
        _check(s2.query(), ctrl)

    @pytest.mark.parametrize("ordinal", [1, 2, 4])
    def test_crash_during_swap_delete(self, tmp_path, ordinal):
        slabs = _slabs()
        ctrl = _inline(slabs, engine="numpy")
        p = str(tmp_path / "s")
        s = _fill(p, slabs)
        with faults.store_inject(kill_at=(ordinal,), op="swap"):
            with pytest.raises(faults.StoreWriteKilled):
                s.compact()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            s2 = IncrementalAggregationStore.open(p)
        # the compact committed (journal flipped before deletes): the new
        # base serves, the undeleted replaced segments are swept as orphans
        assert s2.gen == len(slabs) and s2.info()["segments"] == 1
        _check(s2.query(), ctrl)
        live = [f for f in os.listdir(p) if f.startswith("seg-") and f.endswith(".npz")]
        assert len(live) == 1

    def test_auto_compact_threshold(self, tmp_path):
        slabs = _slabs(6)
        with flox_tpu.set_options(store_compact_threshold=2):
            s = _fill(str(tmp_path / "s"), slabs)
        assert s.info()["segments"] <= 3
        assert METRICS.counters()["store.compactions"] >= 1
        _check(s.query(), _inline(slabs, engine="numpy"))


class TestInlineEquivalence:
    """query == the one-shot fused aggregation over concatenated history,
    whatever engine/execution the inline side used."""

    @pytest.mark.parametrize("inline_engine", ["numpy", "jax", "sort"])
    def test_engines(self, tmp_path, inline_engine):
        slabs = _slabs()
        s = _fill(str(tmp_path / "s"), slabs)
        _check(s.query(), _inline(slabs, engine=inline_engine))

    def test_mesh(self, tmp_path):
        from flox_tpu.parallel.mesh import make_mesh

        slabs = _slabs(4, n=128)
        s = _fill(str(tmp_path / "s"), slabs)
        oracle = _inline(slabs, method="map-reduce", mesh=make_mesh())
        _check(s.query(), oracle)

    def test_store_jax_engine(self, tmp_path):
        slabs = _slabs()
        s = _fill(str(tmp_path / "s"), slabs, engine="jax")
        _check(s.query(), _inline(slabs, engine="jax"))

    def test_nan_data(self, tmp_path):
        rng = np.random.default_rng(5)
        slabs = []
        for _ in range(3):
            codes = rng.integers(0, SIZE, 90)
            vals = rng.normal(size=90)
            vals[rng.random(90) < 0.2] = np.nan
            slabs.append((codes, vals))
        funcs = ("nansum", "count", "nanmax", "nanmean", "nanstd")
        s = IncrementalAggregationStore.create(
            str(tmp_path / "s"), funcs=funcs, size=SIZE
        )
        for codes, vals in slabs:
            s.append(codes, vals)
        oracle = _inline(slabs, funcs=funcs, engine="numpy")
        res = s.query()
        for f in funcs:
            np.testing.assert_allclose(
                np.asarray(res[f]), oracle[f], rtol=1e-12, atol=1e-12, err_msg=f
            )


class TestOptions:
    def test_validation(self):
        with pytest.raises(ValueError):
            flox_tpu.set_options(store_compact_threshold=-1)
        with pytest.raises(ValueError):
            flox_tpu.set_options(store_fsync="maybe")
        with pytest.raises(ValueError):
            flox_tpu.set_options(store_root=123)

    def test_fsync_off_still_correct(self, tmp_path):
        slabs = _slabs(2)
        with flox_tpu.set_options(store_fsync="off"):
            s = _fill(str(tmp_path / "s"), slabs)
        s2 = IncrementalAggregationStore.open(s.path)
        _check(s2.query(), _inline(slabs, engine="numpy"))


class TestCheckpointHardening:
    """StreamCheckpointer spills ride the store's checksummed format; a
    damaged spill means 'fresh run', loudly — never silently wrong state."""

    KEY = ("stream", "sum", 64, 8, 5, (), "fp", None, None, ())

    def _spill(self, tmp_path):
        from flox_tpu.resilience import Snapshot, _dump_snapshot

        p = str(tmp_path / "ckpt.npz")
        snap = Snapshot(
            key=self.KEY, phase=1, slabs_done=4, payload={"acc": np.arange(6.0)}
        )
        _dump_snapshot(p, snap)
        return p

    def test_round_trip(self, tmp_path):
        from flox_tpu.resilience import _load_snapshot

        p = self._spill(tmp_path)
        got = _load_snapshot(p, self.KEY)
        assert got is not None and got.slabs_done == 4 and got.phase == 1
        np.testing.assert_array_equal(got.payload["acc"], np.arange(6.0))

    def test_truncated_spill_restarts_fresh(self, tmp_path):
        from flox_tpu.resilience import _load_snapshot

        p = self._spill(tmp_path)
        data = open(p, "rb").read()
        open(p, "wb").write(data[: len(data) // 2])
        with pytest.warns(RuntimeWarning, match="corrupt"):
            assert _load_snapshot(p, self.KEY) is None
        assert METRICS.counters()["stream.checkpoint_corrupt"] == 1

    def test_bitflip_spill_restarts_fresh(self, tmp_path):
        from flox_tpu.resilience import _load_snapshot

        p = self._spill(tmp_path)
        data = bytearray(open(p, "rb").read())
        data[len(data) // 2] ^= 0x01
        open(p, "wb").write(bytes(data))
        with pytest.warns(RuntimeWarning, match="corrupt"):
            assert _load_snapshot(p, self.KEY) is None

    def test_legacy_uncheck_summed_spill_restarts_fresh(self, tmp_path):
        from flox_tpu.resilience import _load_snapshot

        p = str(tmp_path / "legacy.npz")
        np.savez(p[:-4], leaf0=np.arange(3.0))
        with pytest.warns(RuntimeWarning, match="corrupt"):
            assert _load_snapshot(p, self.KEY) is None


class TestServeStores:
    @pytest.fixture(autouse=True)
    def _root(self, tmp_path):
        with flox_tpu.set_options(store_root=str(tmp_path)):
            yield str(tmp_path)

    def test_unknown_store_typed(self):
        from flox_tpu.serve import stores

        with pytest.raises(stores.UnknownStoreError) as exc_info:
            stores.query("nope")
        assert exc_info.value.code == "unknown_store"

    def test_no_root_typed(self):
        from flox_tpu.serve import stores

        with flox_tpu.set_options(store_root=None):
            with pytest.raises(stores.UnknownStoreError, match="store root"):
                stores.query("x")

    def test_bad_names_typed(self):
        from flox_tpu.serve import stores

        for bad in ("", None, "../evil", "a/b", ".hidden"):
            with pytest.raises(stores.UnknownStoreError):
                stores.resolve(bad)

    def test_append_query_roundtrip(self):
        from flox_tpu.serve import stores

        slabs = _slabs(3)
        create = {"funcs": list(FUNCS), "size": SIZE}
        for codes, vals in slabs:
            ack = stores.append("t", codes, vals, create=create)
        assert ack["ack"] == "ingested" and ack["gen"] == 3
        _check(stores.query("t"), _inline(slabs, engine="numpy"))

    def test_query_device_cache_invalidated_by_append(self):
        from flox_tpu.serve import stores

        slabs = _slabs(3)
        create = {"funcs": list(FUNCS), "size": SIZE}
        stores.append("t", *slabs[0], create=create)
        stores.query("t")
        stores.query("t")
        assert METRICS.counters().get("store.query_device_hits", 0) == 1
        stores.append("t", *slabs[1])
        res = stores.query("t")  # generation moved: must recompute
        assert METRICS.counters().get("store.query_device_hits", 0) == 1
        _check(res, _inline(slabs[:2], engine="numpy"))

    def test_restage_all_recovers(self):
        from flox_tpu.serve import stores

        slabs = _slabs(2)
        create = {"funcs": list(FUNCS), "size": SIZE}
        for codes, vals in slabs:
            stores.append("t", codes, vals, create=create)
        before = stores.query("t")
        assert stores.restage_all() == 1
        assert METRICS.counters()["store.restaged"] == 1
        res = stores.query("t")
        for f in FUNCS:
            np.testing.assert_array_equal(
                np.asarray(res[f]), np.asarray(before[f]), err_msg=f
            )

    def test_corruption_typed_and_quarantined(self, _root):
        from flox_tpu.serve import stores

        p = os.path.join(_root, "bad")
        s = _fill(p, _slabs(3))
        del s
        segs = sorted(f for f in os.listdir(p) if f.startswith("seg-"))
        victim = os.path.join(p, segs[0])
        data = bytearray(open(victim, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(victim, "wb").write(bytes(data))
        with pytest.raises(stores.StoreCorruptedError) as exc_info:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                stores.query("bad")
        assert exc_info.value.code == "store_corruption"
        assert os.path.exists(victim + ".corrupt")

    def test_list_stores_sees_unopened(self, _root):
        from flox_tpu.serve import stores

        _fill(os.path.join(_root, "cold"), _slabs(1))
        stores.append(
            "hot", *_slabs(1)[0], create={"funcs": ["sum"], "size": SIZE}
        )
        rows = {r["store"]: r for r in stores.list_stores()}
        assert rows["hot"]["open"] is True
        assert rows["cold"]["open"] is False

    def test_cache_stats_and_clear_all(self):
        from flox_tpu.serve import stores

        stores.append(
            "t", *_slabs(1)[0], create={"funcs": ["sum", "mean"], "size": SIZE}
        )
        panel = cache.stats()["stores"]
        assert panel["stores"] == 1 and panel["generations"] == {"t": 1}
        assert panel["state_bytes"] > 0
        cache.clear_all()
        assert stores.stores_stats()["stores"] == 0
        # durable state untouched: a later reference reopens it
        assert stores.query("t")["sum"].shape == (SIZE,)

    def test_debug_stores_payload(self):
        from flox_tpu.exposition import _Handler
        from flox_tpu.serve import stores

        stores.append(
            "t", *_slabs(1)[0], create={"funcs": ["sum"], "size": SIZE}
        )
        stores.query("t")
        body, status = _Handler._stores("")
        assert status == 200
        payload = json.loads(body)
        rows = {r["store"]: r for r in payload["stores"]}
        assert rows["t"]["gen"] == 1
        assert "cost_by_store" in payload

    def test_gauges_track_table(self):
        from flox_tpu.serve import stores

        stores.append(
            "t", *_slabs(1)[0], create={"funcs": ["sum"], "size": SIZE}
        )
        g = METRICS.gauges()
        assert g["store.open_stores"] == 1.0 and g["store.state_bytes"] > 0
        stores.clear()
        assert METRICS.gauges()["store.open_stores"] == 0.0

    def test_cost_ledger_rows(self):
        from flox_tpu.serve import stores

        with flox_tpu.set_options(telemetry=True):
            stores.append(
                "t", *_slabs(1)[0], create={"funcs": ["sum"], "size": SIZE}
            )
            stores.query("t")
            by_ds = telemetry.cost_by_dataset()
        assert "t" in by_ds


@pytest.mark.slow
class TestProtocol:
    """append/query/compact/list_stores over the ``python -m
    flox_tpu.serve`` JSON-lines loop, including typed error payloads."""

    def test_line_protocol(self, tmp_path):
        lines = [
            {"id": "1", "op": "append", "store": "s1",
             "codes": [0, 1, 1, 2], "array": [1.0, 2.0, 3.0, 4.0],
             "create": {"funcs": ["sum", "count"], "size": 4}},
            {"id": "2", "op": "append", "store": "s1",
             "codes": [0, 1, 1, 2], "array": [1.0, 2.0, 3.0, 4.0]},
            {"id": "3", "op": "query", "store": "s1"},
            {"id": "4", "op": "compact", "store": "s1"},
            {"id": "5", "op": "list_stores"},
            {"id": "6", "op": "query", "store": "missing"},
        ]
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            FLOX_TPU_STORE_ROOT=str(tmp_path),
        )
        out = subprocess.run(
            [sys.executable, "-m", "flox_tpu.serve"],
            input="\n".join(json.dumps(l) for l in lines) + "\n",
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
        )
        got = [json.loads(l) for l in out.stdout.splitlines() if l.startswith("{")]
        by_op = {}
        for g in got:
            by_op.setdefault(g["op"], []).append(g)
        acks = [g["ack"] for g in by_op["append"]]
        assert acks == ["ingested", "slab_already_ingested"]
        queries = [g for g in by_op["query"] if g.get("ok")]
        assert queries[0]["result"]["sum"] == [1.0, 5.0, 4.0, 0.0]
        assert by_op["compact"][0]["ok"]
        assert any(r["store"] == "s1" for r in by_op["list_stores"][0]["stores"])
        err = [g for g in by_op["query"] if not g.get("ok")][0]
        assert err["code"] == "unknown_store"
