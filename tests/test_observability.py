"""Observability-plane test suite (ISSUE 8).

The contract under test: a live process exposes Prometheus-parseable
``/metrics`` (counters + cumulative histogram buckets + hbm gauges) plus
``/healthz``/``/readyz``; a request's trace id appears on every child span
in both export formats (mesh and streaming paths included, worker threads
included); ``device.memory_stats()`` sampling feeds the hbm gauges and the
per-program attribution in ``cache.stats()``; fatal faults and signals
produce an atomic flight-recorder dump that ``python -m flox_tpu.telemetry
report`` summarizes; and none of it changes results — the disabled path
stays a no-op.
"""

from __future__ import annotations

import json
import os
import signal
import urllib.error
import urllib.request

import numpy as np
import pytest

import flox_tpu
from flox_tpu import cache, exposition, telemetry
from flox_tpu.core import groupby_reduce
from flox_tpu.parallel import make_mesh
from flox_tpu.streaming import streaming_groupby_reduce

RNG = np.random.default_rng(7)


@pytest.fixture(autouse=True)
def _clean_plane():
    """Each test starts with telemetry OFF, empty buffers/registries, no
    flight path, and no readiness — even under the CI instrumented leg."""
    with flox_tpu.set_options(
        telemetry=False, telemetry_export_path=None, flight_recorder_path=None
    ):
        telemetry.reset()
        exposition.set_ready(False)
        yield
        telemetry.reset()
    exposition.stop_metrics_server()
    exposition.set_ready(False)


def _run_reduce(**kw):
    vals = np.random.default_rng(0).normal(size=(3, 48)).astype(np.float64)
    codes = np.arange(48) % 5
    return groupby_reduce(vals, codes, func="nanmean", engine="jax", **kw)


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


def _parse_prometheus(text: str) -> tuple[dict, dict, dict]:
    """Minimal text-format parser: ``{metric-with-labels: value}`` samples,
    ``{metric: type}`` from the # TYPE lines, and ``{metric-with-labels:
    (labels, value)}`` for OpenMetrics-style exemplars hanging off
    ``_bucket`` lines (`` # {trace_id="..."} <value>``). Raises on anything
    that is not a comment, a blank, or a ``name{labels} value [exemplar]``
    sample — the golden-format guarantee the scrape contract rests on."""
    samples: dict[str, float] = {}
    types: dict[str, str] = {}
    exemplars: dict[str, tuple[str, float]] = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        sample_part, sep, exemplar_part = line.partition(" # ")
        name_part, _, value_part = sample_part.rpartition(" ")
        assert name_part, f"unparseable sample line: {line!r}"
        value = float(value_part)  # raises for malformed values
        if "{" in name_part:
            assert name_part.endswith("}"), f"unclosed label set: {line!r}"
        samples[name_part] = value
        if sep:
            # exemplar syntax: `# {label="value"} observed-value`
            labels_part, _, obs_part = exemplar_part.rpartition(" ")
            assert labels_part.startswith("{") and labels_part.endswith("}"), (
                f"malformed exemplar on: {line!r}"
            )
            exemplars[name_part] = (labels_part, float(obs_part))
    return samples, types, exemplars


class TestPrometheusExposition:
    def test_golden_format(self):
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
            telemetry.METRICS.set_gauge("hbm.bytes_in_use", 12345.0)
        samples, types, _ = _parse_prometheus(exposition.prometheus_text())

        # counters carry the _total suffix and the counter TYPE
        assert types["flox_tpu_cache_bundle_calls_total"] == "counter"
        assert samples["flox_tpu_cache_bundle_calls_total"] >= 1
        # gauges are plain
        assert types["flox_tpu_hbm_bytes_in_use"] == "gauge"
        assert samples["flox_tpu_hbm_bytes_in_use"] == 12345.0
        # histograms: cumulative buckets over the shared edges + sum/count
        assert types["flox_tpu_span_ms_groupby_reduce"] == "histogram"
        buckets = [
            v for k, v in samples.items()
            if k.startswith('flox_tpu_span_ms_groupby_reduce_bucket{le="')
        ]
        assert len(buckets) == len(telemetry.HIST_EDGES_MS) + 1  # edges + +Inf
        assert buckets == sorted(buckets), "buckets must be cumulative"
        assert samples['flox_tpu_span_ms_groupby_reduce_bucket{le="+Inf"}'] == (
            samples["flox_tpu_span_ms_groupby_reduce_count"]
        )
        assert samples["flox_tpu_span_ms_groupby_reduce_sum"] > 0

    def test_name_sanitization(self):
        with flox_tpu.set_options(telemetry=True):
            telemetry.METRICS.inc("serve.weird-name.v2")
        samples, _, _ = _parse_prometheus(exposition.prometheus_text())
        assert "flox_tpu_serve_weird_name_v2_total" in samples


class TestMetricsServer:
    def _get(self, port, path):
        return urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5)

    def test_endpoints(self):
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
        port = exposition.start_metrics_server(port=0)
        assert port and port > 0
        # idempotent: a second start reuses the live endpoint
        assert exposition.start_metrics_server(port=0) == port

        assert self._get(port, "/healthz").status == 200
        with pytest.raises(urllib.error.HTTPError) as err:
            self._get(port, "/readyz")
        assert err.value.code == 503  # not ready until warmup is replayed
        exposition.set_ready(True)
        assert self._get(port, "/readyz").status == 200
        with pytest.raises(urllib.error.HTTPError) as err:
            self._get(port, "/nope")
        assert err.value.code == 404

        resp = self._get(port, "/metrics")
        assert resp.status == 200
        assert "text/plain" in resp.headers["Content-Type"]
        samples, _, _ = _parse_prometheus(resp.read().decode())
        assert samples["flox_tpu_cache_bundle_calls_total"] >= 1

    def test_disabled_by_default_option(self):
        # OPTIONS["metrics_port"]=0 means no endpoint: the option-driven
        # start is a no-op returning None
        assert exposition.start_metrics_server() is None


# ---------------------------------------------------------------------------
# request tracing
# ---------------------------------------------------------------------------


class TestRequestTracing:
    def test_trace_id_on_every_child_span_mesh_and_streaming(self):
        mesh = make_mesh()
        n = 512
        labels = RNG.integers(0, 5, n)
        vals = RNG.normal(size=n)
        with flox_tpu.set_options(telemetry=True):
            with telemetry.trace("req-mesh-1"):
                groupby_reduce(vals, labels, func="sum", method="map-reduce", mesh=mesh)
            with telemetry.trace("req-stream-1"):
                streaming_groupby_reduce(vals, labels, func="sum", batch_len=128)
            records = telemetry.drain()

        by_trace: dict = {}
        for rec in records:
            by_trace.setdefault(rec.get("trace"), []).append(rec)
        # no record of either request escaped its trace context
        assert set(by_trace) <= {"req-mesh-1", "req-stream-1"}
        mesh_names = {r["name"] for r in by_trace["req-mesh-1"]}
        assert {"groupby_reduce", "factorize", "combine", "finalize"} <= mesh_names
        assert any(n.startswith(("program-build", "flox:mesh-dispatch")) for n in mesh_names)
        stream_names = {r["name"] for r in by_trace["req-stream-1"]}
        assert {"streaming_groupby_reduce", "factorize", "finalize"} <= stream_names
        assert any(n.startswith("stream[") for n in stream_names)

    def test_trace_id_in_both_export_formats(self, tmp_path):
        with flox_tpu.set_options(telemetry=True):
            with telemetry.trace("req-fmt"):
                _run_reduce()
            records = telemetry.spans()
            jsonl = tmp_path / "t.jsonl"
            chrome = tmp_path / "t.json"
            telemetry.export_jsonl(str(jsonl), records)
            telemetry.export_chrome_trace(str(chrome), records)
        parsed = [json.loads(line) for line in jsonl.read_text().splitlines()]
        spans = [r for r in parsed if r.get("type") == "span"]
        assert spans and all(r["trace"] == "req-fmt" for r in spans)
        payload = json.loads(chrome.read_text())
        events = payload["traceEvents"]
        assert events and all(ev["args"].get("trace_id") == "req-fmt" for ev in events)

    def test_trace_reaches_prefetch_worker_records(self):
        # retry events fire on the prefetch workers; the stager re-binds the
        # stream's trace there, so they still carry the request's id
        from flox_tpu import faults

        n, batch = 512, 128
        labels = RNG.integers(0, 4, n)
        vals = RNG.normal(size=n)
        loader = faults.FlakyLoader(lambda s, e: vals[s:e], {batch: OSError}, times=1)
        with flox_tpu.set_options(telemetry=True, stream_retries=2, stream_backoff=0.0):
            with telemetry.trace("req-worker"):
                streaming_groupby_reduce(
                    loader, labels, func="sum", batch_len=batch
                )
            records = telemetry.drain()
        retries = [r for r in records if r["name"] == "retry"]
        assert retries, "the flaky loader must have produced a retry event"
        assert all(r.get("trace") == "req-worker" for r in retries)

    def test_tail_sampling_keeps_only_slow_traces(self):
        with flox_tpu.set_options(telemetry=True, telemetry_level="basic"):
            # seed the running distribution: a fleet of ~100ms requests, so
            # the p99 the verdict reads is ~100ms
            for _ in range(30):
                telemetry.METRICS.observe("trace_ms", 100.0)

            # a FAST trace (well under the p99): detail records dropped
            with telemetry.trace("fast-req"):
                t0 = 1.0
                telemetry.record_span("stage", t0, t0 + 0.001, detail=True)
            fast_records = telemetry.drain()
            assert not any(r["name"] == "stage" for r in fast_records)
            assert telemetry.METRICS.get("telemetry.tail_dropped") >= 1

            # a SLOW trace (blows the running p99): detail records survive,
            # tagged with the trace id
            import time as _time

            with telemetry.trace("slow-req"):
                telemetry.record_span("stage", 1.0, 1.5, detail=True)
                _time.sleep(0.25)
            slow_records = telemetry.drain()
            kept = [r for r in slow_records if r["name"] == "stage"]
            assert kept and kept[0]["trace"] == "slow-req"
            assert telemetry.METRICS.get("telemetry.tail_kept") >= 1

    def test_detailed_level_bypasses_parking(self):
        with flox_tpu.set_options(telemetry=True, telemetry_level="detailed"):
            with telemetry.trace("det-req"):
                telemetry.record_span("stage", 1.0, 1.001, detail=True)
            records = telemetry.drain()
        assert any(r["name"] == "stage" for r in records)

    def test_serve_request_id_becomes_trace(self):
        import asyncio

        from flox_tpu.serve import AggregationRequest, Dispatcher

        async def go():
            dispatcher = Dispatcher()
            req = AggregationRequest(
                func="sum",
                array=np.array([1.0, 2.0, 4.0, 8.0]),
                by=np.array([0, 0, 1, 1]),
                request_id="req-serve-7",
            )
            result = await dispatcher.submit(req)
            await dispatcher.close()
            return result

        with flox_tpu.set_options(telemetry=True):
            result = asyncio.run(go())
            records = telemetry.drain()
        np.testing.assert_allclose(np.asarray(result.result), [3.0, 12.0])
        execute = [r for r in records if r["name"] == "serve.execute"]
        core = [r for r in records if r["name"] == "groupby_reduce"]
        request = [r for r in records if r["name"] == "serve.request"]
        assert execute and execute[0].get("trace") == "req-serve-7"
        assert core and core[0].get("trace") == "req-serve-7"
        assert request and request[0].get("trace") == "req-serve-7"


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------


class TestHbmAccounting:
    def test_memory_stats_shape(self):
        from flox_tpu import device

        stats = device.memory_stats()
        # CPU backends may report nothing; when they do report, the
        # aggregate keys are fixed
        if stats is not None:
            assert {"bytes_in_use", "peak_bytes_in_use", "devices"} <= set(stats)

    def test_fake_memory_stats_feed_gauges_and_attribution(self, monkeypatch):
        from flox_tpu import device

        feed = iter([
            {"bytes_in_use": 1000, "peak_bytes_in_use": 1500},
            {"bytes_in_use": 800, "peak_bytes_in_use": 1500},
            {"bytes_in_use": 2000, "peak_bytes_in_use": 2500},
        ])
        last = {"bytes_in_use": 500, "peak_bytes_in_use": 2500}
        monkeypatch.setattr(
            device, "memory_stats", lambda devices=None: next(feed, last)
        )
        with flox_tpu.set_options(telemetry=True):
            telemetry.sample_hbm(program="prog-a")
            telemetry.sample_hbm(program="prog-a")
            telemetry.sample_hbm(program="prog-b")
            telemetry.sample_hbm()
        # gauge = latest, peak gauge = running max
        assert telemetry.METRICS.get("hbm.bytes_in_use") == 500
        assert telemetry.METRICS.get("hbm.peak_bytes_in_use") == 2500
        # per-program attribution keeps each program's own max
        attribution = cache.stats()["hbm_by_program"]
        assert attribution == {"prog-a": 1000.0, "prog-b": 2000.0}
        cache.clear_all()
        assert cache.stats()["hbm_by_program"] == {}

    def test_dispatch_paths_attribute_programs(self, monkeypatch):
        from flox_tpu import device

        monkeypatch.setattr(
            device,
            "memory_stats",
            lambda devices=None: {"bytes_in_use": 4096, "peak_bytes_in_use": 8192},
        )
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
        attribution = cache.stats()["hbm_by_program"]
        assert any(key.startswith("bundle[") for key in attribution), attribution

    def test_disabled_sampling_is_untouched(self, monkeypatch):
        from flox_tpu import device

        def boom(devices=None):  # pragma: no cover - must never run
            raise AssertionError("memory_stats consulted while disabled")

        monkeypatch.setattr(device, "memory_stats", boom)
        telemetry.sample_hbm(program="nope")
        assert telemetry.METRICS.snapshot() == {}
        assert cache.stats()["hbm_by_program"] == {}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        with flox_tpu.set_options(telemetry=True, flight_recorder_size=16):
            for i in range(64):
                telemetry.event("tick", i=i)
            records = telemetry.FLIGHT_RECORDER.records()
        assert len(records) == 16
        assert records[-1]["attrs"]["i"] == 63  # newest kept, oldest dropped

    def test_dump_on_fatal_fault_roundtrips_through_report(self, tmp_path, capsys):
        from flox_tpu.resilience import RetryPolicy, call_with_retry

        dump = tmp_path / "flight.jsonl"
        with flox_tpu.set_options(telemetry=True, flight_recorder_path=str(dump)):
            _run_reduce()  # populate the ring with real spans

            def fatal():
                raise ValueError("programming error")

            with pytest.raises(ValueError, match="programming error"):
                call_with_retry(fatal, policy=RetryPolicy(retries=3, backoff=0.0))
        assert dump.exists(), "fatal classification must dump the flight recorder"
        parsed = [json.loads(line) for line in dump.read_text().splitlines()]
        header = parsed[0]
        assert header["name"] == "flight-recorder"
        assert header["attrs"]["reason"].startswith("fatal:ValueError")
        names = {r.get("name") for r in parsed}
        assert "groupby_reduce" in names, "ring must hold the pre-fault spans"
        assert "fatal" in names, "the fatal event itself must be recorded"
        assert parsed[-1]["type"] == "counters"
        # the dump is a valid telemetry export: report exits 0 and
        # summarizes it
        assert telemetry.main(["report", str(dump)]) == 0
        out = capsys.readouterr().out
        assert "groupby_reduce" in out

    def test_transient_fault_does_not_dump(self, tmp_path):
        from flox_tpu.resilience import RetryPolicy, call_with_retry

        dump = tmp_path / "flight.jsonl"
        with flox_tpu.set_options(telemetry=True, flight_recorder_path=str(dump)):
            calls = {"n": 0}

            def flaky():
                calls["n"] += 1
                if calls["n"] < 2:
                    raise OSError("transient hiccup")
                return "ok"

            assert call_with_retry(flaky, policy=RetryPolicy(retries=3, backoff=0.0)) == "ok"
        assert not dump.exists()

    def test_dump_on_signal(self, tmp_path):
        if not hasattr(signal, "SIGUSR2"):
            pytest.skip("no SIGUSR2 on this platform")
        dump = tmp_path / "flight-signal.jsonl"
        # install_signal_dumps registers BOTH signals: restore both, or the
        # SIGTERM dump handler leaks into every later test in this process
        previous = {
            sig: signal.getsignal(sig) for sig in (signal.SIGTERM, signal.SIGUSR2)
        }
        try:
            with flox_tpu.set_options(telemetry=True, flight_recorder_path=str(dump)):
                telemetry.event("before-signal")
                telemetry.install_signal_dumps()
                os.kill(os.getpid(), signal.SIGUSR2)
            assert dump.exists()
            parsed = [json.loads(line) for line in dump.read_text().splitlines()]
            assert parsed[0]["attrs"]["reason"] == "signal:SIGUSR2"
            assert any(r.get("name") == "before-signal" for r in parsed)
        finally:
            for sig, handler in previous.items():
                signal.signal(sig, handler)

    def test_unconfigured_dump_is_noop(self):
        with flox_tpu.set_options(telemetry=True):
            telemetry.event("something")
            assert telemetry.flight_dump(reason="no path") is None


# ---------------------------------------------------------------------------
# bit-identity + disabled-path contracts
# ---------------------------------------------------------------------------


class TestPlaneNeutrality:
    def test_bit_identity_with_plane_enabled(self, tmp_path, monkeypatch):
        from flox_tpu import device

        expected, groups = _run_reduce()
        monkeypatch.setattr(
            device,
            "memory_stats",
            lambda devices=None: {"bytes_in_use": 1, "peak_bytes_in_use": 2},
        )
        with flox_tpu.set_options(
            telemetry=True,
            flight_recorder_path=str(tmp_path / "f.jsonl"),
        ):
            port = exposition.start_metrics_server(port=0)
            with telemetry.trace("bit-req"):
                got, g2 = _run_reduce()
            assert (
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ).status
                == 200
            )
        np.testing.assert_array_equal(np.asarray(expected), np.asarray(got))
        np.testing.assert_array_equal(np.asarray(groups), np.asarray(g2))

    def test_disabled_path_allocates_nothing(self):
        # trace() and span() hand back the one shared no-op; the registry,
        # the buffer, and the flight ring stay untouched
        assert telemetry.trace("req-x") is telemetry.span("anything")
        with telemetry.trace("req-x"):
            _run_reduce()
        assert telemetry.current_trace() is None
        assert telemetry.spans() == []
        assert telemetry.METRICS.snapshot() == {}
        assert len(telemetry.FLIGHT_RECORDER) == 0


class TestNewOptions:
    @pytest.mark.parametrize(
        "bad",
        [
            {"metrics_port": -1},
            {"metrics_port": 70000},
            {"metrics_port": 1.5},
            {"flight_recorder_path": ""},
            {"flight_recorder_size": 0},
            {"flight_recorder_size": True},
            {"profile_dir": ""},
            {"profile_keep": 0},
            {"profile_keep": True},
            {"metrics_sample_interval": -1.0},
            {"metrics_sample_interval": float("inf")},
            {"replica_id": ""},
            {"replica_id": 'bad"label'},
            {"fleet_scrape_interval": 0.0},
            {"fleet_port": -1},
            {"fleet_replicas": ""},
        ],
    )
    def test_validated_at_set_time(self, bad):
        with pytest.raises(ValueError):
            flox_tpu.set_options(**bad)

    def test_env_mirrors_exist(self):
        # the FLX010 contract, asserted at runtime too: every new knob has
        # an env mirror spelled FLOX_TPU_<NAME>
        import inspect

        from flox_tpu import options as opts

        src = inspect.getsource(opts)
        for name in (
            "metrics_port", "flight_recorder_path", "flight_recorder_size",
            "profile_dir", "profile_keep", "metrics_sample_interval",
            "replica_id", "fleet_scrape_interval", "fleet_port",
            "fleet_replicas",
        ):
            assert f"FLOX_TPU_{name.upper()}" in src


# ---------------------------------------------------------------------------
# cost ledger (ISSUE 9)
# ---------------------------------------------------------------------------


class TestCostLedger:
    def test_eager_dispatch_feeds_program_ledger(self):
        cache.clear_all()  # fresh bundle: the first call must pay a compile
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
            _run_reduce()
        costs = cache.stats()["cost_by_program"]
        bundle = [k for k in costs if k.startswith("bundle[")]
        assert bundle, costs
        row = costs[bundle[0]]
        assert row["dispatches"] == 2
        assert row["device_ms"] > 0
        assert row["device_ms_max"] <= row["device_ms"]
        assert row["bytes"] > 0
        # the first call compiled, the second was a cache hit
        assert row["compiles"] >= 1
        cache.clear_all()
        assert cache.stats()["cost_by_program"] == {}

    def test_mesh_and_streaming_dispatches_attributed(self):
        mesh = make_mesh()
        n = 512
        labels = RNG.integers(0, 5, n)
        vals = RNG.normal(size=n)
        with flox_tpu.set_options(telemetry=True):
            groupby_reduce(vals, labels, func="sum", method="map-reduce", mesh=mesh)
            streaming_groupby_reduce(vals, labels, func="sum", batch_len=128)
        costs = cache.stats()["cost_by_program"]
        assert any(k.startswith("mesh[") for k in costs), costs
        assert any(k.startswith("stream[") for k in costs), costs
        stream_rows = [v for k, v in costs.items() if k.startswith("stream[")]
        assert stream_rows[0]["bytes"] > 0  # staged slab bytes attributed

    def test_slow_trace_id_lands_in_ledger(self):
        with flox_tpu.set_options(telemetry=True):
            with telemetry.trace("req-slowest"):
                _run_reduce()
        costs = cache.stats()["cost_by_program"]
        bundle = [v for k, v in costs.items() if k.startswith("bundle[")]
        assert bundle and bundle[0]["last_slow_trace"] == "req-slowest"

    def test_hbm_peak_absorbed_into_ledger(self, monkeypatch):
        from flox_tpu import device

        monkeypatch.setattr(
            device,
            "memory_stats",
            lambda devices=None: {"bytes_in_use": 4096, "peak_bytes_in_use": 8192},
        )
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
        costs = cache.stats()["cost_by_program"]
        bundle = [k for k in costs if k.startswith("bundle[")]
        assert costs[bundle[0]]["hbm_peak"] == 4096
        # the hbm_by_program view is the ledger's hbm_peak column
        assert cache.stats()["hbm_by_program"][bundle[0]] == 4096

    def test_disabled_path_records_nothing(self):
        telemetry.observe_cost("nope", device_ms=1.0, nbytes=10)
        assert cache.stats()["cost_by_program"] == {}
        assert cache.stats()["cost_by_tenant"] == {}

    def test_costs_cli_live_and_file(self, tmp_path, capsys):
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
        assert telemetry.main(["costs"]) == 0
        out = capsys.readouterr().out
        assert "bundle[" in out and "program key" in out
        # a /debug/costs-shaped scrape file round-trips through the CLI
        scrape = tmp_path / "costs.json"
        scrape.write_text(json.dumps({
            "cost_by_program": telemetry.cost_by_program(),
            "cost_by_tenant": telemetry.cost_by_tenant(),
        }))
        assert telemetry.main(["costs", str(scrape), "--top", "1"]) == 0
        out = capsys.readouterr().out
        assert "bundle[" in out

    def test_costs_cli_rejects_garbage(self, tmp_path, capsys):
        bad = tmp_path / "bad.json"
        bad.write_text("not json")
        with pytest.raises(SystemExit):
            telemetry.main(["costs", str(bad)])


class TestTenantAxis:
    def _submit(self, tenant=None, request_id=None):
        import asyncio

        from flox_tpu.serve import AggregationRequest, Dispatcher

        async def go():
            dispatcher = Dispatcher()
            result = await dispatcher.submit(AggregationRequest(
                func="sum",
                array=np.array([1.0, 2.0, 4.0, 8.0]),
                by=np.array([0, 0, 1, 1]),
                tenant=tenant,
                request_id=request_id,
            ))
            await dispatcher.close()
            return result

        return asyncio.run(go())

    def test_tenant_feeds_ledger_and_labeled_histogram(self):
        with flox_tpu.set_options(telemetry=True):
            result = self._submit(tenant="acme", request_id="req-t1")
        np.testing.assert_allclose(np.asarray(result.result), [3.0, 12.0])
        tenants = cache.stats()["cost_by_tenant"]
        assert "acme" in tenants, tenants
        assert tenants["acme"]["dispatches"] == 1
        assert tenants["acme"]["bytes"] > 0
        samples, types, _ = _parse_prometheus(exposition.prometheus_text())
        labeled = [
            k for k in samples
            if k.startswith('flox_tpu_serve_request_ms_bucket{tenant="acme",le="')
        ]
        assert len(labeled) == len(telemetry.HIST_EDGES_MS) + 1  # edges + +Inf
        assert samples['flox_tpu_serve_request_ms_count{tenant="acme"}'] == 1
        # ONE TYPE line covers the base metric and its labeled series
        assert types["flox_tpu_serve_request_ms"] == "histogram"
        text = exposition.prometheus_text()
        assert text.count("# TYPE flox_tpu_serve_request_ms histogram") == 1

    def test_untagged_requests_leave_no_tenant_rows(self):
        with flox_tpu.set_options(telemetry=True):
            self._submit()
        assert cache.stats()["cost_by_tenant"] == {}

    def test_tenant_label_sanitized_against_injection(self):
        # a client-chosen tag must not be able to inject label syntax into
        # the exposition (a raw `|le=5` would render a duplicate le label
        # and poison the whole scrape for every consumer)
        with flox_tpu.set_options(telemetry=True):
            self._submit(tenant='evil|le=5"x')
        tenants = cache.stats()["cost_by_tenant"]
        assert list(tenants) == ["evil_le_5_x"]
        text = exposition.prometheus_text()
        assert 'tenant="evil_le_5_x"' in text
        # every bucket line still carries exactly ONE le label
        for line in text.splitlines():
            if "_bucket{" in line:
                assert line.count('le="') == 1, line
        _parse_prometheus(text)  # and the whole exposition still parses

    def test_tenant_cardinality_is_bounded(self):
        # unique client tags past the cap fold into "_other" instead of
        # allocating a fresh histogram per string
        with flox_tpu.set_options(telemetry=True):
            for i in range(telemetry._TENANT_MAX + 5):
                assert telemetry.tenant_label(f"t{i}") == (
                    f"t{i}" if i < telemetry._TENANT_MAX else "_other"
                )
            # known labels keep resolving to themselves past the cap
            assert telemetry.tenant_label("t0") == "t0"
        cache.clear_all()
        assert telemetry.tenant_label("fresh") == "fresh"

    def test_coalesced_tenant_billing_sums_to_dispatch_wall(self):
        # K coalesced requests share ONE dispatch; the tenant axis bills
        # each its share, so tenant totals never exceed program totals
        import asyncio

        from flox_tpu.serve import AggregationRequest, Dispatcher

        async def go():
            dispatcher = Dispatcher(batch_window=0.05)
            arr = np.array([1.0, 2.0, 4.0, 8.0])
            by = np.array([0, 0, 1, 1])
            results = await asyncio.gather(*[
                dispatcher.submit(AggregationRequest(
                    func="sum", array=arr, by=by, tenant="acme"
                ))
                for _ in range(3)
            ])
            await dispatcher.close()
            return results

        with flox_tpu.set_options(telemetry=True):
            results = asyncio.run(go())
        assert len(results) == 3
        assert telemetry.METRICS.get("serve.dispatches") == 1
        stats = cache.stats()
        program_ms = sum(
            row["device_ms"] for key, row in stats["cost_by_program"].items()
            if key.startswith("serve[")
        )
        tenant_ms = stats["cost_by_tenant"]["acme"]["device_ms"]
        assert tenant_ms <= program_ms * 1.001 + 1e-6, (tenant_ms, program_ms)

    def test_tenant_does_not_change_results(self):
        with flox_tpu.set_options(telemetry=True):
            tagged = self._submit(tenant="acme")
            untagged = self._submit()
        np.testing.assert_array_equal(
            np.asarray(tagged.result), np.asarray(untagged.result)
        )


# ---------------------------------------------------------------------------
# exemplars (ISSUE 9)
# ---------------------------------------------------------------------------


class TestExemplars:
    def test_bucket_lines_parse_with_and_without_exemplars(self):
        with flox_tpu.set_options(telemetry=True):
            # one traced observation (carries an exemplar) and one bare
            telemetry.METRICS.observe("demo_ms", 0.5, exemplar="req-ex-1")
            telemetry.METRICS.observe("demo_ms", 700.0)
        text = exposition.prometheus_text()
        samples, _, exemplars = _parse_prometheus(text)
        with_ex = [k for k in exemplars if k.startswith("flox_tpu_demo_ms_bucket")]
        assert len(with_ex) == 1
        labels, observed = exemplars[with_ex[0]]
        assert labels == '{trace_id="req-ex-1"}'
        assert observed == 0.5
        # the untraced observation's bucket line carries none, and both
        # still parse as ordinary cumulative samples
        buckets = [
            v for k, v in samples.items()
            if k.startswith('flox_tpu_demo_ms_bucket{le="')
        ]
        assert buckets == sorted(buckets)
        assert samples['flox_tpu_demo_ms_bucket{le="+Inf"}'] == 2

    def test_exemplar_keeps_max_observation_per_bucket(self):
        with flox_tpu.set_options(telemetry=True):
            # both land in the same bucket; the larger wins the slot
            telemetry.METRICS.observe("demo_ms", 0.40, exemplar="req-small")
            telemetry.METRICS.observe("demo_ms", 0.51, exemplar="req-big")
            telemetry.METRICS.observe("demo_ms", 0.45, exemplar="req-mid")
        _, _, exemplars = _parse_prometheus(exposition.prometheus_text())
        (labels, observed), = exemplars.values()
        assert labels == '{trace_id="req-big"}'
        assert observed == 0.51

    def test_http_scrape_clean_by_default_exemplars_on_request(self):
        # the classic 0.0.4 text parser (a default Prometheus scrape)
        # aborts on exemplars, so the plain endpoint must omit them; a
        # scraper that wants them asks with ?exemplars=1
        with flox_tpu.set_options(telemetry=True):
            telemetry.METRICS.observe("demo_ms", 0.5, exemplar="req-http")
            port = exposition.start_metrics_server(port=0)
            plain = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ).read().decode()
            rich = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics?exemplars=1", timeout=5
            ).read().decode()
        assert " # {trace_id=" not in plain
        assert ' # {trace_id="req-http"}' in rich
        _parse_prometheus(plain)
        _parse_prometheus(rich)

    def test_exemplar_trace_id_is_escaped(self):
        # trace ids are client-supplied (request ids): quotes/backslashes
        # must not produce malformed label syntax on the bucket line
        with flox_tpu.set_options(telemetry=True):
            telemetry.METRICS.observe("demo_ms", 0.5, exemplar='r"1\\x')
        text = exposition.prometheus_text()
        assert ' # {trace_id="r\\"1\\\\x"}' in text
        _parse_prometheus(text)

    def test_traced_spans_carry_exemplars_to_metrics(self):
        with flox_tpu.set_options(telemetry=True):
            with telemetry.trace("req-exemplar"):
                _run_reduce()
        _, _, exemplars = _parse_prometheus(exposition.prometheus_text())
        span_ex = {
            k: v for k, v in exemplars.items()
            if k.startswith("flox_tpu_span_ms_groupby_reduce_bucket")
        }
        assert span_ex
        assert all(v[0] == '{trace_id="req-exemplar"}' for v in span_ex.values())

    def test_report_links_slowest_trace(self, tmp_path, capsys):
        with flox_tpu.set_options(telemetry=True):
            with telemetry.trace("req-linked"):
                _run_reduce()
            export = tmp_path / "t.jsonl"
            telemetry.export_jsonl(str(export))
        assert telemetry.main(["report", str(export), "--histograms"]) == 0
        out = capsys.readouterr().out
        assert "slowest trace" in out
        assert "req-linked" in out


# ---------------------------------------------------------------------------
# on-demand capture (ISSUE 9)
# ---------------------------------------------------------------------------


class _FakeProfiler:
    """Deterministic stand-in for jax.profiler: records start/stop calls
    without touching the real (backend-dependent) profiler."""

    def __init__(self, fail_start=False):
        self.fail_start = fail_start
        self.starts: list[str] = []
        self.stops = 0

    def install(self, monkeypatch):
        import jax

        def start_trace(logdir):
            if self.fail_start:
                raise RuntimeError("no profiler on this backend")
            os.makedirs(logdir, exist_ok=True)
            self.starts.append(logdir)

        monkeypatch.setattr(jax.profiler, "start_trace", start_trace)
        monkeypatch.setattr(jax.profiler, "stop_trace", lambda: setattr(
            self, "stops", self.stops + 1
        ))
        return self


class TestOnDemandCapture:
    @pytest.fixture(autouse=True)
    def _fresh_capture_state(self):
        from flox_tpu import profiling

        profiling._CAPTURE_STATE.clear()
        yield
        profiling._CAPTURE_STATE.clear()

    def test_unconfigured_root_is_unavailable(self):
        from flox_tpu import profiling

        with pytest.raises(profiling.CaptureUnavailableError):
            profiling.start_capture(seconds=0.05)

    def test_capture_runs_and_guard_clears(self, tmp_path, monkeypatch):
        import time as _time

        from flox_tpu import profiling

        fake = _FakeProfiler().install(monkeypatch)
        with flox_tpu.set_options(telemetry=True, profile_dir=str(tmp_path)):
            capture_dir = profiling.start_capture(seconds=0.05)
            assert capture_dir.startswith(str(tmp_path))
            assert cache.stats()["profile_capture_active"] is True
            # a second capture while one runs is refused (HTTP 409)
            with pytest.raises(profiling.CaptureBusyError):
                profiling.start_capture(seconds=0.05)
            for _ in range(100):
                if profiling.capture_active() is None:
                    break
                _time.sleep(0.02)
        assert profiling.capture_active() is None
        assert fake.starts == [capture_dir]
        assert fake.stops == 1
        assert telemetry.METRICS.get("profile.captures") == 1

    def test_profiler_less_backend_is_unavailable(self, tmp_path, monkeypatch):
        from flox_tpu import profiling

        _FakeProfiler(fail_start=True).install(monkeypatch)
        with flox_tpu.set_options(profile_dir=str(tmp_path)):
            with pytest.raises(profiling.CaptureUnavailableError):
                profiling.start_capture(seconds=0.05)
        # the guard did not leak: a later capture may start
        assert profiling.capture_active() is None

    def test_capture_dir_rotation(self, tmp_path, monkeypatch):
        import time as _time

        from flox_tpu import profiling

        _FakeProfiler().install(monkeypatch)
        with flox_tpu.set_options(profile_dir=str(tmp_path), profile_keep=2):
            for _ in range(4):
                profiling.start_capture(seconds=0.01)
                for _ in range(100):
                    if profiling.capture_active() is None:
                        break
                    _time.sleep(0.02)
        captures = sorted(p.name for p in tmp_path.iterdir())
        assert len(captures) <= 2, captures

    def test_http_endpoint_409_and_501(self, tmp_path, monkeypatch):
        import time as _time

        from flox_tpu import profiling

        _FakeProfiler().install(monkeypatch)
        port = exposition.start_metrics_server(port=0)
        with flox_tpu.set_options(telemetry=True, profile_dir=str(tmp_path)):
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/profile?seconds=0.3", timeout=5
            )
            assert resp.status == 202
            payload = json.loads(resp.read())
            assert payload["ok"] and payload["dir"].startswith(str(tmp_path))
            # concurrent second request: 409, and the reply names the clash
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/profile?seconds=0.3", timeout=5
                )
            assert err.value.code == 409
            for _ in range(100):
                if profiling.capture_active() is None:
                    break
                _time.sleep(0.02)
        # unconfigured root -> clean 501, never an exception in the server
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/profile", timeout=5
            )
        assert err.value.code == 501

    def test_trace_defaults_to_profile_dir_and_warns_without_profiler(
        self, tmp_path, monkeypatch, caplog
    ):
        import logging as _logging

        from flox_tpu import profiling

        with pytest.raises(ValueError, match="profile_dir"):
            with profiling.trace():
                pass
        fake = _FakeProfiler().install(monkeypatch)
        with flox_tpu.set_options(profile_dir=str(tmp_path)):
            with profiling.trace():
                pass
        assert fake.starts == [str(tmp_path)]
        # a profiler-less backend warns and runs the block untraced
        _FakeProfiler(fail_start=True).install(monkeypatch)
        ran = []
        with caplog.at_level(_logging.WARNING, logger="flox_tpu.profiling"):
            with profiling.trace(str(tmp_path)):
                ran.append(True)
        assert ran == [True]
        assert any("untraced" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# /debug/costs + saturation gauges (ISSUE 9)
# ---------------------------------------------------------------------------


class TestDebugCostsEndpoint:
    def test_scrape_matches_cache_stats(self, tmp_path):
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
            port = exposition.start_metrics_server(port=0)
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/costs", timeout=5
            )
            assert resp.status == 200
            assert "application/json" in resp.headers["Content-Type"]
            payload = json.loads(resp.read())
            stats = cache.stats()
        assert set(payload) >= {"cost_by_program", "cost_by_tenant", "hbm_by_program"}
        assert payload["cost_by_program"].keys() == stats["cost_by_program"].keys()
        bundle = [k for k in payload["cost_by_program"] if k.startswith("bundle[")]
        assert payload["cost_by_program"][bundle[0]]["dispatches"] >= 1
        # the scrape is exactly what `telemetry costs` tabulates
        scrape = tmp_path / "scrape.json"
        scrape.write_text(json.dumps(payload))
        assert telemetry.main(["costs", str(scrape), "--top", "3"]) == 0


class TestSaturationGauges:
    def test_seeded_to_zero_at_server_start(self):
        with flox_tpu.set_options(telemetry=True):
            port = exposition.start_metrics_server(port=0)
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            )
            samples, types, _ = _parse_prometheus(resp.read().decode())
        for name in telemetry.SATURATION_GAUGES:
            metric = "flox_tpu_" + name.replace(".", "_")
            assert samples[metric] == 0, f"{metric} not seeded"
            assert types[metric] == "gauge"

    def test_seeding_never_rewinds_a_live_gauge(self):
        with flox_tpu.set_options(telemetry=True):
            telemetry.METRICS.set_gauge("serve.queue_depth", 7)
            telemetry.seed_saturation_gauges()
            assert telemetry.METRICS.get("serve.queue_depth") == 7

    def test_sample_saturation_reads_live_state(self):
        from flox_tpu import pipeline
        from flox_tpu.serve.dispatcher import _PENDING_REGISTRY

        with flox_tpu.set_options(telemetry=True):
            _PENDING_REGISTRY[991] = object()
            pipeline._PREFETCH_INFLIGHT[0] = 3
            try:
                telemetry.sample_saturation()
            finally:
                _PENDING_REGISTRY.pop(991, None)
                pipeline._PREFETCH_INFLIGHT[0] = 0
            assert telemetry.METRICS.get("serve.queue_depth") == 1
            assert telemetry.METRICS.get("stream.prefetch_occupancy") == 3

    def test_sampler_thread_runs_and_stops(self):
        import time as _time

        with flox_tpu.set_options(telemetry=True, metrics_sample_interval=0.01):
            assert telemetry.start_saturation_sampler() is True
            # idempotent while live
            assert telemetry.start_saturation_sampler() is True
            for _ in range(200):
                if telemetry.METRICS.gauges().get("serve.queue_depth") is not None:
                    break
                _time.sleep(0.01)
            assert telemetry.METRICS.gauges().get("serve.queue_depth") == 0
        telemetry.stop_saturation_sampler()
        assert telemetry._SAMPLER_STATE["thread"] is None

    def test_sampler_off_by_default(self):
        with flox_tpu.set_options(telemetry=True):
            assert telemetry.start_saturation_sampler() is False

    def test_prefetch_occupancy_returns_to_zero_after_stream(self):
        from flox_tpu import pipeline

        n = 512
        labels = RNG.integers(0, 4, n)
        vals = RNG.normal(size=n)
        with flox_tpu.set_options(telemetry=True, stream_prefetch=2):
            streaming_groupby_reduce(vals, labels, func="sum", batch_len=64)
        assert pipeline.prefetch_occupancy() == 0


class TestFullPlaneBitIdentity:
    def test_bit_identity_with_cost_plane_enabled(self, tmp_path, monkeypatch):
        # the whole ISSUE 9 plane at once: cost ledger feeding, exemplars,
        # tenant axis off, saturation sampler live, capture state guarded —
        # results must stay bit-identical to the disabled run
        from flox_tpu import device

        expected, groups = _run_reduce()
        monkeypatch.setattr(
            device,
            "memory_stats",
            lambda devices=None: {"bytes_in_use": 1, "peak_bytes_in_use": 2},
        )
        with flox_tpu.set_options(
            telemetry=True,
            metrics_sample_interval=0.01,
            profile_dir=str(tmp_path),
            flight_recorder_path=str(tmp_path / "f.jsonl"),
        ):
            port = exposition.start_metrics_server(port=0)
            with telemetry.trace("bit-req-9"):
                got, g2 = _run_reduce()
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/costs", timeout=5
            )
            assert resp.status == 200
        np.testing.assert_array_equal(np.asarray(expected), np.asarray(got))
        np.testing.assert_array_equal(np.asarray(groups), np.asarray(g2))
        assert cache.stats()["cost_by_program"]


# ---------------------------------------------------------------------------
# analytical cost model (ISSUE 14)
# ---------------------------------------------------------------------------


from flox_tpu import costmodel, device as device_mod, faults  # noqa: E402


def _plane(**extra):
    return flox_tpu.set_options(telemetry=True, costmodel=True, **extra)


class TestCostModelCards:
    def test_off_by_default_is_a_noop(self):
        # costmodel pinned off explicitly: the assertion must hold under a
        # CI leg exporting FLOX_TPU_COSTMODEL=1 too
        with flox_tpu.set_options(telemetry=True, costmodel=False):
            _run_reduce()
        assert costmodel.cards() == {}
        assert cache.stats()["costmodel_cards"] == 0
        gauges = telemetry.METRICS.gauges()
        assert not any(k.startswith("program.") for k in gauges)

    def test_eager_bundle_card_nonzero_flops_and_bytes(self):
        with _plane():
            _run_reduce()
        card = costmodel.card_for("bundle[nanmean]")
        assert card is not None
        assert card["analysis"] == "ok"
        assert card["flops"] > 0 and card["bytes_accessed"] > 0
        assert card["predicted_ms"] > 0
        assert card["hlo_hash"]
        assert cache.stats()["costmodel_cards"] >= 1

    def test_card_compiles_never_pollute_jax_compiles(self):
        # the analysis pass compiles the program a second time; that
        # compile must count on costmodel.card_* and leave jax.compiles
        # exactly where a cards-off run puts it
        cache.clear_all()
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
            baseline = telemetry.METRICS.get("jax.compiles")
        assert baseline >= 1  # a fresh bundle really compiled
        cache.clear_all()
        with _plane():
            _run_reduce()
            assert telemetry.METRICS.get("jax.compiles") == baseline
            assert telemetry.METRICS.get("costmodel.card_compiles") >= 1
            assert telemetry.METRICS.get("costmodel.card_compile_ms") > 0

    def test_every_runtime_path_has_a_card(self):
        # acceptance: eager, fused, streaming, and mesh dispatches on the
        # CPU backend all yield cards with nonzero analytical flops+bytes
        from flox_tpu.fusion import groupby_aggregate_many

        vals = RNG.normal(size=48)
        codes = np.arange(48) % 5
        with _plane():
            _run_reduce()
            groupby_aggregate_many(
                vals, codes, funcs=("sum", "min", "max"), engine="jax"
            )
            streaming_groupby_reduce(vals, codes, func="sum", batch_len=16)
            mesh = make_mesh(1)
            groupby_reduce(
                vals, codes, func="sum", engine="jax",
                method="map-reduce", mesh=mesh,
            )
        by_label = {c["label"]: c for c in costmodel.cards().values()}
        for label in (
            "bundle[nanmean]",
            "fused[sum+min+max]",
            "stream[reduce[sum]]",
            "mesh[sum/map-reduce]",
        ):
            card = by_label[label]
            assert card["analysis"] == "ok", (label, card)
            assert card["flops"] > 0, label
            assert card["bytes_accessed"] > 0, label
        # and each label joins its observed ledger row
        report = costmodel.program_report()["programs"]
        for label in by_label:
            assert report[label]["observed"] is not None, label

    def test_cards_memoized_per_signature(self):
        with _plane():
            _run_reduce()
            n0 = telemetry.METRICS.get("costmodel.card_compiles")
            _run_reduce()  # same program+shape: registry hit, no compile
            assert telemetry.METRICS.get("costmodel.card_compiles") == n0
        assert cache.stats()["costmodel_cards"] == 1

    def test_serve_dispatch_aliases_underlying_card(self):
        import asyncio

        from flox_tpu.serve import Dispatcher

        async def go():
            d = Dispatcher()
            res = await d.submit(
                func="sum",
                array=np.array([1.0, 2.0, 4.0, 8.0]),
                by=np.array([0, 0, 1, 1]),
                # pin the jit engine: a tiny payload under x64 would take
                # the numpy engine, which compiles no program to card
                options={"numpy_engine_max_elems": 0},
            )
            await d.close()
            return res

        with _plane():
            asyncio.run(go())
        serve_labels = [
            label
            for label in costmodel.program_report()["programs"]
            if label.startswith("serve[")
        ]
        assert serve_labels, costmodel.program_report()["programs"].keys()
        card = costmodel.card_for(serve_labels[0])
        assert card is not None and card["flops"] > 0

    def test_clear_all_drops_the_registry(self):
        with _plane():
            _run_reduce()
        assert costmodel.cards()
        cache.clear_all()
        assert costmodel.cards() == {}
        assert costmodel.card_for("bundle[nanmean]") is None
        assert cache.stats()["costmodel_cards"] == 0

    def test_full_plane_bit_identity(self):
        # acceptance: results with telemetry + cards enabled are
        # bit-identical to the plane off — eager, mesh, and streaming
        from flox_tpu.fusion import groupby_aggregate_many

        vals = RNG.normal(size=(3, 48))
        flat = vals[0]
        codes = np.arange(48) % 5
        mesh = make_mesh(1)

        def run_all():
            out = {}
            out["eager"], _ = groupby_reduce(vals, codes, func="nanmean", engine="jax")
            out["mesh"], _ = groupby_reduce(
                vals, codes, func="sum", engine="jax",
                method="map-reduce", mesh=mesh,
            )
            out["stream"], _ = streaming_groupby_reduce(
                flat, codes, func="sum", batch_len=16
            )
            fused, _ = groupby_aggregate_many(flat, codes, funcs=("sum", "max"))
            out.update({f"fused[{k}]": v for k, v in fused.items()})
            return {k: np.asarray(v) for k, v in out.items()}

        cache.clear_all()
        baseline = run_all()
        cache.clear_all()
        with _plane():
            instrumented = run_all()
        assert instrumented.keys() == baseline.keys()
        for key in baseline:
            np.testing.assert_array_equal(instrumented[key], baseline[key])


class TestRooflineJoin:
    def test_gauges_published_and_scrape_clean(self):
        with _plane():
            _run_reduce()
        gauges = telemetry.METRICS.gauges()
        assert "program.utilization|program=bundle[nanmean]" in gauges
        assert "program.predicted_ms|program=bundle[nanmean]" in gauges
        assert gauges["program.predicted_ms|program=bundle[nanmean]"] > 0
        with flox_tpu.set_options(telemetry=True):
            text = exposition.prometheus_text()
        samples, types, _ = _parse_prometheus(text)
        assert types["flox_tpu_program_utilization"] == "gauge"
        assert any(
            k.startswith('flox_tpu_program_utilization{program="bundle[nanmean]"')
            for k in samples
        ), [k for k in samples if "program_util" in k]

    def test_utilization_is_model_over_observed(self):
        with _plane():
            _run_reduce()
        row = costmodel.program_report()["programs"]["bundle[nanmean]"]
        obs = row["observed"]
        net_ms = max(0.0, obs["device_ms"] - obs["compile_ms"])
        if net_ms > 0:
            expected = row["predicted_ms"] * obs["dispatches"] / net_ms
            # abs tolerance: the published value is rounded to 6 places
            assert row["utilization"] == pytest.approx(expected, abs=1e-6)

    def test_program_report_filters(self):
        with _plane():
            _run_reduce()
            streaming_groupby_reduce(
                RNG.normal(size=48), np.arange(48) % 5, func="sum", batch_len=16
            )
        full = costmodel.program_report()["programs"]
        assert len(full) >= 2
        only = costmodel.program_report(program="bundle[")["programs"]
        assert set(only) == {k for k in full if "bundle[" in k}
        top1 = costmodel.program_report(top=1)["programs"]
        assert len(top1) == 1


class TestDriftSentinel:
    def test_honest_run_is_clean(self):
        with _plane():
            _run_reduce()
            _run_reduce()
            report = costmodel.drift_report()
        assert report["flagged"] == []
        assert report["rows"], "the bundle row must be judged"

    def test_injected_delay_flags_and_scrape_drift_matches(self):
        with _plane():
            _run_reduce()  # cold: pays the compile (net out of the model)
            with faults.dispatch_delay_inject("bundle[nanmean]", 0.5, times=1):
                _run_reduce()
            report = costmodel.drift_report()
            assert report["flagged"] == ["bundle[nanmean]"]
            # the sentinel runs identically over a /debug/programs scrape
            rows = costmodel.program_report()["programs"]
            again = costmodel.drift_report(rows)
            assert again["flagged"] == ["bundle[nanmean]"]

    def test_threshold_option_validated(self):
        with pytest.raises(ValueError):
            flox_tpu.set_options(costmodel_drift_threshold=0.5)
        with pytest.raises(ValueError):
            flox_tpu.set_options(costmodel_overhead_ms=-1.0)
        with pytest.raises(ValueError):
            flox_tpu.set_options(costmodel="yes")


class TestDebugProgramsEndpoint:
    def _get(self, port, path):
        return urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5)

    def test_golden_format_and_filters(self):
        with _plane():
            _run_reduce()
            port = exposition.start_metrics_server(port=0)
            resp = self._get(port, "/debug/programs")
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("application/json")
            payload = json.loads(resp.read())
            assert "programs" in payload and "peaks" in payload
            assert payload["replica"] and payload["host"]
            row = payload["programs"]["bundle[nanmean]"]
            for key in (
                "digest", "flops", "bytes_accessed", "predicted_ms",
                "analysis", "observed", "utilization", "hlo_hash",
            ):
                assert key in row, key
            assert row["flops"] > 0
            assert row["observed"]["dispatches"] >= 1
            # ?top= keeps the K most expensive rows
            top = json.loads(self._get(port, "/debug/programs?top=1").read())
            assert len(top["programs"]) == 1
            # ?program= filters by substring
            none = json.loads(
                self._get(port, "/debug/programs?program=nosuch").read()
            )
            assert none["programs"] == {}

    def test_malformed_top_is_400(self):
        with _plane():
            port = exposition.start_metrics_server(port=0)
            for bad in ("abc", "0", "-3"):
                with pytest.raises(urllib.error.HTTPError) as err:
                    self._get(port, f"/debug/programs?top={bad}")
                assert err.value.code == 400
                body = json.loads(err.value.read())
                assert body["ok"] is False


class TestProgramsCLI:
    def test_live_and_file_and_top(self, tmp_path, capsys):
        with _plane():
            _run_reduce()
            assert telemetry.main(["programs"]) == 0
            out = capsys.readouterr().out
            assert "bundle[nanmean]" in out and "live process" in out
            port = exposition.start_metrics_server(port=0)
            scrape = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/programs", timeout=5
            ).read()
        path = tmp_path / "programs.json"
        path.write_bytes(scrape)
        assert telemetry.main(["programs", str(path), "--top", "1"]) == 0
        out = capsys.readouterr().out
        assert "bundle[nanmean]" in out

    def test_drift_exit_codes(self, tmp_path, capsys):
        with _plane():
            _run_reduce()
            assert telemetry.main(["programs", "--drift"]) == 0
            assert "clean" in capsys.readouterr().out
            with faults.dispatch_delay_inject("bundle[nanmean]", 0.5, times=1):
                _run_reduce()
            assert telemetry.main(["programs", "--drift"]) == 2
            assert "DRIFT" in capsys.readouterr().out

    def test_garbage_file_errors(self, tmp_path, capsys):
        bad = tmp_path / "bad.json"
        bad.write_text("[1, 2, 3]")
        with pytest.raises(SystemExit):
            telemetry.main(["programs", str(bad)])
        capsys.readouterr()


class TestBytesLimit:
    class _Dev:
        def __init__(self, stats):
            self._stats = stats

        def memory_stats(self):
            return self._stats

    def test_summed_like_the_other_fields(self, monkeypatch):
        devs = [
            self._Dev({"bytes_in_use": 10, "peak_bytes_in_use": 20,
                       "bytes_limit": 100}),
            self._Dev({"bytes_in_use": 5, "peak_bytes_in_use": 6,
                       "bytes_limit": 200}),
        ]
        stats = device_mod.memory_stats(devices=devs)
        assert stats["bytes_in_use"] == 15
        assert stats["bytes_limit"] == 300

    def test_none_safe_when_no_device_reports_a_limit(self):
        devs = [self._Dev({"bytes_in_use": 10})]
        stats = device_mod.memory_stats(devices=devs)
        assert stats["bytes_limit"] is None

    def test_gauge_seeded_at_metrics_server_start(self, monkeypatch):
        from flox_tpu import device

        monkeypatch.setattr(
            device, "memory_stats",
            lambda devices=None: {
                "bytes_in_use": 1, "peak_bytes_in_use": 2,
                "devices": 1, "bytes_limit": 16 * 2**30,
            },
        )
        with flox_tpu.set_options(telemetry=True):
            exposition.start_metrics_server(port=0)
            assert telemetry.METRICS.get("hbm.bytes_limit") == 16 * 2**30
            text = exposition.prometheus_text()
        assert "flox_tpu_hbm_bytes_limit" in text


class TestCaptureStamping:
    def test_capture_dir_stamped_with_window_programs(self, tmp_path):
        from flox_tpu import profiling

        with _plane(profile_dir=str(tmp_path)):
            _run_reduce()  # pre-window dispatch: must NOT be stamped
            capture_dir = profiling.start_capture(seconds=0.3)
            _run_reduce()  # in-window dispatch: must be stamped
            deadline = __import__("time").time() + 10
            stamp = os.path.join(capture_dir, "programs.json")
            while __import__("time").time() < deadline and not os.path.exists(stamp):
                __import__("time").sleep(0.05)
            assert os.path.exists(stamp), "capture never stamped"
            payload = json.loads(open(stamp).read())
            progs = payload["programs"]
            assert "bundle[nanmean]" in progs
            assert progs["bundle[nanmean]"]["dispatches"] == 1
            assert progs["bundle[nanmean]"]["digest"]


class TestAutotunePrior:
    @pytest.fixture(autouse=True)
    def _fresh_store(self):
        # the autotune store survives telemetry.reset(); these tests
        # reason about an EMPTY store, so drop it on both sides
        cache.clear_all()
        yield
        cache.clear_all()

    def test_prior_consulted_when_no_measured_band(self):
        from flox_tpu import autotune

        with _plane(autotune=True):
            choice = autotune.decide(
                "fused", "fused", ("fused", "sequential"),
                dtype="float32", ngroups=8, nelems=4096,
            )
            assert choice == "fused"
            assert telemetry.METRICS.get("costmodel.prior_consults") >= 1
            assert telemetry.METRICS.get("costmodel.prior_decisions") >= 1

    def test_measured_band_outranks_the_prior(self):
        from flox_tpu import autotune

        with _plane(autotune=True):
            autotune.record(
                "fused", "sequential", 99.0,
                dtype="float32", ngroups=8, nelems=4096,
            )
            autotune.record(
                "fused", "fused", 1.0,
                dtype="float32", ngroups=8, nelems=4096,
            )
            consults0 = telemetry.METRICS.get("costmodel.prior_consults")
            choice = autotune.decide(
                "fused", "fused", ("fused", "sequential"),
                dtype="float32", ngroups=8, nelems=4096,
            )
            assert choice == "sequential"  # the measurement, not the model
            assert telemetry.METRICS.get("costmodel.prior_consults") == consults0

    def test_plane_off_keeps_the_fallback(self):
        from flox_tpu import autotune

        with flox_tpu.set_options(telemetry=True, autotune=True, costmodel=False):
            choice = autotune.decide(
                "fused", "fused", ("fused", "sequential"),
                dtype="float32", ngroups=8, nelems=4096,
            )
            assert choice == "fused"
            assert telemetry.METRICS.get("costmodel.prior_consults") == 0
