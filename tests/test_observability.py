"""Observability-plane test suite (ISSUE 8).

The contract under test: a live process exposes Prometheus-parseable
``/metrics`` (counters + cumulative histogram buckets + hbm gauges) plus
``/healthz``/``/readyz``; a request's trace id appears on every child span
in both export formats (mesh and streaming paths included, worker threads
included); ``device.memory_stats()`` sampling feeds the hbm gauges and the
per-program attribution in ``cache.stats()``; fatal faults and signals
produce an atomic flight-recorder dump that ``python -m flox_tpu.telemetry
report`` summarizes; and none of it changes results — the disabled path
stays a no-op.
"""

from __future__ import annotations

import json
import os
import signal
import urllib.error
import urllib.request

import numpy as np
import pytest

import flox_tpu
from flox_tpu import cache, exposition, telemetry
from flox_tpu.core import groupby_reduce
from flox_tpu.parallel import make_mesh
from flox_tpu.streaming import streaming_groupby_reduce

RNG = np.random.default_rng(7)


@pytest.fixture(autouse=True)
def _clean_plane():
    """Each test starts with telemetry OFF, empty buffers/registries, no
    flight path, and no readiness — even under the CI instrumented leg."""
    with flox_tpu.set_options(
        telemetry=False, telemetry_export_path=None, flight_recorder_path=None
    ):
        telemetry.reset()
        exposition.set_ready(False)
        yield
        telemetry.reset()
    exposition.stop_metrics_server()
    exposition.set_ready(False)


def _run_reduce(**kw):
    vals = np.random.default_rng(0).normal(size=(3, 48)).astype(np.float64)
    codes = np.arange(48) % 5
    return groupby_reduce(vals, codes, func="nanmean", engine="jax", **kw)


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


def _parse_prometheus(text: str) -> tuple[dict, dict]:
    """Minimal text-format parser: ``{metric-with-labels: value}`` samples
    plus ``{metric: type}`` from the # TYPE lines. Raises on anything that
    is not a comment, a blank, or a ``name{labels} value`` sample — the
    golden-format guarantee the scrape contract rests on."""
    samples: dict[str, float] = {}
    types: dict[str, str] = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        name_part, _, value_part = line.rpartition(" ")
        assert name_part, f"unparseable sample line: {line!r}"
        value = float(value_part)  # raises for malformed values
        if "{" in name_part:
            assert name_part.endswith("}"), f"unclosed label set: {line!r}"
        samples[name_part] = value
    return samples, types


class TestPrometheusExposition:
    def test_golden_format(self):
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
            telemetry.METRICS.set_gauge("hbm.bytes_in_use", 12345.0)
        samples, types = _parse_prometheus(exposition.prometheus_text())

        # counters carry the _total suffix and the counter TYPE
        assert types["flox_tpu_cache_bundle_calls_total"] == "counter"
        assert samples["flox_tpu_cache_bundle_calls_total"] >= 1
        # gauges are plain
        assert types["flox_tpu_hbm_bytes_in_use"] == "gauge"
        assert samples["flox_tpu_hbm_bytes_in_use"] == 12345.0
        # histograms: cumulative buckets over the shared edges + sum/count
        assert types["flox_tpu_span_ms_groupby_reduce"] == "histogram"
        buckets = [
            v for k, v in samples.items()
            if k.startswith('flox_tpu_span_ms_groupby_reduce_bucket{le="')
        ]
        assert len(buckets) == len(telemetry.HIST_EDGES_MS) + 1  # edges + +Inf
        assert buckets == sorted(buckets), "buckets must be cumulative"
        assert samples['flox_tpu_span_ms_groupby_reduce_bucket{le="+Inf"}'] == (
            samples["flox_tpu_span_ms_groupby_reduce_count"]
        )
        assert samples["flox_tpu_span_ms_groupby_reduce_sum"] > 0

    def test_name_sanitization(self):
        with flox_tpu.set_options(telemetry=True):
            telemetry.METRICS.inc("serve.weird-name.v2")
        samples, _ = _parse_prometheus(exposition.prometheus_text())
        assert "flox_tpu_serve_weird_name_v2_total" in samples


class TestMetricsServer:
    def _get(self, port, path):
        return urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5)

    def test_endpoints(self):
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
        port = exposition.start_metrics_server(port=0)
        assert port and port > 0
        # idempotent: a second start reuses the live endpoint
        assert exposition.start_metrics_server(port=0) == port

        assert self._get(port, "/healthz").status == 200
        with pytest.raises(urllib.error.HTTPError) as err:
            self._get(port, "/readyz")
        assert err.value.code == 503  # not ready until warmup is replayed
        exposition.set_ready(True)
        assert self._get(port, "/readyz").status == 200
        with pytest.raises(urllib.error.HTTPError) as err:
            self._get(port, "/nope")
        assert err.value.code == 404

        resp = self._get(port, "/metrics")
        assert resp.status == 200
        assert "text/plain" in resp.headers["Content-Type"]
        samples, _ = _parse_prometheus(resp.read().decode())
        assert samples["flox_tpu_cache_bundle_calls_total"] >= 1

    def test_disabled_by_default_option(self):
        # OPTIONS["metrics_port"]=0 means no endpoint: the option-driven
        # start is a no-op returning None
        assert exposition.start_metrics_server() is None


# ---------------------------------------------------------------------------
# request tracing
# ---------------------------------------------------------------------------


class TestRequestTracing:
    def test_trace_id_on_every_child_span_mesh_and_streaming(self):
        mesh = make_mesh()
        n = 512
        labels = RNG.integers(0, 5, n)
        vals = RNG.normal(size=n)
        with flox_tpu.set_options(telemetry=True):
            with telemetry.trace("req-mesh-1"):
                groupby_reduce(vals, labels, func="sum", method="map-reduce", mesh=mesh)
            with telemetry.trace("req-stream-1"):
                streaming_groupby_reduce(vals, labels, func="sum", batch_len=128)
            records = telemetry.drain()

        by_trace: dict = {}
        for rec in records:
            by_trace.setdefault(rec.get("trace"), []).append(rec)
        # no record of either request escaped its trace context
        assert set(by_trace) <= {"req-mesh-1", "req-stream-1"}
        mesh_names = {r["name"] for r in by_trace["req-mesh-1"]}
        assert {"groupby_reduce", "factorize", "combine", "finalize"} <= mesh_names
        assert any(n.startswith(("program-build", "flox:mesh-dispatch")) for n in mesh_names)
        stream_names = {r["name"] for r in by_trace["req-stream-1"]}
        assert {"streaming_groupby_reduce", "factorize", "finalize"} <= stream_names
        assert any(n.startswith("stream[") for n in stream_names)

    def test_trace_id_in_both_export_formats(self, tmp_path):
        with flox_tpu.set_options(telemetry=True):
            with telemetry.trace("req-fmt"):
                _run_reduce()
            records = telemetry.spans()
            jsonl = tmp_path / "t.jsonl"
            chrome = tmp_path / "t.json"
            telemetry.export_jsonl(str(jsonl), records)
            telemetry.export_chrome_trace(str(chrome), records)
        parsed = [json.loads(line) for line in jsonl.read_text().splitlines()]
        spans = [r for r in parsed if r.get("type") == "span"]
        assert spans and all(r["trace"] == "req-fmt" for r in spans)
        payload = json.loads(chrome.read_text())
        events = payload["traceEvents"]
        assert events and all(ev["args"].get("trace_id") == "req-fmt" for ev in events)

    def test_trace_reaches_prefetch_worker_records(self):
        # retry events fire on the prefetch workers; the stager re-binds the
        # stream's trace there, so they still carry the request's id
        from flox_tpu import faults

        n, batch = 512, 128
        labels = RNG.integers(0, 4, n)
        vals = RNG.normal(size=n)
        loader = faults.FlakyLoader(lambda s, e: vals[s:e], {batch: OSError}, times=1)
        with flox_tpu.set_options(telemetry=True, stream_retries=2, stream_backoff=0.0):
            with telemetry.trace("req-worker"):
                streaming_groupby_reduce(
                    loader, labels, func="sum", batch_len=batch
                )
            records = telemetry.drain()
        retries = [r for r in records if r["name"] == "retry"]
        assert retries, "the flaky loader must have produced a retry event"
        assert all(r.get("trace") == "req-worker" for r in retries)

    def test_tail_sampling_keeps_only_slow_traces(self):
        with flox_tpu.set_options(telemetry=True, telemetry_level="basic"):
            # seed the running distribution: a fleet of ~100ms requests, so
            # the p99 the verdict reads is ~100ms
            for _ in range(30):
                telemetry.METRICS.observe("trace_ms", 100.0)

            # a FAST trace (well under the p99): detail records dropped
            with telemetry.trace("fast-req"):
                t0 = 1.0
                telemetry.record_span("stage", t0, t0 + 0.001, detail=True)
            fast_records = telemetry.drain()
            assert not any(r["name"] == "stage" for r in fast_records)
            assert telemetry.METRICS.get("telemetry.tail_dropped") >= 1

            # a SLOW trace (blows the running p99): detail records survive,
            # tagged with the trace id
            import time as _time

            with telemetry.trace("slow-req"):
                telemetry.record_span("stage", 1.0, 1.5, detail=True)
                _time.sleep(0.25)
            slow_records = telemetry.drain()
            kept = [r for r in slow_records if r["name"] == "stage"]
            assert kept and kept[0]["trace"] == "slow-req"
            assert telemetry.METRICS.get("telemetry.tail_kept") >= 1

    def test_detailed_level_bypasses_parking(self):
        with flox_tpu.set_options(telemetry=True, telemetry_level="detailed"):
            with telemetry.trace("det-req"):
                telemetry.record_span("stage", 1.0, 1.001, detail=True)
            records = telemetry.drain()
        assert any(r["name"] == "stage" for r in records)

    def test_serve_request_id_becomes_trace(self):
        import asyncio

        from flox_tpu.serve import AggregationRequest, Dispatcher

        async def go():
            dispatcher = Dispatcher()
            req = AggregationRequest(
                func="sum",
                array=np.array([1.0, 2.0, 4.0, 8.0]),
                by=np.array([0, 0, 1, 1]),
                request_id="req-serve-7",
            )
            result = await dispatcher.submit(req)
            await dispatcher.close()
            return result

        with flox_tpu.set_options(telemetry=True):
            result = asyncio.run(go())
            records = telemetry.drain()
        np.testing.assert_allclose(np.asarray(result.result), [3.0, 12.0])
        execute = [r for r in records if r["name"] == "serve.execute"]
        core = [r for r in records if r["name"] == "groupby_reduce"]
        request = [r for r in records if r["name"] == "serve.request"]
        assert execute and execute[0].get("trace") == "req-serve-7"
        assert core and core[0].get("trace") == "req-serve-7"
        assert request and request[0].get("trace") == "req-serve-7"


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------


class TestHbmAccounting:
    def test_memory_stats_shape(self):
        from flox_tpu import device

        stats = device.memory_stats()
        # CPU backends may report nothing; when they do report, the
        # aggregate keys are fixed
        if stats is not None:
            assert {"bytes_in_use", "peak_bytes_in_use", "devices"} <= set(stats)

    def test_fake_memory_stats_feed_gauges_and_attribution(self, monkeypatch):
        from flox_tpu import device

        feed = iter([
            {"bytes_in_use": 1000, "peak_bytes_in_use": 1500},
            {"bytes_in_use": 800, "peak_bytes_in_use": 1500},
            {"bytes_in_use": 2000, "peak_bytes_in_use": 2500},
        ])
        last = {"bytes_in_use": 500, "peak_bytes_in_use": 2500}
        monkeypatch.setattr(
            device, "memory_stats", lambda devices=None: next(feed, last)
        )
        with flox_tpu.set_options(telemetry=True):
            telemetry.sample_hbm(program="prog-a")
            telemetry.sample_hbm(program="prog-a")
            telemetry.sample_hbm(program="prog-b")
            telemetry.sample_hbm()
        # gauge = latest, peak gauge = running max
        assert telemetry.METRICS.get("hbm.bytes_in_use") == 500
        assert telemetry.METRICS.get("hbm.peak_bytes_in_use") == 2500
        # per-program attribution keeps each program's own max
        attribution = cache.stats()["hbm_by_program"]
        assert attribution == {"prog-a": 1000.0, "prog-b": 2000.0}
        cache.clear_all()
        assert cache.stats()["hbm_by_program"] == {}

    def test_dispatch_paths_attribute_programs(self, monkeypatch):
        from flox_tpu import device

        monkeypatch.setattr(
            device,
            "memory_stats",
            lambda devices=None: {"bytes_in_use": 4096, "peak_bytes_in_use": 8192},
        )
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
        attribution = cache.stats()["hbm_by_program"]
        assert any(key.startswith("bundle[") for key in attribution), attribution

    def test_disabled_sampling_is_untouched(self, monkeypatch):
        from flox_tpu import device

        def boom(devices=None):  # pragma: no cover - must never run
            raise AssertionError("memory_stats consulted while disabled")

        monkeypatch.setattr(device, "memory_stats", boom)
        telemetry.sample_hbm(program="nope")
        assert telemetry.METRICS.snapshot() == {}
        assert cache.stats()["hbm_by_program"] == {}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        with flox_tpu.set_options(telemetry=True, flight_recorder_size=16):
            for i in range(64):
                telemetry.event("tick", i=i)
            records = telemetry.FLIGHT_RECORDER.records()
        assert len(records) == 16
        assert records[-1]["attrs"]["i"] == 63  # newest kept, oldest dropped

    def test_dump_on_fatal_fault_roundtrips_through_report(self, tmp_path, capsys):
        from flox_tpu.resilience import RetryPolicy, call_with_retry

        dump = tmp_path / "flight.jsonl"
        with flox_tpu.set_options(telemetry=True, flight_recorder_path=str(dump)):
            _run_reduce()  # populate the ring with real spans

            def fatal():
                raise ValueError("programming error")

            with pytest.raises(ValueError, match="programming error"):
                call_with_retry(fatal, policy=RetryPolicy(retries=3, backoff=0.0))
        assert dump.exists(), "fatal classification must dump the flight recorder"
        parsed = [json.loads(line) for line in dump.read_text().splitlines()]
        header = parsed[0]
        assert header["name"] == "flight-recorder"
        assert header["attrs"]["reason"].startswith("fatal:ValueError")
        names = {r.get("name") for r in parsed}
        assert "groupby_reduce" in names, "ring must hold the pre-fault spans"
        assert "fatal" in names, "the fatal event itself must be recorded"
        assert parsed[-1]["type"] == "counters"
        # the dump is a valid telemetry export: report exits 0 and
        # summarizes it
        assert telemetry.main(["report", str(dump)]) == 0
        out = capsys.readouterr().out
        assert "groupby_reduce" in out

    def test_transient_fault_does_not_dump(self, tmp_path):
        from flox_tpu.resilience import RetryPolicy, call_with_retry

        dump = tmp_path / "flight.jsonl"
        with flox_tpu.set_options(telemetry=True, flight_recorder_path=str(dump)):
            calls = {"n": 0}

            def flaky():
                calls["n"] += 1
                if calls["n"] < 2:
                    raise OSError("transient hiccup")
                return "ok"

            assert call_with_retry(flaky, policy=RetryPolicy(retries=3, backoff=0.0)) == "ok"
        assert not dump.exists()

    def test_dump_on_signal(self, tmp_path):
        if not hasattr(signal, "SIGUSR2"):
            pytest.skip("no SIGUSR2 on this platform")
        dump = tmp_path / "flight-signal.jsonl"
        # install_signal_dumps registers BOTH signals: restore both, or the
        # SIGTERM dump handler leaks into every later test in this process
        previous = {
            sig: signal.getsignal(sig) for sig in (signal.SIGTERM, signal.SIGUSR2)
        }
        try:
            with flox_tpu.set_options(telemetry=True, flight_recorder_path=str(dump)):
                telemetry.event("before-signal")
                telemetry.install_signal_dumps()
                os.kill(os.getpid(), signal.SIGUSR2)
            assert dump.exists()
            parsed = [json.loads(line) for line in dump.read_text().splitlines()]
            assert parsed[0]["attrs"]["reason"] == "signal:SIGUSR2"
            assert any(r.get("name") == "before-signal" for r in parsed)
        finally:
            for sig, handler in previous.items():
                signal.signal(sig, handler)

    def test_unconfigured_dump_is_noop(self):
        with flox_tpu.set_options(telemetry=True):
            telemetry.event("something")
            assert telemetry.flight_dump(reason="no path") is None


# ---------------------------------------------------------------------------
# bit-identity + disabled-path contracts
# ---------------------------------------------------------------------------


class TestPlaneNeutrality:
    def test_bit_identity_with_plane_enabled(self, tmp_path, monkeypatch):
        from flox_tpu import device

        expected, groups = _run_reduce()
        monkeypatch.setattr(
            device,
            "memory_stats",
            lambda devices=None: {"bytes_in_use": 1, "peak_bytes_in_use": 2},
        )
        with flox_tpu.set_options(
            telemetry=True,
            flight_recorder_path=str(tmp_path / "f.jsonl"),
        ):
            port = exposition.start_metrics_server(port=0)
            with telemetry.trace("bit-req"):
                got, g2 = _run_reduce()
            assert (
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ).status
                == 200
            )
        np.testing.assert_array_equal(np.asarray(expected), np.asarray(got))
        np.testing.assert_array_equal(np.asarray(groups), np.asarray(g2))

    def test_disabled_path_allocates_nothing(self):
        # trace() and span() hand back the one shared no-op; the registry,
        # the buffer, and the flight ring stay untouched
        assert telemetry.trace("req-x") is telemetry.span("anything")
        with telemetry.trace("req-x"):
            _run_reduce()
        assert telemetry.current_trace() is None
        assert telemetry.spans() == []
        assert telemetry.METRICS.snapshot() == {}
        assert len(telemetry.FLIGHT_RECORDER) == 0


class TestNewOptions:
    @pytest.mark.parametrize(
        "bad",
        [
            {"metrics_port": -1},
            {"metrics_port": 70000},
            {"metrics_port": 1.5},
            {"flight_recorder_path": ""},
            {"flight_recorder_size": 0},
            {"flight_recorder_size": True},
        ],
    )
    def test_validated_at_set_time(self, bad):
        with pytest.raises(ValueError):
            flox_tpu.set_options(**bad)

    def test_env_mirrors_exist(self):
        # the FLX010 contract, asserted at runtime too: every new knob has
        # an env mirror spelled FLOX_TPU_<NAME>
        import inspect

        from flox_tpu import options as opts

        src = inspect.getsource(opts)
        for name in ("metrics_port", "flight_recorder_path", "flight_recorder_size"):
            assert f"FLOX_TPU_{name.upper()}" in src
