"""End-to-end groupby_reduce tests against per-group numpy oracles.

Modeled on the reference's giant parametrized sweep
(tests/test_core.py:222-388): {func × engine × 1d/2d × NaN-in-data ×
NaN-in-by × expected/None × finalize_kwargs} compared against plain numpy
applied to each group's masked slice.
"""

import numpy as np
import pandas as pd
import pytest
import scipy.stats

from flox_tpu.core import groupby_reduce

RNG = np.random.default_rng(123)

ALL_FUNCS = [
    "sum", "nansum", "prod", "nanprod", "mean", "nanmean", "var", "nanvar",
    "std", "nanstd", "max", "nanmax", "min", "nanmin", "argmax", "nanargmax",
    "argmin", "nanargmin", "any", "all", "count",
    "first", "last", "nanfirst", "nanlast",
    "median", "nanmedian", "quantile", "nanquantile", "mode", "nanmode",
]


def _np_oracle(func):
    """func name -> plain numpy callable over axis=-1 (independent oracle)."""
    if func == "count":
        return lambda g, **kw: np.sum(~np.isnan(g), axis=-1)
    if func in ("first", "nanfirst"):
        def first_(g, **kw):
            if func == "first":
                return g[..., 0]
            out = np.full(g.shape[:-1], np.nan)
            for idx in np.ndindex(g.shape[:-1]):
                valid = g[idx][~np.isnan(g[idx])]
                if valid.size:
                    out[idx] = valid[0]
            return out
        return first_
    if func in ("last", "nanlast"):
        def last_(g, **kw):
            if func == "last":
                return g[..., -1]
            out = np.full(g.shape[:-1], np.nan)
            for idx in np.ndindex(g.shape[:-1]):
                valid = g[idx][~np.isnan(g[idx])]
                if valid.size:
                    out[idx] = valid[-1]
            return out
        return last_
    if func in ("mode", "nanmode"):
        def mode_(g, **kw):
            nan_policy = "omit" if func == "nanmode" else "propagate"
            res = scipy.stats.mode(g, axis=-1, nan_policy=nan_policy, keepdims=False)
            return res.mode
        return mode_
    if func in ("quantile", "nanquantile"):
        base = np.nanquantile if func == "nanquantile" else np.quantile
        return lambda g, q=0.5, **kw: base(g, q, axis=-1)
    np_func = getattr(np, func)
    return lambda g, **kw: np_func(g, axis=-1, **kw)


def compare(result, expected, func):
    result = np.asarray(result)
    rtol, atol = 1e-12, 1e-12
    np.testing.assert_allclose(
        result.astype(np.float64),
        np.asarray(expected).astype(np.float64),
        rtol=rtol,
        atol=atol,
        equal_nan=True,
    )


def reference_loop(func, values, codes, size, **kw):
    """Apply the oracle per group; NaN where undefined."""
    oracle = _np_oracle(func)
    q = kw.get("q")
    lead = values.shape[:-1]
    extra = (len(q),) if q is not None and np.ndim(q) > 0 else ()
    out = np.full(extra + lead + (size,), np.nan)
    for g in range(size):
        sel = np.flatnonzero(codes == g)
        if sel.size == 0:
            if func in ("sum", "nansum"):
                out[..., g] = 0
            elif func in ("prod", "nanprod"):
                out[..., g] = 1
            elif func == "count":
                out[..., g] = 0
            elif func == "all":
                out[..., g] = 1
            elif func == "any":
                out[..., g] = 0
            elif "arg" in func:
                out[..., g] = -1
            continue
        grp = values[..., sel]
        with np.errstate(invalid="ignore", divide="ignore"), np.testing.suppress_warnings() as sup:
            sup.filter(RuntimeWarning)
            if "arg" in func:
                if func.startswith("nanarg"):
                    allnan = np.all(np.isnan(grp), axis=-1)
                    safe = np.where(
                        np.isnan(grp), -np.inf if "max" in func else np.inf, grp
                    )
                    local = np.argmax(safe, -1) if "max" in func else np.argmin(safe, -1)
                    res = np.where(allnan, -1, sel[local])
                else:
                    local = np.argmax(grp, -1) if "max" in func else np.argmin(grp, -1)
                    res = sel[local]
            elif func.startswith("nan") and func not in ("nanfirst", "nanlast", "nanmode", "nanquantile", "nanmedian"):
                allnan = np.all(np.isnan(grp), axis=-1)
                res = _np_oracle(func)(grp, **kw)
                if func in ("nanmean", "nanvar", "nanstd", "nanmedian"):
                    res = np.where(allnan, np.nan, res)
            else:
                res = _np_oracle(func)(grp, **kw)
            if func in ("nanquantile",) and np.ndim(kw.get("q", 0.5)) > 0:
                out[..., g] = res
                continue
        out[..., g] = res
    return out


@pytest.mark.parametrize("shape", ["1d", "2d"])
@pytest.mark.parametrize("add_nan", [False, True])
@pytest.mark.parametrize("func", ALL_FUNCS)
def test_groupby_reduce_all(engine, func, shape, add_nan):
    n, size = 60, 4
    codes = RNG.integers(0, size, n)
    labels = codes.astype(np.int64)
    values = np.round(RNG.normal(size=(3, n) if shape == "2d" else (n,)), 1)
    if add_nan:
        values[..., RNG.random(n) < 0.25] = np.nan
    # no skips: the argmax/argmin NaN semantics and partial-NaN mode are
    # pinned to numpy / scipy>=1.11 behavior (VERDICT r3 #10)

    fkw = {}
    if func in ("var", "nanvar", "std", "nanstd"):
        fkw = {"ddof": 1}
    if func in ("quantile", "nanquantile"):
        fkw = {"q": 0.7}

    result, groups = groupby_reduce(values, labels, func=func, engine=engine, finalize_kwargs=fkw)
    np.testing.assert_array_equal(groups, np.arange(size))

    expected = reference_loop(func, values, codes, size, **fkw)
    # ddof guard: groups with n<=ddof give NaN in both
    compare(result, expected, func)


@pytest.mark.parametrize("nby", [2, 3])
@pytest.mark.parametrize("nan_by", [False, True])
@pytest.mark.parametrize("func", ALL_FUNCS)
def test_groupby_reduce_all_multiby(engine, func, nby, nan_by):
    """Product-grid correctness for every func at nby 2-3, with and without
    NaN labels, against the per-group oracle (reference
    tests/test_core.py:222-388 sweeps nby 1-3; the nby=1 leg is
    test_groupby_reduce_all)."""
    import zlib

    rng = np.random.default_rng(zlib.crc32(f"{func}-{nby}-{nan_by}".encode()))
    n = 60
    values = np.round(rng.normal(size=n), 1)
    sizes = (3, 2, 2)[:nby]
    bys = [rng.integers(0, s, n).astype(np.float64) for s in sizes]
    if nan_by:
        for b in bys:
            b[rng.random(n) < 0.15] = np.nan

    fkw = {}
    if func in ("var", "nanvar", "std", "nanstd"):
        fkw = {"ddof": 1}
    if func in ("quantile", "nanquantile"):
        fkw = {"q": 0.7}

    result, *groups = groupby_reduce(
        values, *bys, func=func, engine=engine, finalize_kwargs=fkw
    )

    # oracle: row-major ravel of per-by codes over the discovered-group grid
    exp_groups = [np.unique(b[~np.isnan(b)]) for b in bys]
    for g, e in zip(groups, exp_groups):
        np.testing.assert_array_equal(np.asarray(g, dtype=np.float64), e)
    grid = tuple(len(e) for e in exp_groups)
    flat_codes = np.zeros(n, dtype=np.int64)
    invalid = np.zeros(n, dtype=bool)
    for b, e in zip(bys, exp_groups):
        nanmask = np.isnan(b)
        c = np.searchsorted(e, np.where(nanmask, e[0], b))
        flat_codes = flat_codes * len(e) + c
        invalid |= nanmask
    flat_codes[invalid] = -1

    expected = reference_loop(func, values, flat_codes, int(np.prod(grid)), **fkw)
    assert np.asarray(result).shape == grid
    compare(np.asarray(result).reshape(-1), expected, func)


@pytest.mark.parametrize("func", ["sum", "nanmean", "max", "count"])
def test_expected_groups_reindex(engine, func):
    labels = np.array([1, 1, 3, 3, 5])
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0])
    result, groups = groupby_reduce(
        vals, labels, func=func, engine=engine, expected_groups=np.array([1, 2, 3, 4, 5])
    )
    np.testing.assert_array_equal(groups, [1, 2, 3, 4, 5])
    res = np.asarray(result).astype(float)
    if func == "sum":
        np.testing.assert_allclose(res, [3, 0, 7, 0, 5])
    elif func == "count":
        np.testing.assert_allclose(res, [2, 0, 2, 0, 1])
    elif func == "nanmean":
        np.testing.assert_allclose(res, [1.5, np.nan, 3.5, np.nan, 5.0], equal_nan=True)
    elif func == "max":
        np.testing.assert_allclose(res, [2, np.nan, 4, np.nan, 5], equal_nan=True)


def test_nan_labels_dropped(engine):
    labels = np.array([0.0, np.nan, 0.0, 1.0])
    vals = np.array([1.0, 100.0, 2.0, 3.0])
    result, groups = groupby_reduce(vals, labels, func="sum", engine=engine)
    np.testing.assert_allclose(np.asarray(result).astype(float), [3.0, 3.0])
    np.testing.assert_array_equal(groups, [0.0, 1.0])


def test_binning(engine):
    vals = np.array([0.5, 1.5, 2.5, 3.5, 4.5])
    result, bins = groupby_reduce(
        vals, vals, func="count", engine=engine,
        expected_groups=np.array([0.0, 2.0, 4.0, 6.0]), isbin=True,
    )
    assert isinstance(bins, pd.IntervalIndex)
    np.testing.assert_array_equal(np.asarray(result), [2, 2, 1])


def test_multi_by_product_grid(engine):
    by1 = np.array([0, 0, 1, 1, 0, 1])
    by2 = np.array(["a", "b", "a", "b", "a", "a"])
    vals = np.arange(6.0)
    result, g1, g2 = groupby_reduce(vals, by1, by2, func="sum", engine=engine)
    np.testing.assert_array_equal(g1, [0, 1])
    np.testing.assert_array_equal(g2, ["a", "b"])
    # grid: (0,a)=0+4, (0,b)=1, (1,a)=2+5, (1,b)=3
    np.testing.assert_allclose(np.asarray(result).astype(float), [[4, 1], [7, 3]])


def test_partial_axis_reduction(engine):
    # labels 2d, reduce only the last axis -> per-row group spaces
    labels = np.array([[0, 1, 0], [1, 1, 0]])
    vals = np.arange(6.0).reshape(2, 3)
    result, groups = groupby_reduce(vals, labels, func="sum", engine=engine, axis=-1)
    np.testing.assert_allclose(np.asarray(result).astype(float), [[2, 1], [5, 7]])


def test_axis_beyond_by(engine):
    # reduce over an axis the labels don't span: labels broadcast
    labels = np.array([0, 1, 0])
    vals = np.arange(6.0).reshape(2, 3)
    result, groups = groupby_reduce(vals, labels, func="sum", engine=engine, axis=(0, 1))
    np.testing.assert_allclose(np.asarray(result).astype(float), [0 + 2 + 3 + 5, 1 + 4])


def test_min_count(engine):
    labels = np.array([0, 0, 1])
    vals = np.array([1.0, np.nan, np.nan])
    result, _ = groupby_reduce(vals, labels, func="nansum", engine=engine, min_count=1)
    np.testing.assert_allclose(np.asarray(result).astype(float), [1.0, np.nan], equal_nan=True)
    result, _ = groupby_reduce(vals, labels, func="nansum", engine=engine, min_count=2)
    np.testing.assert_allclose(np.asarray(result).astype(float), [np.nan, np.nan], equal_nan=True)


def test_sort_false(engine):
    labels = np.array([3, 1, 3, 2])
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    _, groups_sorted = groupby_reduce(vals, labels, func="sum", engine=engine, sort=True)
    np.testing.assert_array_equal(groups_sorted, [1, 2, 3])
    _, groups_unsorted = groupby_reduce(vals, labels, func="sum", engine=engine, sort=False)
    np.testing.assert_array_equal(groups_unsorted, [3, 1, 2])


def test_datetime_minmax(engine):
    dt = np.array(["2020-01-03", "2020-01-01", "2020-01-02", "NaT"], dtype="datetime64[ns]")
    labels = np.array([0, 0, 1, 1])
    result, _ = groupby_reduce(dt, labels, func="min", engine=engine)
    assert result.dtype == dt.dtype
    np.testing.assert_array_equal(
        result, np.array(["2020-01-01", "NaT"], dtype="datetime64[ns]")
    )
    result, _ = groupby_reduce(dt, labels, func="nanmin", engine=engine)
    np.testing.assert_array_equal(
        result, np.array(["2020-01-01", "2020-01-02"], dtype="datetime64[ns]")
    )


def test_datetime_mean_casts_back(engine):
    # non-dtype-preserving reductions of datetimes return the datetime dtype,
    # NaN -> NaT (parity: reference core.py:1205-1211); var-like results keep
    # numeric units (ns²) and counts/indices stay integral
    dt = np.array(
        ["2021-01-01T00", "2021-01-01T12", "2021-01-02T00", "NaT"],
        dtype="datetime64[ns]",
    )
    labels = np.array([0, 0, 1, 1])
    result, _ = groupby_reduce(dt, labels, func="nanmean", engine=engine)
    assert result.dtype == dt.dtype
    np.testing.assert_array_equal(
        result, np.array(["2021-01-01T06", "2021-01-02T00"], dtype="datetime64[ns]")
    )
    # non-skipna mean propagates NaT
    result, _ = groupby_reduce(dt, labels, func="mean", engine=engine)
    assert not np.isnat(result[0]) and np.isnat(result[1])
    # all-NaT group -> NaT
    result, _ = groupby_reduce(
        np.array(["2021-01-01", "NaT", "NaT"], dtype="datetime64[ns]"),
        np.array([0, 1, 1]), func="nanmean", engine=engine,
    )
    assert np.isnat(result[1])
    result, _ = groupby_reduce(dt, labels, func="nanmedian", engine=engine)
    assert result.dtype == dt.dtype
    assert result[1] == np.datetime64("2021-01-02T00", "ns")
    result, _ = groupby_reduce(dt, labels, func="nanvar", engine=engine)
    assert result.dtype.kind == "f"
    result, _ = groupby_reduce(dt, labels, func="count", engine=engine)
    assert result.dtype.kind == "i" and list(result) == [2, 1]
    result, _ = groupby_reduce(dt, labels, func="nanargmax", engine=engine)
    assert result.dtype.kind == "i" and list(result) == [1, 2]
    # timedelta round-trips the same way
    td = dt - dt[0]
    result, _ = groupby_reduce(td, labels, func="nanmean", engine=engine)
    assert result.dtype == td.dtype
    assert result[0] == np.timedelta64(6 * 3600 * 10**9, "ns")


def test_datetime_mean_mesh():
    from flox_tpu.parallel import make_mesh

    dt = np.array(
        ["2021-01-01T00", "2021-01-01T12", "2021-01-02T00", "NaT"],
        dtype="datetime64[ns]",
    )
    labels = np.array([0, 0, 1, 1])
    result, _ = groupby_reduce(dt, labels, func="nanmean", method="map-reduce", mesh=make_mesh(4))
    assert result.dtype == dt.dtype
    np.testing.assert_array_equal(
        result, np.array(["2021-01-01T06", "2021-01-02T00"], dtype="datetime64[ns]")
    )


def test_bool_input(engine):
    labels = np.array([0, 0, 1, 1])
    vals = np.array([True, False, True, True])
    result, _ = groupby_reduce(vals, labels, func="sum", engine=engine)
    np.testing.assert_array_equal(np.asarray(result), [1, 2])
    result, _ = groupby_reduce(vals, labels, func="all", engine=engine)
    np.testing.assert_array_equal(np.asarray(result), [False, True])


def test_dtype_request(engine):
    labels = np.array([0, 1, 0])
    vals = np.array([1, 2, 3], dtype=np.int32)
    result, _ = groupby_reduce(vals, labels, func="sum", engine=engine, dtype=np.float32)
    assert np.asarray(result).dtype == np.float32


def test_fill_value_absent_groups(engine):
    labels = np.array([0, 0])
    vals = np.array([1.0, 2.0])
    result, _ = groupby_reduce(
        vals, labels, func="sum", engine=engine,
        expected_groups=np.array([0, 1]), fill_value=-999.0,
    )
    np.testing.assert_allclose(np.asarray(result).astype(float), [3.0, -999.0])


def test_jax_input_array(engine):
    import jax.numpy as jnp

    labels = np.array([0, 1, 0])
    vals = jnp.asarray([1.0, 2.0, 3.0])
    result, _ = groupby_reduce(vals, labels, func="sum", engine="jax")
    np.testing.assert_allclose(np.asarray(result), [4.0, 2.0])


def test_quantile_multi_q(engine):
    labels = np.array([0, 0, 0, 1, 1, 1])
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
    result, _ = groupby_reduce(
        vals, labels, func="quantile", engine=engine, finalize_kwargs={"q": [0.0, 0.5, 1.0]}
    )
    np.testing.assert_allclose(
        np.asarray(result), [[1.0, 4.0], [2.0, 5.0], [3.0, 6.0]]
    )


# --- regression tests for review findings -----------------------------------


def test_datetime_ns_precision(engine):
    # max of ns-resolution timestamps must be exact (no float roundtrip)
    dt = np.array(
        ["2000-01-01T00:00:00.123456789", "2000-01-01T00:00:00.123456456"],
        dtype="datetime64[ns]",
    )
    out, _ = groupby_reduce(dt, np.array([0, 0]), func="max", engine=engine)
    assert out[0] == dt[0]


def test_datetime_nat_leading_dims(engine):
    # NaT exclusion must be per-element, not collapsed across leading dims
    dt2 = np.array(
        [["NaT", "2000-01-02", "2000-01-03", "NaT"],
         ["2000-01-05", "2000-01-06", "2000-01-07", "2000-01-08"]],
        dtype="datetime64[ns]",
    )
    by = np.array([0, 0, 1, 1])
    out, _ = groupby_reduce(dt2, by, func="nanmin", engine=engine)
    expected = np.array(
        [["2000-01-02", "2000-01-03"], ["2000-01-05", "2000-01-07"]],
        dtype="datetime64[ns]",
    )
    np.testing.assert_array_equal(out, expected)
    # non-skipna: NaT propagates
    out, _ = groupby_reduce(dt2, by, func="min", engine=engine)
    assert np.isnat(out[0]).all() and not np.isnat(out[1]).any()


def test_min_count_int_input(engine):
    # min_count on integer input must produce NaN, not a silent 0
    r, _ = groupby_reduce(
        np.array([1, 2, 3, 4]), np.array([0, 0, 1, 2]),
        func="nansum", min_count=2, engine=engine,
    )
    np.testing.assert_allclose(np.asarray(r).astype(float), [3, np.nan, np.nan], equal_nan=True)


def test_jit_bundle_cache_stable():
    # NaN fills must not defeat the jit program cache
    from flox_tpu.core import _jitted_bundle

    _jitted_bundle.cache_clear()
    for _ in range(3):
        groupby_reduce(np.arange(6.0), np.array([0, 1, 0, 1, 0, 1]), func="mean", engine="jax")
    info = _jitted_bundle.cache_info()
    assert info.misses == 1 and info.hits == 2


def test_invalid_method():
    with pytest.raises(ValueError, match="method"):
        groupby_reduce(np.arange(4.0), np.array([0, 1, 0, 1]), func="sum", method="bogus")


# --- dtype preservation matrix (reference test_core.py:1135-1176) -----------


DTYPE_FUNCS_PRESERVING = ["max", "nanmax", "min", "nanmin", "first", "last", "nanfirst", "nanlast"]


@pytest.mark.parametrize("dtype", [np.int32, np.int64, np.float32, np.float64])
@pytest.mark.parametrize("func", DTYPE_FUNCS_PRESERVING)
def test_dtype_preserved(engine, func, dtype):
    labels = np.array([0, 1, 0, 1])
    vals = np.array([4, 1, 3, 2], dtype=dtype)
    result, _ = groupby_reduce(vals, labels, func=func, engine=engine)
    assert np.asarray(result).dtype == np.dtype(dtype), (func, dtype)


@pytest.mark.parametrize("dtype", [np.int32, np.float32, np.float64])
def test_dtype_sum_promotes_ints(engine, dtype):
    labels = np.array([0, 1, 0, 1])
    vals = np.array([4, 1, 3, 2], dtype=dtype)
    result, _ = groupby_reduce(vals, labels, func="sum", engine=engine)
    got = np.asarray(result).dtype
    if np.dtype(dtype).kind == "i":
        assert got.kind == "i" and got.itemsize >= 4
    else:
        assert got == np.dtype(dtype)


@pytest.mark.parametrize("func", ["mean", "nanmean", "var", "nanvar"])
def test_dtype_mean_of_ints_is_float(engine, func):
    labels = np.array([0, 1, 0, 1])
    vals = np.array([4, 1, 3, 2], dtype=np.int64)
    result, _ = groupby_reduce(vals, labels, func=func, engine=engine)
    assert np.asarray(result).dtype.kind == "f"


def test_dtype_count_is_int(engine):
    result, _ = groupby_reduce(
        np.array([1.0, 2.0]), np.array([0, 1]), func="count", engine=engine
    )
    assert np.asarray(result).dtype.kind == "i"


# --- fill_value behaviour across funcs (reference test_core.py:1109-1133) ---


FILL_FUNCS = ["sum", "nansum", "prod", "mean", "nanmean", "max", "nanmin", "var",
              "std", "count", "first", "nanlast", "median", "nanquantile"]


@pytest.mark.parametrize("func", FILL_FUNCS)
def test_fill_value_applied_to_absent_groups(engine, func):
    labels = np.array([0, 0, 2, 2])
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    fkw = {"q": 0.5} if "quantile" in func else {}
    result, _ = groupby_reduce(
        vals, labels, func=func, engine=engine,
        expected_groups=np.array([0, 1, 2]), fill_value=-123.0, finalize_kwargs=fkw,
    )
    res = np.asarray(result).astype(float)
    assert res[1] == -123.0, (func, res)
    assert res[0] != -123.0 and res[2] != -123.0


def test_explicit_nat_fill(engine):
    # an explicit NaT fill must not crash or round timestamps through float
    dt = np.array(["2000-01-01T00:00:00.123456789", "2000-01-02"], dtype="datetime64[ns]")
    labels = np.array([0, 0])
    result, _ = groupby_reduce(
        dt, labels, func="first", engine=engine,
        expected_groups=np.array([0, 1]), fill_value=np.datetime64("NaT"),
    )
    assert result.dtype == dt.dtype
    assert result[0] == dt[0] and np.isnat(result[1])


def test_min_count_complex(engine):
    # min_count masking must not destroy imaginary parts
    vals = np.array([1 + 2j, 3 - 1j, 9 + 9j])
    labels = np.array([0, 0, 1])
    result, _ = groupby_reduce(vals, labels, func="nansum", engine=engine, min_count=2)
    res = np.asarray(result)
    assert res.dtype.kind == "c"
    assert res[0] == 4 + 1j and np.isnan(res[1].real)


def test_custom_aggregation(engine):
    # users can define custom aggregations (public Aggregation export,
    # reference aggregations.py:161)
    from flox_tpu import Aggregation

    def sum_of_cubes(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
        import flox_tpu.engine_numpy as en

        arr = np.asarray(array)
        return en.generic_kernel("sum", group_idx, arr**3, size=size, fill_value=fill_value)

    agg = Aggregation("sum_of_cubes", numpy=(sum_of_cubes,), chunk=(sum_of_cubes,), combine=("sum",))
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    labels = np.array([0, 0, 1, 1])
    result, groups = groupby_reduce(vals, labels, func=agg, engine=engine)
    np.testing.assert_allclose(np.asarray(result).astype(float), [9.0, 91.0])


def test_three_groupers_product_grid(engine):
    # nby=3 (reference sweep covers nby 1-3, test_core.py:222-388)
    rng = np.random.default_rng(77)
    n = 60
    b1 = rng.integers(0, 2, n)
    b2 = rng.integers(0, 3, n)
    b3 = rng.integers(0, 2, n)
    vals = np.round(rng.normal(size=n), 1)
    result, g1, g2, g3 = groupby_reduce(vals, b1, b2, b3, func="sum", engine=engine)
    assert np.asarray(result).shape == (2, 3, 2)
    expected = np.zeros((2, 3, 2))
    for i in range(2):
        for j in range(3):
            for k in range(2):
                expected[i, j, k] = vals[(b1 == i) & (b2 == j) & (b3 == k)].sum()
    np.testing.assert_allclose(np.asarray(result).astype(float), expected, rtol=1e-12)


def test_datetime_sum_nat_propagates(engine):
    # review regression: non-skipna sum must not cast the NaN-bearing float
    # back to int64 mid-reduction (kernel dtype request skipped on the
    # datetime path)
    td = np.array([1000, 2000, 3000, "NaT"], dtype="timedelta64[ns]")
    labels = np.array([0, 0, 1, 1])
    result, _ = groupby_reduce(td, labels, func="sum", engine=engine)
    assert result.dtype == td.dtype
    assert result[0] == np.timedelta64(3000, "ns") and np.isnat(result[1])
    result, _ = groupby_reduce(td, labels, func="nansum", engine=engine)
    assert result[1] == np.timedelta64(3000, "ns")


class TestNonNumericData:
    """first/last/count on string/object arrays via the position-proxy path
    (reference: its numpy engines accept any dtype; strategies.py unicode)."""

    S = np.array(["a", "bb", "ccc", "dd", None, "e"], dtype=object)
    LABELS = np.array([0, 1, 0, 1, 2, 2])

    @pytest.mark.parametrize(
        "func,expected",
        [
            ("first", ["a", "bb", None]),
            ("last", ["ccc", "dd", "e"]),
            ("nanfirst", ["a", "bb", "e"]),
            ("nanlast", ["ccc", "dd", "e"]),
            ("count", [2, 2, 1]),
        ],
    )
    def test_object_reductions(self, func, expected):
        result, groups = groupby_reduce(self.S, self.LABELS, func=func)
        assert list(np.asarray(result)) == expected
        np.testing.assert_array_equal(groups, [0, 1, 2])

    def test_unicode_with_empty_group(self):
        s = np.array(["x", "y", "z", "w"])
        labels = np.array([0, 0, 2, 2])
        result, _ = groupby_reduce(
            s, labels, func="last", expected_groups=np.array([0, 1, 2])
        )
        assert list(result) == ["y", None, "w"]

    def test_on_mesh(self):
        from flox_tpu.parallel import make_mesh

        s = np.tile(np.array(["x", "y", "z", "w"]), 4)
        labels = np.tile(np.array([0, 0, 2, 2]), 4)
        result, _ = groupby_reduce(
            s, labels, func="first", method="map-reduce", mesh=make_mesh(8)
        )
        assert list(result) == ["x", "z"]

    def test_2d_strings(self):
        s = np.array([["a", "b", "c"], ["d", "e", "f"]], dtype=object)
        labels = np.array([0, 1, 0])
        result, _ = groupby_reduce(s, labels, func="last")
        assert np.asarray(result).tolist() == [["c", "b"], ["f", "e"]]

    def test_unsupported_func_raises(self):
        with pytest.raises(TypeError, match="non-numeric data"):
            groupby_reduce(self.S, self.LABELS, func="sum")

    def test_count_honors_fill_value(self):
        s = np.array(["x", "y"], dtype=object)
        labels = np.array([0, 0])
        result, _ = groupby_reduce(
            s, labels, func="count", fill_value=-1,
            expected_groups=np.array([0, 1]),
        )
        assert list(np.asarray(result)) == [2, -1]

    def test_finalize_kwargs_rejected(self):
        with pytest.raises(NotImplementedError, match="finalize_kwargs"):
            groupby_reduce(self.S, self.LABELS, func="count", finalize_kwargs={"q": 0.5})


@pytest.mark.parametrize("engine", ["jax", "numpy"])
class TestPinnedEdgeSemantics:
    """VERDICT r3 #10: inf/NaN argreduction ties and partial-NaN mode are
    pinned, not skipped. Oracles: numpy argmax/argmin; scipy>=1.11
    stats.mode(nan_policy="propagate")."""

    def test_argmax_first_nan_beats_inf(self, engine):
        vals = np.array([np.inf, np.nan, 3.0, np.nan, -np.inf, 2.0])
        codes = np.array([0, 0, 0, 1, 1, 1])
        got, _ = groupby_reduce(vals, codes, func="argmax", engine=engine)
        np.testing.assert_array_equal(np.asarray(got), [1, 3])  # first NaN wins
        got, _ = groupby_reduce(vals, codes, func="argmin", engine=engine)
        np.testing.assert_array_equal(np.asarray(got), [1, 3])
        # and without NaN, inf wins normally
        clean = np.array([1.0, np.inf, -np.inf, 5.0])
        ccodes = np.array([0, 0, 0, 0])
        got, _ = groupby_reduce(clean, ccodes, func="argmax", engine=engine)
        assert int(np.asarray(got)[0]) == 1

    def test_mode_partial_nan_counts_as_one_value(self, engine):
        import scipy.stats

        vals = np.array([1.0, 1.0, 2.0, np.nan,  # g0: mode 1.0 (NaN minority)
                         5.0, np.nan, np.nan, 7.0])  # g1: mode NaN (majority)
        codes = np.array([0, 0, 0, 0, 1, 1, 1, 1])
        got, _ = groupby_reduce(vals, codes, func="mode", engine=engine)
        got = np.asarray(got)
        for g in range(2):
            want = scipy.stats.mode(
                vals[codes == g], nan_policy="propagate", keepdims=False
            ).mode
            np.testing.assert_array_equal(got[g], want)
        assert got[0] == 1.0 and np.isnan(got[1])

    def test_mode_nan_tie_prefers_value(self, engine):
        # 2x NaN vs 2x 3.0: scipy's unique order puts NaN last -> 3.0 wins
        vals = np.array([3.0, 3.0, np.nan, np.nan, 9.0])
        codes = np.zeros(5, dtype=np.int64)
        got, _ = groupby_reduce(vals, codes, func="mode", engine=engine)
        assert float(np.asarray(got)[0]) == 3.0
