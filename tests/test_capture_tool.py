"""tools/onchip_capture.py logic tests (VERDICT r3 #1 machinery).

The capture loop's job is TRUSTWORTHY hardware artifacts, so the guards —
never persist a CPU fallback as TPU evidence, never mint phantom rounds,
never crash the supervisor, never re-burn tunnel-up time — are pinned
here with subprocess stubs. The on-chip legs themselves can only run on
real hardware (tests_tpu/).
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_module():
    spec = importlib.util.spec_from_file_location(
        "onchip_capture", os.path.join(REPO, "tools", "onchip_capture.py")
    )
    mod = importlib.util.module_from_spec(spec)
    before = list(sys.path)
    spec.loader.exec_module(mod)
    # the tool prepends REPO to sys.path at import; don't let per-test
    # loads accumulate interpreter-wide entries
    sys.path[:] = before
    return mod


@pytest.fixture()
def oc(monkeypatch, tmp_path):
    mod = _load_module()
    # sandbox every file the tool writes: REPO roots all artifact paths,
    # LOG the probe log — no test may touch the real committed evidence
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    monkeypatch.setattr(mod, "LOG", str(tmp_path / "capture.log"))
    os.makedirs(tmp_path / "BENCH_HISTORY")
    mod._DONE.clear()
    return mod


def _fake_proc(rows, returncode=0):
    class P:
        stdout = "\n".join(json.dumps(r) for r in rows)
        stderr = ""

    P.returncode = returncode
    return P


def test_current_round_follows_driver_trail(oc, tmp_path):
    # the driver commits BENCH_r{N}.json at the END of round N: with
    # r01..r03 present the session is round 4 (stub files, not live repo
    # state — the real trail grows every round)
    assert oc._current_round() == 1  # empty sandbox
    for n in (1, 2, 3):
        (tmp_path / f"BENCH_r{n:02d}.json").write_text("{}\n")
    assert oc._current_round() == 4


def test_history_sweep_rejects_cpu_fallback(oc, monkeypatch, tmp_path):
    import subprocess

    rows = [{"bench": "platform", "value": "cpu", "unit": "config"}] + [
        {"bench": f"b{i}[x-jax]", "value": 1.0, "unit": "ms"} for i in range(6)
    ]
    monkeypatch.setattr(subprocess, "run", lambda *a, **k: _fake_proc(rows))
    assert oc.run_history_sweep() is False
    assert not list((tmp_path / "BENCH_HISTORY").iterdir())


def test_history_sweep_records_true_backend_idempotently(oc, monkeypatch, tmp_path):
    import subprocess

    rows = [{"bench": "platform", "value": "tpu", "unit": "config"}] + [
        {"bench": f"b{i}[x-jax]", "value": 1.0, "unit": "ms"} for i in range(6)
    ]
    monkeypatch.setattr(subprocess, "run", lambda *a, **k: _fake_proc(rows))
    # no BENCH_r*.json in the sandbox -> round 1
    assert oc.run_history_sweep() is True
    assert oc.run_history_sweep() is True  # same file, no phantom rounds
    files = sorted(os.listdir(tmp_path / "BENCH_HISTORY"))
    assert files == ["r01_tpu.jsonl"]
    recs = [json.loads(l) for l in open(tmp_path / "BENCH_HISTORY" / files[0])]
    assert recs[0] == {"bench": "platform", "value": "tpu", "unit": "config"}
    assert len(recs) == 7


def test_history_sweep_survives_junk_stdout(oc, monkeypatch, tmp_path):
    import subprocess

    class P:
        returncode = 0
        stdout = "{'not json'}\nWARNING: stuff\n" + "\n".join(
            json.dumps(r)
            for r in [{"bench": "platform", "value": "tpu", "unit": "config"}]
            + [{"bench": f"b{i}", "value": 1.0, "unit": "ms"} for i in range(6)]
        )
        stderr = ""

    monkeypatch.setattr(subprocess, "run", lambda *a, **k: P())
    assert oc.run_history_sweep() is True


def test_history_sweep_never_raises(oc, monkeypatch):
    import subprocess

    def boom(*a, **k):
        raise OSError("disk gone")

    monkeypatch.setattr(subprocess, "run", boom)
    assert oc.run_history_sweep() is False  # logged, not raised


def test_capture_once_memoizes_completed_steps(oc, monkeypatch):
    calls = []
    monkeypatch.setattr(oc, "run_bench", lambda: calls.append("b") or True)
    monkeypatch.setattr(oc, "run_tests_tpu", lambda: calls.append("t") or False)
    monkeypatch.setattr(oc, "run_accuracy", lambda: calls.append("a") or True)
    monkeypatch.setattr(oc, "run_history_sweep", lambda: calls.append("h") or True)
    assert oc.capture_once() is False  # tests leg failed
    assert calls == ["b", "t", "a", "h"]
    # retry: only the failed leg re-runs
    monkeypatch.setattr(oc, "run_tests_tpu", lambda: calls.append("t2") or True)
    assert oc.capture_once() is True
    assert calls == ["b", "t", "a", "h", "t2"]


def test_accuracy_rejects_cpu_fallback(oc, monkeypatch, tmp_path):
    import subprocess

    rec = {"platform": "cpu", "table": {}}

    class P:
        returncode = 0
        stdout = json.dumps(rec)
        stderr = ""

    monkeypatch.setattr(subprocess, "run", lambda *a, **k: P())
    (tmp_path / "bench_accuracy.py").write_text("# present\n")
    assert oc.run_accuracy() is False
    assert not (tmp_path / "ACCURACY_TPU_LAST.json").exists()
