"""Test harness configuration.

Mirrors the reference's strategy (SURVEY.md §4): all tests single-process,
with "distributed" correctness exercised on a virtual 8-device CPU mesh
(``xla_force_host_platform_device_count``) — the TPU analogue of running
dask with the synchronous scheduler (reference tests/test_core.py:65).
float64 is enabled so results are comparable bit-for-bit with numpy oracles.
"""

import os

# The environment pre-imports jax at interpreter startup (sitecustomize), so
# env vars are too late; jax.config.update still works before first backend use.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def ndevices() -> int:
    return len(jax.devices())


@pytest.fixture(scope="module", params=["jax", "numpy"])
def engine(request):
    """Run engine-parameterized tests per engine (reference conftest.py:22-32)."""
    return request.param


#: modules whose module-level locks the schedule-stress leg watches for
#: acquisition-order inversions (the serve/fleet concurrency surface)
_STRESS_WATCH = (
    "flox_tpu.autotune",
    "flox_tpu.exposition",
    "flox_tpu.pipeline",
    "flox_tpu.profiling",
    "flox_tpu.telemetry",
    "flox_tpu.serve.aot",
    "flox_tpu.serve.breaker",
    "flox_tpu.serve.dispatcher",
)


@pytest.fixture(scope="session", autouse=True)
def _schedule_stress():
    """CI's schedule-stress leg: ``FLOX_TPU_STRESS_SCHEDULE=1`` re-runs the
    suite with the thread switch interval at ~1 µs and the serve plane's
    module-level locks wrapped in acquisition-order-asserting proxies
    (``faults.stress_schedule``) — a reintroduced race or lock-order
    inversion fails here instead of once a month in production."""
    if not os.environ.get("FLOX_TPU_STRESS_SCHEDULE"):
        yield
        return
    from flox_tpu import faults

    # FLOX_TPU_STRESS_ORDER_GRAPH: path to floxlint's --lock-graph JSON;
    # seeding with the static edges makes one runtime acquire against the
    # established order enough to fail
    with faults.stress_schedule(
        watch=_STRESS_WATCH,
        order_graph=os.environ.get("FLOX_TPU_STRESS_ORDER_GRAPH") or None,
    ):
        yield
