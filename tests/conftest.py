"""Test harness configuration.

Mirrors the reference's strategy (SURVEY.md §4): all tests single-process,
with "distributed" correctness exercised on a virtual 8-device CPU mesh
(``xla_force_host_platform_device_count``) — the TPU analogue of running
dask with the synchronous scheduler (reference tests/test_core.py:65).
float64 is enabled so results are comparable bit-for-bit with numpy oracles.
"""

import os

# The environment pre-imports jax at interpreter startup (sitecustomize), so
# env vars are too late; jax.config.update still works before first backend use.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def ndevices() -> int:
    return len(jax.devices())


@pytest.fixture(scope="module", params=["jax", "numpy"])
def engine(request):
    """Run engine-parameterized tests per engine (reference conftest.py:22-32)."""
    return request.param
