"""Fault-injection suite for the resilient streaming executor (ISSUE 3).

Every resilience claim is exercised against the deterministic harness in
``flox_tpu.faults``: transient loader faults retry with backoff and leave
the result bit-identical; a fault repeated past ``stream_retries`` surfaces
the ORIGINAL exception; programming errors never retry; a simulated-OOM
slab splits on the power-of-two ladder without retracing the base step
(compile-count asserted); and kill-at-slab-k + resume reproduces the
uninterrupted result exactly — for reduce/scan/quantile, prefetch on and
off, single-device and CPU-mesh shard_map paths.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

import flox_tpu
from flox_tpu import faults
from flox_tpu.resilience import (
    FATAL,
    OOM,
    TRANSIENT,
    _SNAPSHOTS,
    StreamCounters,
    classify_error,
    register_transient,
)
from flox_tpu.streaming import (
    _STEP_CACHE,
    streaming_groupby_reduce,
    streaming_groupby_scan,
)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    n = 3000
    vals = rng.normal(size=(3, n))
    vals[:, ::11] = np.nan
    labels = rng.integers(0, 7, n)
    return vals, labels


@pytest.fixture(autouse=True)
def _clean_snapshots():
    _SNAPSHOTS.clear()
    yield
    _SNAPSHOTS.clear()


def _bits(x):
    return np.ascontiguousarray(np.asarray(x)).tobytes()


# ---------------------------------------------------------------------------
# error taxonomy


class TestClassifier:
    @pytest.mark.parametrize("exc", [
        IOError("read failed"),
        OSError("connection reset"),
        ConnectionError("refused"),
        TimeoutError("slow backend"),
        BrokenPipeError(),
    ])
    def test_io_family_is_transient(self, exc):
        assert classify_error(exc) == TRANSIENT

    @pytest.mark.parametrize("exc", [
        ValueError("bad arg"),
        TypeError("not callable"),
        KeyError("missing"),
        IndexError("oob"),
        NotImplementedError("nope"),
        faults.StreamKilled("preempted"),
        # configuration errors in the OSError family can never succeed on
        # retry: burning the backoff budget on them is the FLX006 hazard
        FileNotFoundError("/wrong/path/chunk.0.0"),
        PermissionError("denied"),
        IsADirectoryError("/data"),
        NotADirectoryError("/data/file/x"),
    ])
    def test_programming_errors_are_fatal(self, exc):
        assert classify_error(exc) == FATAL

    def test_non_recoverable_os_can_opt_back_in(self):
        # an eventually-consistent store whose missing-key reads ARE
        # transient re-registers the type explicitly
        from flox_tpu.resilience import _TRANSIENT_TYPES

        assert classify_error(FileNotFoundError("s3 404")) == FATAL
        register_transient(FileNotFoundError)
        try:
            assert classify_error(FileNotFoundError("s3 404")) == TRANSIENT
        finally:
            _TRANSIENT_TYPES.remove(FileNotFoundError)

    def test_oom_family(self):
        assert classify_error(faults.SimulatedOOM("slab")) == OOM
        assert classify_error(MemoryError()) == OOM
        # the real jaxlib error, classified by name + status token so no
        # version-pinned import is needed
        XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
        assert classify_error(
            XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating 1 bytes.")
        ) == OOM
        assert classify_error(XlaRuntimeError("UNAVAILABLE: backend rpc")) == TRANSIENT
        assert classify_error(XlaRuntimeError("INVALID_ARGUMENT: shapes")) == FATAL

    def test_register_transient_extends(self):
        class ThrottlingError(Exception):
            pass

        assert classify_error(ThrottlingError()) == FATAL
        register_transient(ThrottlingError)
        assert classify_error(ThrottlingError()) == TRANSIENT
        with pytest.raises(TypeError):
            register_transient("not a type")

    def test_device_loss_family(self):
        from flox_tpu.resilience import DEVICE_LOST

        assert classify_error(faults.SimulatedDeviceLoss("chip 0")) == DEVICE_LOST
        XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
        assert classify_error(
            XlaRuntimeError("INTERNAL: device lost (it crashed)")
        ) == DEVICE_LOST
        assert classify_error(XlaRuntimeError("DEVICE_LOST: gone")) == DEVICE_LOST


class TestClassifierWrappedChains:
    """ISSUE 12 satellite: a transient/oom/device-loss error wrapped in a
    generic RuntimeError (asyncio.to_thread plumbing, loader-SDK
    ``raise ... from exc``) must not be misclassified fatal — the chain is
    walked when the outer verdict is fatal, and ONLY then (an explicitly
    transient outer error never consults its context)."""

    def test_cause_chain_unwraps_transient(self):
        outer = RuntimeError("loader wrapper")
        outer.__cause__ = IOError("read failed")
        assert classify_error(outer) == TRANSIENT

    def test_context_chain_unwraps_transient(self):
        try:
            try:
                raise IOError("flaky read")
            except IOError:
                raise ValueError("raised while handling")  # noqa: B904
        except ValueError as exc:
            assert exc.__context__ is not None
            assert classify_error(exc) == TRANSIENT

    def test_cause_chain_unwraps_oom_and_device_loss(self):
        from flox_tpu.resilience import DEVICE_LOST

        outer = RuntimeError("wrapper")
        outer.__cause__ = MemoryError()
        assert classify_error(outer) == OOM
        outer = KeyError("wrapper")
        outer.__cause__ = faults.SimulatedDeviceLoss("chip")
        assert classify_error(outer) == DEVICE_LOST

    def test_nested_two_level_chain(self):
        inner = OSError("socket reset")
        mid = RuntimeError("mid wrapper")
        mid.__cause__ = inner
        outer = RuntimeError("outer wrapper")
        outer.__cause__ = mid
        assert classify_error(outer) == TRANSIENT

    def test_plain_fatal_stays_fatal(self):
        outer = RuntimeError("genuine bug")
        outer.__cause__ = TypeError("still a bug")
        assert classify_error(outer) == FATAL

    def test_self_referential_chain_terminates(self):
        exc = RuntimeError("cyclic")
        exc.__context__ = exc
        assert classify_error(exc) == FATAL

    def test_transient_outer_never_consults_chain(self):
        # an explicitly transient classification is already the verdict;
        # a fatal link underneath must not harden it
        outer = IOError("transient outer")
        outer.__cause__ = TypeError("fatal inner")
        assert classify_error(outer) == TRANSIENT

    def test_to_thread_propagated_exception_keeps_class(self):
        import asyncio

        async def main():
            def boom():
                raise IOError("raised inside to_thread")

            try:
                await asyncio.to_thread(boom)
            except Exception as exc:  # noqa: BLE001 — classifying is the test
                return classify_error(exc)

        assert asyncio.run(main()) == TRANSIENT


class TestBackoffJitter:
    """ISSUE 12 satellite: full jitter on the exponential backoff, so
    prefetch workers hitting the same transient fault do not retry in
    lockstep — seedable for deterministic chaos runs."""

    def test_full_jitter_spreads_within_cap(self):
        from flox_tpu.resilience import RetryPolicy, seed_backoff

        seed_backoff(7)
        policy = RetryPolicy(backoff=0.1)
        delays = [policy.delay(2) for _ in range(64)]
        cap = 0.1 * 4
        assert all(0 < d <= cap for d in delays)
        # genuinely jittered: not all equal, and spread across the window
        assert len({round(d, 9) for d in delays}) > 8
        assert min(delays) < cap / 4 and max(delays) > cap / 2

    def test_seeded_schedule_is_reproducible(self):
        from flox_tpu.resilience import RetryPolicy, seed_backoff

        policy = RetryPolicy(backoff=0.05)
        seed_backoff(123)
        first = [policy.delay(a) for a in range(6)]
        seed_backoff(123)
        assert [policy.delay(a) for a in range(6)] == first

    def test_zero_backoff_stays_zero(self):
        from flox_tpu.resilience import RetryPolicy

        assert RetryPolicy(backoff=0.0).delay(3) == 0.0

    def test_jittered_retries_stay_bit_identical(self, data):
        # the jitter changes WHEN retries fire, never WHAT they compute
        from flox_tpu.resilience import seed_backoff

        vals, labels = data
        base, _ = streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=700)
        seed_backoff(99)
        flaky = faults.FlakyLoader(lambda s, e: vals[:, s:e], {700: IOError}, times=2)
        with flox_tpu.set_options(stream_backoff=0.001):
            got, _ = streaming_groupby_reduce(
                flaky, labels, func="nanmean", batch_len=700
            )
        assert _bits(got) == _bits(base)
        assert flaky.loads_of(700) == 3


# ---------------------------------------------------------------------------
# retry with backoff + per-slab deadline


class TestRetryBackoff:
    @pytest.mark.parametrize("depth", [0, 2])
    def test_transient_fault_retried_bit_identical(self, data, depth):
        vals, labels = data
        base, _ = streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=700)
        flaky = faults.FlakyLoader(lambda s, e: vals[:, s:e], {1400: IOError}, times=2)
        with flox_tpu.set_options(stream_prefetch=depth, stream_backoff=0.001):
            from flox_tpu import profiling

            with profiling.stream_monitor() as reports:
                got, _ = streaming_groupby_reduce(
                    flaky, labels, func="nanmean", batch_len=700
                )
        assert _bits(got) == _bits(base)
        assert flaky.loads_of(1400) == 3  # 2 injected failures + the success
        # the retries flow into the StreamReport counters
        assert reports[0].retries == 2
        assert reports[0].backoff_ms > 0
        assert "retries 2" in reports[0].summary()

    @pytest.mark.parametrize("depth", [0, 3])
    def test_exhausted_retries_surface_original_exception(self, data, depth):
        # acceptance: a fault injected stream_retries + 1 times surfaces the
        # ORIGINAL exception (not a wrapper), promptly, pool torn down
        import threading

        vals, labels = data
        with flox_tpu.set_options(
            stream_prefetch=depth, stream_retries=2, stream_backoff=0.001
        ):
            flaky = faults.FlakyLoader(
                lambda s, e: vals[:, s:e], {1400: IOError("loader died at 1400")},
                times=3,
            )
            with pytest.raises(IOError, match="loader died at 1400"):
                streaming_groupby_reduce(flaky, labels, func="nanmean", batch_len=700)
        time.sleep(0.05)
        assert not [t for t in threading.enumerate() if "flox-tpu-stage" in t.name]

    def test_fatal_error_never_retried(self, data):
        vals, labels = data
        flaky = faults.FlakyLoader(
            lambda s, e: vals[:, s:e], {1400: TypeError("bug, not weather")}, times=-1
        )
        with flox_tpu.set_options(stream_retries=5, stream_backoff=0.001):
            with pytest.raises(TypeError, match="bug, not weather"):
                streaming_groupby_reduce(flaky, labels, func="nanmean", batch_len=700)
        assert flaky.loads_of(1400) == 1  # one attempt, zero retries

    def test_slab_deadline_bounds_backoff(self, data):
        vals, labels = data
        flaky = faults.FlakyLoader(lambda s, e: vals[:, s:e], {1400: IOError}, times=-1)
        t0 = time.perf_counter()
        with flox_tpu.set_options(
            stream_retries=50, stream_backoff=30.0, stream_slab_timeout=0.05
        ):
            with pytest.raises(TimeoutError, match="stream_slab_timeout"):
                streaming_groupby_reduce(flaky, labels, func="nanmean", batch_len=700)
        # the deadline refuses the 30 s backoff sleep instead of serving it
        assert time.perf_counter() - t0 < 10.0

    def test_scan_and_quantile_retry_too(self, data):
        vals, labels = data
        base_scan = streaming_groupby_scan(vals, labels, func="nancumsum", batch_len=700)
        flaky = faults.FlakyLoader(lambda s, e: vals[:, s:e], {1400: IOError}, times=1)
        with flox_tpu.set_options(stream_backoff=0.001):
            got = streaming_groupby_scan(flaky, labels, func="nancumsum", batch_len=700)
        assert _bits(got) == _bits(base_scan)

        v32 = vals.astype(np.float32)
        base_q, _ = streaming_groupby_reduce(v32, labels, func="nanmedian", batch_len=1000)
        flaky_q = faults.FlakyLoader(lambda s, e: v32[:, s:e], {1000: IOError}, times=2)
        with flox_tpu.set_options(stream_backoff=0.001):
            got_q, _ = streaming_groupby_reduce(
                flaky_q, labels, func="nanmedian", batch_len=1000
            )
        assert _bits(got_q) == _bits(base_q)


# ---------------------------------------------------------------------------
# graceful OOM degradation: halve + re-stage on the power-of-two ladder


class TestOOMSplit:
    def test_reduce_split_completes_and_matches(self, data):
        vals, labels = data
        ref, _ = streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=700)
        from flox_tpu import profiling

        with faults.inject(oom_at=[1400]) as plan:
            with profiling.stream_monitor() as reports:
                got, _ = streaming_groupby_reduce(
                    vals, labels, func="nanmean", batch_len=700
                )
        assert [rec for rec in plan.log if rec[0] == "SimulatedOOM"] == [
            ("SimulatedOOM", 1400, 2100)
        ]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-12, equal_nan=True
        )
        assert reports[0].oom_splits == 1
        assert "oom-splits 1" in reports[0].summary()

    def test_position_reductions_split_exactly(self, data):
        # argmax positions are integers: sub-slab offsets must be exact
        vals, labels = data
        v = np.nan_to_num(vals, nan=0.5)
        ref, _ = streaming_groupby_reduce(v, labels, func="argmax", batch_len=700)
        with faults.inject(oom_at=[700, 2100]):
            got, _ = streaming_groupby_reduce(v, labels, func="argmax", batch_len=700)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_base_step_not_retraced_ladder_reused(self, data):
        # acceptance: the split completes WITHOUT retracing the base step —
        # sub-slabs pad to a power-of-two rung that compiles once and is
        # reused by every later split
        vals, labels = data
        _STEP_CACHE.clear()
        ref, _ = streaming_groupby_reduce(vals, labels, func="sum", batch_len=500)
        step = next(v for k, v in _STEP_CACHE.items() if k[0] == "reduce-step")
        base_traces = step._jitted._cache_size()
        with faults.inject(oom_at=[1000, 2500]) as plan:
            got, _ = streaming_groupby_reduce(vals, labels, func="sum", batch_len=500)
        assert sum(1 for rec in plan.log if rec[0]) == 2  # both slabs split
        # ONE new trace: the 256-wide rung, shared by both split slabs
        assert step._jitted._cache_size() == base_traces + 1
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)
        # a later run splitting a third slab reuses the rung: no new traces
        with faults.inject(oom_at=[2000]):
            streaming_groupby_reduce(vals, labels, func="sum", batch_len=500)
        assert step._jitted._cache_size() == base_traces + 1

    def test_recursive_split(self, data):
        # oom_times=2: the first re-staged sub-slab (same start offset)
        # OOMs again and splits one rung deeper
        vals, labels = data
        ref, _ = streaming_groupby_reduce(vals, labels, func="sum", batch_len=700)
        counters_seen = []
        from flox_tpu import profiling

        with faults.inject(oom_at=[1400], oom_times=2):
            with profiling.stream_monitor() as reports:
                got, _ = streaming_groupby_reduce(
                    vals, labels, func="sum", batch_len=700
                )
        counters_seen.append(reports[0].oom_splits)
        assert counters_seen[0] == 2
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)

    def test_ladder_half_descends_for_any_quantum(self):
        from flox_tpu.resilience import _ladder_half

        # power-of-two quanta: pure pow2 ladder
        assert _ladder_half(1000, 1) == 512
        assert _ladder_half(512, 1) == 256
        assert _ladder_half(3, 1) == 2
        assert _ladder_half(1000, 8) == 512
        # non-power-of-two quanta must still descend: rounding the pow2
        # rung up to the quantum may reach the span itself, where the
        # largest quantum multiple below it is the legal split
        assert _ladder_half(24, 6) == 18
        assert _ladder_half(18, 6) == 12
        assert _ladder_half(12, 6) == 6
        for quantum in (1, 2, 3, 5, 6, 7, 8):
            length = 16 * quantum
            while length > quantum:
                half = _ladder_half(length, quantum)
                assert quantum <= half < length and half % quantum == 0, (
                    length, quantum, half,
                )
                length = half

    def test_unsplittable_oom_surfaces(self, data):
        # a slab that OOMs at EVERY granularity cannot degrade: the original
        # resource-exhausted error surfaces once the ladder hits bottom
        vals, labels = data
        with faults.inject(oom_at=[1400], oom_times=-1):
            with pytest.raises(faults.SimulatedOOM, match="RESOURCE_EXHAUSTED"):
                streaming_groupby_reduce(vals, labels, func="sum", batch_len=700)

    def test_scan_split_forward_and_reverse(self, data):
        vals, labels = data
        for func in ("nancumsum", "bfill"):
            ref = streaming_groupby_scan(vals, labels, func=func, batch_len=700)
            with faults.inject(oom_at=[1400]):
                got = streaming_groupby_scan(vals, labels, func=func, batch_len=700)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(ref), rtol=1e-12, atol=1e-12,
                equal_nan=True,
            )

    def test_quantile_split(self, data):
        vals, labels = data
        v32 = vals.astype(np.float32)
        ref, _ = streaming_groupby_reduce(v32, labels, func="nanmedian", batch_len=1000)
        with faults.inject(oom_at=[1000]):
            got, _ = streaming_groupby_reduce(v32, labels, func="nanmedian", batch_len=1000)
        # counting passes are exact: the split result is bit-identical
        assert _bits(got) == _bits(ref)

    def test_mesh_split_positions_exact(self, data):
        from flox_tpu.parallel.mesh import make_mesh

        vals, labels = data
        v = np.nan_to_num(vals, nan=0.5)[:, :2400]
        lab = labels[:2400]
        mesh = make_mesh()
        ref, _ = streaming_groupby_reduce(v, lab, func="argmax", batch_len=800, mesh=mesh)
        with faults.inject(oom_at=[800]):
            got, _ = streaming_groupby_reduce(
                v, lab, func="argmax", batch_len=800, mesh=mesh
            )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


# ---------------------------------------------------------------------------
# checkpoint / resume: kill-at-slab-k is bit-identical to uninterrupted


class _KillScenario:
    """One kill+resume scenario: ``baseline()`` the uninterrupted bytes,
    ``prepare()`` resets state before the killed attempt, ``run()`` executes
    (raising StreamKilled under the plan) and returns result bytes.

    The scan scenario streams through a writer into a NaN-poisoned buffer:
    the killed run writes slabs [0, k), the resumed run rewrites from the
    checkpoint cursor on — any slab NEITHER covers stays NaN and fails the
    byte comparison, so the test cannot pass by accident of leftover state.
    """

    def __init__(self, kind, vals, labels, mesh=None, batch_len=500):
        self.kind = kind
        self.labels = labels
        self.batch_len = batch_len
        self.mesh_kw = {} if mesh is None else {"mesh": mesh}
        # f32 keys keep the quantile at 33 passes instead of 65
        self.vals = vals.astype(np.float32) if kind == "quantile" else vals
        if kind == "scan":
            self.buf = np.full(vals.shape, np.nan)
            self.kill_plan = {"kill_at": [2 * batch_len]}
        elif kind == "reduce":
            self.kill_plan = {"kill_at": [2 * batch_len]}
        else:  # kill inside the quantile bit passes, past the count pass
            self.kill_plan = {"kill_after": 8}

    def prepare(self):
        if self.kind == "scan":
            self.buf[...] = np.nan

    def run(self):
        if self.kind == "scan":
            r = streaming_groupby_scan(
                self.vals, self.labels, func="nancumsum", batch_len=self.batch_len,
                out=lambda s, e, res: self.buf.__setitem__((..., slice(s, e)), res),
                **self.mesh_kw,
            )
            assert r is None
            return self.buf.tobytes()
        func = "nanmedian" if self.kind == "quantile" else "nanmean"
        got, _ = streaming_groupby_reduce(
            self.vals, self.labels, func=func, batch_len=self.batch_len,
            **self.mesh_kw,
        )
        return _bits(got)

    def baseline(self):
        self.prepare()
        return self.run()


class TestKillResume:
    @pytest.mark.parametrize("depth", [0, 2])
    @pytest.mark.parametrize("kind", ["reduce", "scan", "quantile"])
    def test_single_device_bit_identical(self, data, kind, depth):
        vals, labels = data
        sc = _KillScenario(kind, vals, labels)
        with flox_tpu.set_options(stream_prefetch=depth):
            base = sc.baseline()
            with flox_tpu.set_options(stream_checkpoint_every=2):
                sc.prepare()
                with faults.inject(**sc.kill_plan):
                    with pytest.raises(faults.StreamKilled):
                        sc.run()
                assert len(_SNAPSHOTS) == 1
                from flox_tpu import profiling

                with profiling.stream_monitor() as reports:
                    resumed = sc.run()
                assert reports[-1].counters.resumed_at is not None
        assert resumed == base  # byte strings
        assert _SNAPSHOTS == {}  # done() dropped the snapshot

    @pytest.mark.parametrize("kind", ["reduce", "scan", "quantile"])
    def test_mesh_bit_identical(self, data, kind):
        from flox_tpu.parallel.mesh import make_mesh

        vals, labels = data
        sc = _KillScenario(
            kind, vals[:, :2400], labels[:2400], mesh=make_mesh(), batch_len=800
        )
        base = sc.baseline()
        with flox_tpu.set_options(stream_checkpoint_every=1):
            sc.prepare()
            with faults.inject(**sc.kill_plan):
                with pytest.raises(faults.StreamKilled):
                    sc.run()
            assert len(_SNAPSHOTS) == 1
            resumed = sc.run()
        assert resumed == base

    def test_resume_skips_processed_slabs(self, data):
        vals, labels = data
        calls = []

        def loader(s, e):
            calls.append((s, e))
            return vals[:, s:e]

        base, _ = streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=500)
        with flox_tpu.set_options(stream_checkpoint_every=2):
            with faults.inject(kill_at=[4 * 500]):
                with pytest.raises(faults.StreamKilled):
                    streaming_groupby_reduce(loader, labels, func="nanmean", batch_len=500)
            calls.clear()
            got, _ = streaming_groupby_reduce(loader, labels, func="nanmean", batch_len=500)
        assert _bits(got) == _bits(base)
        # slabs before the checkpoint cursor were NOT re-read (the probe
        # loader(0, 1) is the only touch below it)
        assert not [c for c in calls if c[0] == 0 and c[1] - c[0] > 1]
        assert min(s for s, e in calls if e - s > 1) == 4 * 500

    def test_npz_spill_survives_process_death(self, data, tmp_path):
        vals, labels = data
        base, _ = streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=500)
        with flox_tpu.set_options(
            stream_checkpoint_every=2, stream_checkpoint_path=str(tmp_path)
        ):
            with faults.inject(kill_at=[4 * 500]):
                with pytest.raises(faults.StreamKilled):
                    streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=500)
            spilled = list(tmp_path.glob("*.npz"))
            assert len(spilled) == 1
            # "new process": the in-memory registry is gone, only the file
            _SNAPSHOTS.clear()
            got, _ = streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=500)
            assert _bits(got) == _bits(base)
            assert list(tmp_path.glob("*.npz")) == []  # done() removed it

    def test_corrupt_spill_falls_back_to_fresh_run(self, data, tmp_path):
        vals, labels = data
        base, _ = streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=500)
        target = tmp_path / "snap.npz"
        target.write_bytes(b"not an npz at all")
        with flox_tpu.set_options(
            stream_checkpoint_every=2, stream_checkpoint_path=str(target)
        ):
            got, _ = streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=500)
        assert _bits(got) == _bits(base)

    def test_scan_without_writer_not_checkpointed(self, data):
        # no writer = nowhere for already-emitted slabs to survive a kill,
        # so the scan takes no snapshots rather than promising a resume it
        # cannot honor
        vals, labels = data
        with flox_tpu.set_options(stream_checkpoint_every=1):
            streaming_groupby_scan(vals, labels, func="nancumsum", batch_len=500)
            with faults.inject(kill_at=[3 * 500]):
                with pytest.raises(faults.StreamKilled):
                    streaming_groupby_scan(vals, labels, func="nancumsum", batch_len=500)
            assert _SNAPSHOTS == {}

    def test_disabled_by_default(self, data):
        vals, labels = data
        with faults.inject(kill_at=[2 * 500]):
            with pytest.raises(faults.StreamKilled):
                streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=500)
        assert _SNAPSHOTS == {}

    def test_different_agg_identity_misses_stale_snapshot(self, data):
        # the reduce key carries the RESOLVED aggregation identity: a
        # dtype= override changes the accumulators, so a snapshot from the
        # float32 run must not fold into the float64 rerun
        vals, labels = data
        v32 = np.nan_to_num(vals, nan=0.0).astype(np.float32)
        base64, _ = streaming_groupby_reduce(
            v32, labels, func="nansum", dtype=np.float64, batch_len=500
        )
        with flox_tpu.set_options(stream_checkpoint_every=2):
            with faults.inject(kill_at=[4 * 500]):
                with pytest.raises(faults.StreamKilled):
                    streaming_groupby_reduce(
                        v32, labels, func="nansum", dtype=np.float32, batch_len=500
                    )
            assert len(_SNAPSHOTS) == 1
            got, _ = streaming_groupby_reduce(
                v32, labels, func="nansum", dtype=np.float64, batch_len=500
            )
        assert _bits(got) == _bits(base64)
        assert len(_SNAPSHOTS) == 1  # the float32 snapshot was never touched

    def test_scan_checkpoint_identity_distinguishes_custom_scans(self):
        # a custom Scan sharing a builtin's name must produce a different
        # checkpoint identity — resuming a cumsum snapshot into a custom
        # same-named scan would silently fold mismatched carries
        from flox_tpu.aggregations import SCANS, Scan
        from flox_tpu.streaming import _scan_ckpt_id

        builtin = SCANS["cumsum"]
        custom = Scan(
            "cumsum", scan="cumsum", reduction="sum",
            binary_op=lambda a, b: a + b, identity=0,
        )
        assert _scan_ckpt_id(custom) != _scan_ckpt_id(builtin)
        assert _scan_ckpt_id(builtin) == _scan_ckpt_id(SCANS["cumsum"])

    def test_changed_data_tripwire_misses_stale_snapshot(self, data):
        # the checkpoint key fingerprints the probe slab: a run over edited
        # data must NOT resume from the old run's snapshot (which would
        # silently fold stale state into the new values)
        vals, labels = data
        v2 = vals.copy()
        v2[:, 0] = 5.0  # the fixture's column 0 is NaN: give the probe new bytes
        base2, _ = streaming_groupby_reduce(v2, labels, func="nanmean", batch_len=500)
        with flox_tpu.set_options(stream_checkpoint_every=2):
            with faults.inject(kill_at=[4 * 500]):
                with pytest.raises(faults.StreamKilled):
                    streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=500)
            assert len(_SNAPSHOTS) == 1
            got, _ = streaming_groupby_reduce(v2, labels, func="nanmean", batch_len=500)
        assert _bits(got) == _bits(base2)
        assert len(_SNAPSHOTS) == 1  # the stale v1 snapshot was never touched

    def test_clear_all_drops_snapshots(self, data):
        vals, labels = data
        with flox_tpu.set_options(stream_checkpoint_every=1):
            with faults.inject(kill_at=[2 * 500]):
                with pytest.raises(faults.StreamKilled):
                    streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=500)
        assert len(_SNAPSHOTS) == 1
        flox_tpu.cache.clear_all()
        assert _SNAPSHOTS == {}


# ---------------------------------------------------------------------------
# loader contract


class TestLoaderContract:
    @pytest.mark.parametrize("depth", [0, 2])
    def test_wrong_shape_names_slab_range(self, data, depth):
        vals, labels = data
        bad = faults.misshaping_loader(lambda s, e: vals[:, s:e], at=1400, shape=(3, 11))
        with flox_tpu.set_options(stream_prefetch=depth):
            with pytest.raises(ValueError, match=r"slab \[1400:2100\).*\(3, 11\)"):
                streaming_groupby_reduce(bad, labels, func="nanmean", batch_len=700)

    def test_dtype_drift_names_slab_range(self, data):
        vals, labels = data

        def bad(s, e):
            sl = vals[:, s:e]
            return sl.astype(np.float32) if s >= 1400 else sl

        with pytest.raises(ValueError, match=r"slab \[1400:2100\).*float32"):
            streaming_groupby_reduce(bad, labels, func="nanmean", batch_len=700)

    def test_contract_violation_not_retried(self, data):
        vals, labels = data
        calls = []

        def bad(s, e):
            calls.append((s, e))
            if s == 1400:
                return np.zeros((3, 5))
            return vals[:, s:e]

        with flox_tpu.set_options(stream_retries=5, stream_backoff=0.001):
            with pytest.raises(ValueError, match="loader contract"):
                streaming_groupby_reduce(bad, labels, func="nanmean", batch_len=700)
        assert len([c for c in calls if c[0] == 1400]) == 1


# ---------------------------------------------------------------------------
# option validation (set-time, not mid-stream)


class TestOptionValidation:
    @pytest.mark.parametrize("kwargs", [
        {"stream_retries": -1},
        {"stream_retries": 2.5},
        {"stream_retries": True},
        {"stream_backoff": -0.1},
        {"stream_backoff": "fast"},
        {"stream_backoff": float("nan")},
        {"stream_backoff": float("inf")},
        {"stream_slab_timeout": float("nan")},
        {"stream_slab_timeout": -1},
        {"stream_checkpoint_every": -2},
        {"stream_checkpoint_every": 1.5},
        {"stream_checkpoint_path": ""},
        {"stream_checkpoint_path": 123},
        {"stream_prefetch": -1},
        {"stream_prefetch": True},
        {"stream_dispatch_depth": -2},
    ])
    def test_invalid_values_raise_at_set_time(self, kwargs):
        with pytest.raises(ValueError):
            flox_tpu.set_options(**kwargs)

    def test_valid_values_roundtrip(self, tmp_path):
        from flox_tpu.options import OPTIONS

        before = {k: OPTIONS[k] for k in OPTIONS}
        with flox_tpu.set_options(
            stream_retries=0, stream_backoff=0.0, stream_slab_timeout=1.5,
            stream_checkpoint_every=10, stream_checkpoint_path=str(tmp_path),
        ):
            assert OPTIONS["stream_checkpoint_every"] == 10
        assert {k: OPTIONS[k] for k in OPTIONS} == before
        # pathlib.Path is a filesystem option: accepted, not rejected
        with flox_tpu.set_options(stream_checkpoint_path=tmp_path):
            assert OPTIONS["stream_checkpoint_path"] == tmp_path

    def test_env_mirrors_follow_validator_bounds(self):
        # malformed/out-of-bounds env values fall back instead of breaking
        # import — mirroring the _env_int contract
        from flox_tpu.options import _env_float, _env_int

        os.environ["_FLOX_TEST_ENV"] = "-3"
        try:
            assert _env_int("_FLOX_TEST_ENV", 2, 0) == 2
            assert _env_float("_FLOX_TEST_ENV", 0.5) == 0.5
            os.environ["_FLOX_TEST_ENV"] = "junk"
            assert _env_int("_FLOX_TEST_ENV", 2, 0) == 2
            assert _env_float("_FLOX_TEST_ENV", 0.5) == 0.5
            os.environ["_FLOX_TEST_ENV"] = "0.25"
            assert _env_float("_FLOX_TEST_ENV", 0.5) == 0.25
            # nan would reach time.sleep mid-retry, inf would sleep forever:
            # the env cannot seed what set_options refuses
            for bad in ("nan", "inf", "-inf"):
                os.environ["_FLOX_TEST_ENV"] = bad
                assert _env_float("_FLOX_TEST_ENV", 0.5) == 0.5
        finally:
            del os.environ["_FLOX_TEST_ENV"]

    def test_env_float_open_and_upper_bounds(self):
        # ISSUE 5 (FLX010): every OPTIONS field now has an env mirror, which
        # needs _env_float to express `0 < x <= 1`-shaped validator bounds
        from flox_tpu.options import _env_float

        try:
            os.environ["_FLOX_TEST_ENV"] = "0"
            assert _env_float("_FLOX_TEST_ENV", 0.25, 0.0, 1.0, lo_open=True) == 0.25
            os.environ["_FLOX_TEST_ENV"] = "1.5"
            assert _env_float("_FLOX_TEST_ENV", 0.25, 0.0, 1.0, lo_open=True) == 0.25
            os.environ["_FLOX_TEST_ENV"] = "0.75"
            assert _env_float("_FLOX_TEST_ENV", 0.25, 0.0, 1.0, lo_open=True) == 0.75
            os.environ["_FLOX_TEST_ENV"] = "1.0"
            assert _env_float("_FLOX_TEST_ENV", 0.25, 0.0, 1.0, lo_open=True) == 1.0
        finally:
            del os.environ["_FLOX_TEST_ENV"]

    def test_every_option_has_env_mirror(self):
        # the static FLX010 contract, asserted at runtime too: re-importing
        # options with a mirror set must seed the field; invalid values fall
        # back (the cannot-seed-what-set_options-refuses contract)
        import importlib
        import flox_tpu.options as options_mod

        probes = {
            "FLOX_TPU_DEFAULT_ENGINE": ("default_engine", "numpy", "bogus"),
            "FLOX_TPU_QUANTILE_IMPL": ("quantile_impl", "select", "bogus"),
            "FLOX_TPU_MATMUL_NUM_GROUPS_MAX": ("matmul_num_groups_max", 77, "junk"),
            "FLOX_TPU_STREAM_DONATE": ("stream_donate", "off", "maybe"),
        }
        saved = {k: os.environ.get(k) for k in probes}
        try:
            for env, (field, good, _bad) in probes.items():
                os.environ[env] = str(good)
            mod = importlib.reload(options_mod)
            for env, (field, good, _bad) in probes.items():
                assert mod.OPTIONS[field] == good, field
            for env, (field, _good, bad) in probes.items():
                os.environ[env] = str(bad)
            defaults = {"default_engine": "jax", "quantile_impl": "auto",
                        "matmul_num_groups_max": 384, "stream_donate": "auto"}
            mod = importlib.reload(options_mod)
            for field, expected in defaults.items():
                assert mod.OPTIONS[field] == expected, field
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            importlib.reload(options_mod)


# ---------------------------------------------------------------------------
# the harness itself


class TestFaultHarness:
    def test_plan_is_deterministic(self, data):
        vals, labels = data
        logs = []
        for _ in range(2):
            with faults.inject(oom_at=[1400]) as plan:
                streaming_groupby_reduce(vals, labels, func="sum", batch_len=700)
            logs.append(list(plan.log))
        assert logs[0] == logs[1]
        assert ("SimulatedOOM", 1400, 2100) in logs[0]

    def test_inject_nests_and_restores(self):
        assert not faults.active()
        with faults.inject(kill_after=100):
            assert faults.active()
            with faults.inject(oom_at=[0]):
                assert faults.active()
            assert faults.active()
        assert not faults.active()

    def test_poke_noop_without_plan(self):
        faults.poke(0, 100)  # must not raise

    def test_counters_are_threadsafe_accumulators(self):
        c = StreamCounters()
        import threading

        def spin():
            for _ in range(1000):
                c.record_retry(0.001)

        ts = [threading.Thread(target=spin) for _ in range(4)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert c.retries == 4000
        assert abs(c.backoff_ms - 4000 * 1.0) < 1e-6
