"""Contract tests for the adapter's real-xarray branches (VERDICT r3 #4).

xarray cannot be installed in this environment (pip has no network; the
attempt fails resolving pypi.org — retried and still dead 2026-07-30,
round 5: ``pip install xarray`` and ``pip download xarray --no-deps``
both return "no matching distribution"), so the ``HAS_XARRAY`` branches of
``flox_tpu.xarray`` would otherwise never execute. This module installs a
mock ``xarray`` package implementing the EXACT API subset those branches
touch — method-delegate reductions with real-xarray signatures
(``obj.mean(dim=..., skipna=..., keep_attrs=...)``),
``Coordinates.from_pandas_multiindex``, and ``apply_ufunc``'s keyword
contract — forces ``HAS_XARRAY`` True, and runs the adapter end-to-end.
Every assertion here is a call-shape real xarray would enforce with a
TypeError, so a drifted kwarg or a dict-returning argmax surfaces as a
test failure instead of sailing through the xrlite binding.

Reference parity: xarray.py:303-322 (delegate reductions), 416-446
(apply_ufunc dispatch), 468-479 (MultiIndex coords).
"""

from __future__ import annotations

import sys
import types

import numpy as np
import pandas as pd
import pytest

import flox_tpu.xarray as fxr
from flox_tpu import xrlite

CALLS: dict[str, list] = {}


def _to_mock(da):
    if isinstance(da, xrlite.DataArray) and not isinstance(da, MockDataArray):
        m = MockDataArray.__new__(MockDataArray)
        # xrlite.DataArray is slotted; copy every slot up the MRO
        for cls in type(da).__mro__:
            for s in getattr(cls, "__slots__", ()):
                if hasattr(da, s):
                    object.__setattr__(m, s, getattr(da, s))
        return m
    return da


class MockCoordinates:
    """xr.Coordinates stand-in: only the classmethod the adapter calls."""

    def __init__(self, mapping):
        self.mapping = mapping

    @classmethod
    def from_pandas_multiindex(cls, midx, dim):
        assert isinstance(midx, pd.MultiIndex), (
            "real xarray's Coordinates.from_pandas_multiindex requires a "
            f"pandas.MultiIndex, got {type(midx)}"
        )
        CALLS.setdefault("from_pandas_multiindex", []).append(dim)
        return cls({dim: midx})


class MockDataArray(xrlite.DataArray):
    """xrlite array wearing real xarray's reduction-method surface."""

    def assign_coords(self, coords):
        if isinstance(coords, MockCoordinates):
            coords = coords.mapping
        return _to_mock(super().assign_coords(coords))

    def _delegate(self, base, dim, skipna, keep_attrs, **kw):
        CALLS.setdefault(base, []).append(
            {"dim": dim, "skipna": skipna, "keep_attrs": keep_attrs, **kw}
        )
        dims = [dim] if not isinstance(dim, (list, tuple)) else list(dim)
        axes = tuple(list(self.dims).index(d) for d in dims)
        data = np.asarray(self.data)
        if base in ("argmax", "argmin"):
            # real xarray returns a DICT for a sequence dim= — the adapter
            # must pass a scalar or the result type changes under it
            assert not isinstance(dim, (list, tuple)), (
                "argmax/argmin with a list dim returns a dict in real "
                "xarray; the adapter must pass a scalar dim"
            )
            fn = getattr(np, ("nan" + base) if skipna else base)
            out = fn(data, axis=axes[0])
        elif base == "quantile":
            q = kw.pop("q")
            out = (np.nanquantile if skipna else np.quantile)(data, q, axis=axes, **kw)
        elif base == "count":
            out = np.sum(~np.isnan(data), axis=axes)
        else:
            fn = getattr(np, ("nan" + base) if skipna else base)
            out = fn(data, axis=axes, **kw)
        out_dims = tuple(d for d in self.dims if d not in dims)
        return MockDataArray(
            out, dims=out_dims, name=self.name,
            attrs=dict(self.attrs) if keep_attrs else {},
        )


def _add_delegates():
    for base in ("sum", "mean", "max", "min", "prod", "var", "std", "median",
                 "quantile", "argmax", "argmin", "count"):
        def method(self, dim=None, *, skipna=None, keep_attrs=None,
                   _base=base, **kw):
            return self._delegate(_base, dim, skipna, keep_attrs, **kw)
        setattr(MockDataArray, base, method)


_add_delegates()


def _mock_apply_ufunc(func, *args, **kwargs):
    # pin the exact keyword contract the adapter relies on: real xarray
    # would TypeError on an unknown kwarg and behave differently without
    # join/dask set — drift here is what this test exists to catch
    CALLS.setdefault("apply_ufunc", []).append(set(kwargs))
    expected = {"input_core_dims", "output_core_dims", "dask", "keep_attrs",
                "vectorize", "join", "dataset_fill_value"}
    assert set(kwargs) == expected, (
        f"apply_ufunc called with {set(kwargs)} != real-xarray contract {expected}"
    )
    assert kwargs["dask"] == "forbidden"
    assert kwargs["join"] == "exact"
    assert kwargs["vectorize"] is False
    assert len(kwargs["input_core_dims"]) == len(args)
    out = xrlite.apply_ufunc(func, *args, **kwargs)
    return _to_mock(out)


def _build_mock_xarray():
    mod = types.ModuleType("xarray")
    mod.DataArray = MockDataArray
    mod.Dataset = xrlite.Dataset
    mod.broadcast = xrlite.broadcast
    mod.apply_ufunc = _mock_apply_ufunc
    mod.Coordinates = MockCoordinates
    return mod


@pytest.fixture()
def real_xr(monkeypatch):
    import flox_tpu.utils

    mod = _build_mock_xarray()
    monkeypatch.setitem(sys.modules, "xarray", mod)
    monkeypatch.setattr(flox_tpu.utils, "HAS_XARRAY", True)
    monkeypatch.setattr(fxr, "HAS_XARRAY", True)
    CALLS.clear()
    return mod


def test_get_xr_binds_to_installed_xarray(real_xr):
    assert fxr._get_xr() is real_xr


def test_plain_reduce_delegates_to_obj_method(real_xr):
    # reducing over a dim the groupers don't span: the adapter must call
    # obj.mean(dim=..., skipna=True, keep_attrs=...) — xarray.py:102-109
    rng = np.random.default_rng(0)
    data = rng.normal(size=(4, 10))
    data[0, 0] = np.nan
    obj = MockDataArray(data, dims=("x", "t"), name="v", attrs={"units": "K"})
    by = MockDataArray(np.array([0, 0, 1, 1]), dims=("x",), name="g")
    out = fxr.xarray_reduce(obj, by, func="nanmean", dim="t")
    assert CALLS["mean"] == [{"dim": ["t"], "skipna": True, "keep_attrs": True}]
    assert isinstance(out, MockDataArray)
    np.testing.assert_allclose(np.asarray(out.data), np.nanmean(data, axis=1))
    assert out.attrs == {"units": "K"}
    # skipna=False spelling: plain variant, no skipna kwarg injected
    fxr.xarray_reduce(obj, by, func="mean", dim="t", keep_attrs=False)
    assert CALLS["mean"][-1] == {"dim": ["t"], "skipna": None, "keep_attrs": False}


def test_plain_reduce_var_forwards_finalize_kwargs(real_xr):
    rng = np.random.default_rng(1)
    data = rng.normal(size=(4, 10))
    obj = MockDataArray(data, dims=("x", "t"))
    by = MockDataArray(np.array([0, 0, 1, 1]), dims=("x",), name="g")
    out = fxr.xarray_reduce(obj, by, func="var", dim="t", ddof=1)
    assert CALLS["var"] == [{"dim": ["t"], "skipna": None, "keep_attrs": True, "ddof": 1}]
    np.testing.assert_allclose(np.asarray(out.data), data.var(axis=1, ddof=1))


def test_plain_reduce_argmax_passes_scalar_dim(real_xr):
    rng = np.random.default_rng(2)
    data = rng.normal(size=(4, 10))
    obj = MockDataArray(data, dims=("x", "t"))
    by = MockDataArray(np.array([0, 0, 1, 1]), dims=("x",), name="g")
    out = fxr.xarray_reduce(obj, by, func="argmax", dim="t")
    assert CALLS["argmax"] == [{"dim": "t", "skipna": None, "keep_attrs": True}]
    np.testing.assert_array_equal(np.asarray(out.data), np.argmax(data, axis=1))


def test_grouped_path_uses_apply_ufunc_contract(real_xr):
    rng = np.random.default_rng(3)
    data = rng.normal(size=(3, 12))
    obj = MockDataArray(data, dims=("x", "t"), name="v")
    by = MockDataArray(np.arange(12) % 4, dims=("t",), name="g")
    out = fxr.xarray_reduce(obj, by, func="sum")
    assert len(CALLS["apply_ufunc"]) == 1
    oracle = np.stack([data[:, np.arange(12) % 4 == g].sum(-1) for g in range(4)], -1)
    np.testing.assert_allclose(np.asarray(out.data), oracle, rtol=1e-12)
    np.testing.assert_array_equal(np.asarray(out["g"].data), np.arange(4))


def test_multiindex_groups_use_coordinates_api(real_xr):
    # grouping by a MultiIndex-backed coord: the adapter must build the
    # coordinate via Coordinates.from_pandas_multiindex on real xarray
    # (modern xarray rejects a raw MultiIndex in assign_coords)
    mi = pd.MultiIndex.from_product([["a", "b"], [0, 1]], names=("letter", "num"))
    labels = mi.take(np.array([0, 1, 2, 3, 0, 1, 2, 3]))
    da = MockDataArray(
        np.arange(8.0), dims=("sample",), coords={"stacked": ("sample", labels)}
    )
    out = fxr.xarray_reduce(da, "stacked", func="sum")
    assert CALLS["from_pandas_multiindex"] == ["stacked"]
    groups = out["stacked"].data
    assert isinstance(groups, pd.MultiIndex)
    assert list(groups.names) == ["letter", "num"]
    np.testing.assert_allclose(np.asarray(out.data), [4.0, 6.0, 8.0, 10.0])


# ---------------------------------------------------------------------------
# high-value behaviors from xarray's own test_groupby.py (VERDICT r4 #7),
# asserted against BOTH the xrlite binding and the mock-real-xarray binding
# so neither backend can drift: groupby_bins labels, resample-shaped time
# groupers, and the Dataset attrs policy.
# ---------------------------------------------------------------------------


@pytest.fixture(params=["xrlite", "mock"])
def da_cls(request, monkeypatch):
    """DataArray class under the selected binding. 'xrlite' runs the
    bundled fallback (HAS_XARRAY False, the env default); 'mock' installs
    the real-xarray API mock."""
    if request.param == "mock":
        import flox_tpu.utils

        mod = _build_mock_xarray()
        monkeypatch.setitem(sys.modules, "xarray", mod)
        monkeypatch.setattr(flox_tpu.utils, "HAS_XARRAY", True)
        monkeypatch.setattr(fxr, "HAS_XARRAY", True)
        CALLS.clear()
        return MockDataArray
    return xrlite.DataArray


def test_groupby_bins_labels(da_cls):
    # xarray test_groupby.py::test_groupby_bins — the output dim is named
    # "{name}_bins" and its coordinate is the right-closed IntervalIndex
    # pd.cut would produce; out-of-range values fall outside every bin
    vals = da_cls(np.arange(10.0), dims=("x",), name="v")
    by = da_cls(
        np.array([1, 1, 2, 3, 4, 5, 6, 7, 8, 20], dtype=float),
        dims=("x",), name="g",
    )
    out = fxr.xarray_reduce(
        vals, by, func="sum", expected_groups=np.array([0, 3, 6, 10]),
        isbin=True, fill_value=0.0,
    )
    assert "g_bins" in out.dims
    groups = out["g_bins"].data
    assert isinstance(groups, pd.IntervalIndex)
    assert groups.closed == "right"
    np.testing.assert_array_equal(groups.left, [0, 3, 6])
    np.testing.assert_array_equal(groups.right, [3, 6, 10])
    # (0,3]: by 1,1,2,3 -> 0+1+2+3; (3,6]: 4,5,6 -> 4+5+6; (6,10]: 7,8
    # (the 20 falls outside every bin and must not contribute)
    np.testing.assert_allclose(np.asarray(out.data), [6.0, 15.0, 15.0])


def test_resample_shaped_time_grouper(da_cls):
    # xarray test_groupby.py::test_groupby_resample-shape: hourly data
    # grouped by its floor-to-day datetime labels — the result coordinate
    # carries the datetime64 day labels in order
    hours = np.arange(72, dtype="timedelta64[h]")
    times = np.datetime64("2001-01-01", "ns") + hours
    days = times.astype("datetime64[D]")
    obj = da_cls(np.arange(72.0), dims=("time",), name="v")
    by = da_cls(days, dims=("time",), name="date")
    out = fxr.xarray_reduce(obj, by, func="mean")
    groups = np.asarray(out["date"].data)
    np.testing.assert_array_equal(
        groups.astype("datetime64[D]"),
        np.array(["2001-01-01", "2001-01-02", "2001-01-03"], dtype="datetime64[D]"),
    )
    np.testing.assert_allclose(
        np.asarray(out.data),
        [np.arange(24).mean(), np.arange(24, 48).mean(), np.arange(48, 72).mean()],
    )


def test_dataset_attrs_policy(da_cls):
    # xarray's keep_attrs contract on Datasets: True keeps BOTH the
    # Dataset attrs and each variable's attrs; False drops both
    a = da_cls(np.arange(8.0), dims=("x",), name="a", attrs={"units": "K"})
    b = da_cls(np.arange(8.0) * 2, dims=("x",), name="b", attrs={"units": "m"})
    ds = xrlite.Dataset({"a": a, "b": b}, attrs={"title": "t0"})
    by = da_cls(np.arange(8) % 2, dims=("x",), name="g")

    kept = fxr.xarray_reduce(ds, by, func="sum", keep_attrs=True)
    assert kept.attrs == {"title": "t0"}
    assert kept["a"].attrs == {"units": "K"}
    assert kept["b"].attrs == {"units": "m"}
    np.testing.assert_allclose(np.asarray(kept["a"].data), [12.0, 16.0])

    dropped = fxr.xarray_reduce(ds, by, func="sum", keep_attrs=False)
    assert dropped.attrs in ({}, None) or not dropped.attrs
    assert not dropped["a"].attrs
