"""Property-based invariants (reference: tests/test_properties.py:99-332).

Invariants:
* single-group groupby == the plain numpy reduction (reference :99-178)
* jax engine == numpy engine on identical data (the reference's
  chunked==eager analogue, :187-219)
* first/last on reversed data == last/first (reference :295-332)
* ffill/bfill reversal symmetry (reference :269-287)
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st
from hypothesis.extra.numpy import arrays

from flox_tpu.core import groupby_reduce
from flox_tpu.scan import groupby_scan

SIMPLE_FUNCS = ["sum", "nansum", "mean", "nanmean", "max", "nanmax", "min", "nanmin",
                "var", "nanvar", "count", "first", "last", "nanfirst", "nanlast"]

# bounded floats so sums cannot overflow (reference's not_overflowing_array,
# test_properties.py:67-90)
ELEMENTS = st.floats(
    min_value=-1e6, max_value=1e6, allow_nan=False, allow_infinity=False, width=64
)
ELEMENTS_NAN = st.one_of(ELEMENTS, st.just(np.nan))


@st.composite
def array_and_labels(draw, with_nan=False):
    n = draw(st.integers(min_value=1, max_value=40))
    vals = draw(arrays(np.float64, (n,), elements=ELEMENTS_NAN if with_nan else ELEMENTS))
    nlabels = draw(st.integers(min_value=1, max_value=5))
    labels = draw(arrays(np.int64, (n,), elements=st.integers(0, nlabels - 1)))
    return vals, labels


@settings(max_examples=50, deadline=None)
@given(data=array_and_labels(), func=st.sampled_from(SIMPLE_FUNCS))
def test_single_group_equals_numpy(data, func):
    vals, _ = data
    labels = np.zeros(len(vals), dtype=np.int64)
    result, _ = groupby_reduce(vals, labels, func=func, engine="numpy")
    oracle = {
        "sum": np.sum, "nansum": np.nansum, "mean": np.mean, "nanmean": np.nanmean,
        "max": np.max, "nanmax": np.nanmax, "min": np.min, "nanmin": np.nanmin,
        "var": np.var, "nanvar": np.nanvar,
        "count": lambda x: np.sum(~np.isnan(x)),
        "first": lambda x: x[0], "last": lambda x: x[-1],
        "nanfirst": lambda x: x[0], "nanlast": lambda x: x[-1],
    }[func]
    with np.errstate(invalid="ignore"), np.testing.suppress_warnings() as sup:
        sup.filter(RuntimeWarning)
        expected = oracle(vals)
    # atol covers shifted-two-pass rounding residue for var of near-constant
    # data (|x|<=1e6 -> dev^2 residue <= ~1e-8); not a correctness deviation
    np.testing.assert_allclose(
        np.asarray(result).astype(float)[0], float(expected),
        rtol=1e-9, atol=1e-7, equal_nan=True,
    )


@settings(max_examples=50, deadline=None)
@given(data=array_and_labels(with_nan=True), func=st.sampled_from(SIMPLE_FUNCS))
def test_engines_agree(data, func):
    vals, labels = data
    a, _ = groupby_reduce(vals, labels, func=func, engine="jax")
    b, _ = groupby_reduce(vals, labels, func=func, engine="numpy")
    np.testing.assert_allclose(
        np.asarray(a).astype(float), np.asarray(b).astype(float),
        rtol=1e-10, atol=1e-10, equal_nan=True,
    )


@settings(max_examples=40, deadline=None)
@given(data=array_and_labels(with_nan=True))
def test_first_last_reversal_duality(data):
    vals, labels = data
    f, gf = groupby_reduce(vals, labels, func="nanfirst", engine="numpy")
    l, gl = groupby_reduce(vals[::-1], labels[::-1], func="nanlast", engine="numpy")
    np.testing.assert_array_equal(gf, gl)
    np.testing.assert_allclose(np.asarray(f), np.asarray(l), equal_nan=True)


@settings(max_examples=40, deadline=None)
@given(data=array_and_labels(with_nan=True))
def test_ffill_bfill_reversal(data):
    vals, labels = data
    b = np.asarray(groupby_scan(vals, labels, func="bfill", engine="numpy"))
    f_rev = np.asarray(
        groupby_scan(vals[::-1], labels[::-1], func="ffill", engine="numpy")
    )[::-1]
    np.testing.assert_allclose(b, f_rev, equal_nan=True)


@settings(max_examples=30, deadline=None)
@given(data=array_and_labels())
def test_cumsum_last_equals_sum(data):
    vals, labels = data
    scanned = np.asarray(groupby_scan(vals, labels, func="cumsum", engine="numpy"))
    total, groups = groupby_reduce(vals, labels, func="sum", engine="numpy")
    for i, g in enumerate(groups):
        sel = np.flatnonzero(labels == g)
        np.testing.assert_allclose(scanned[sel[-1]], np.asarray(total)[i], rtol=1e-12)


@settings(max_examples=40, deadline=None)
@given(
    data=array_and_labels(with_nan=True),
    q=st.floats(min_value=0.0, max_value=1.0),
    method=st.sampled_from(["linear", "lower", "higher", "nearest", "midpoint"]),
)
def test_radix_select_equals_sort(data, q, method):
    # the sort-free order-statistics lowering is bit-identical to the
    # two-key-sort path on ARBITRARY data (duplicates, NaN mixes, tiny
    # groups, extreme q) — both compute exact order statistics
    import flox_tpu

    vals, labels = data
    # engine='jax' explicitly: small host arrays would otherwise route to
    # the numpy engine, which has no quantile_impl knob — the comparison
    # must exercise the jax select lowering, not compare numpy to itself
    ref, _ = groupby_reduce(
        vals, labels, func="nanquantile", engine="jax",
        finalize_kwargs={"q": q, "method": method},
    )
    with flox_tpu.set_options(quantile_impl="select"):
        got, _ = groupby_reduce(
            vals, labels, func="nanquantile", engine="jax",
            finalize_kwargs={"q": q, "method": method},
        )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


@settings(max_examples=40, deadline=None)
@given(
    data=array_and_labels(with_nan=True),
    func=st.sampled_from(SIMPLE_FUNCS + ["nanmedian", "median"]),
    batch_len=st.integers(min_value=1, max_value=17),
)
def test_streaming_equals_eager_property(data, func, batch_len):
    # the streaming runtime (including the counts-only streaming quantile)
    # must equal eager for ANY slab size, label layout, and NaN pattern
    from flox_tpu.streaming import streaming_groupby_reduce

    vals, labels = data
    ref, g1 = groupby_reduce(vals, labels, func=func)
    got, g2 = streaming_groupby_reduce(vals, labels, func=func, batch_len=batch_len)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
    np.testing.assert_allclose(
        np.asarray(got).astype(float), np.asarray(ref).astype(float),
        rtol=1e-9, atol=1e-9, equal_nan=True,
    )


@settings(max_examples=40, deadline=None)
@given(
    data=array_and_labels(with_nan=True),
    func=st.sampled_from(["cumsum", "nancumsum", "ffill", "bfill"]),
    batch_len=st.integers(min_value=1, max_value=17),
)
def test_streaming_scan_equals_eager_property(data, func, batch_len):
    # the cross-slab carry must reproduce the eager scan for ANY slab
    # boundary placement (carries crossing mid-group, empty slabs for a
    # group, bfill's reverse order)
    from flox_tpu.streaming import streaming_groupby_scan

    vals, labels = data
    ref = groupby_scan(vals, labels, func=func)
    got = streaming_groupby_scan(vals, labels, func=func, batch_len=batch_len)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=1e-9, atol=1e-9, equal_nan=True
    )
