"""Serving-layer test suite (ISSUE 7).

The contracts under test:

* **coalescing** — K concurrent identical-key requests trigger exactly ONE
  device dispatch (``serve.dispatches`` counter) and all K receive correct
  results;
* **micro-batching** — program-compatible different-payload requests stack
  into one dispatch whose per-row results are bit-identical to solo runs;
* **concurrency correctness** — N concurrent requests with mixed option
  scopes produce bit-identical results to the same requests run
  sequentially;
* **admission control** — submits beyond ``serve_queue_depth`` are
  load-shed without queueing; deadline-expired requests are cancelled
  without poisoning the queue (an all-expired batch is never dispatched);
* **option scoping** — ``options.scoped`` overlays are per-context
  (asyncio tasks and threads isolated), nest innermost-wins, leave the
  process-global OPTIONS untouched, and carry ``explicitly_set``
  provenance;
* **LRU program caches** — ``_PROGRAM_CACHE`` / ``_STEP_CACHE`` evict one
  stale entry past capacity (never the whole hot set) with the eviction
  count visible in ``cache.stats()``;
* **AOT persistence** — ``record_reduce`` -> manifest -> ``warmup``
  round-trips, and a restarted process pointed at a warm dir serves its
  first request with ``jax.compiles == 0`` (the two-process smoke, via
  the ``python -m flox_tpu.serve`` JSON-lines protocol).
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import flox_tpu
from flox_tpu import cache, serve
from flox_tpu.cache import LRUCache
from flox_tpu.core import groupby_reduce
from flox_tpu.options import OPTIONS, explicitly_set, scoped, set_options
from flox_tpu.serve import (
    AggregationRequest,
    DeadlineExceededError,
    Dispatcher,
    LoadShedError,
    aot,
)
from flox_tpu.telemetry import METRICS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    """Serving state and counters reset per test; AOT persistence off so
    dispatch tests never touch disk (the AOT tests opt in per-test), and
    the autotuner pinned off so a mid-test decision flip cannot break the
    sequential-vs-concurrent bit-identity assertions under the CI
    FLOX_TPU_AUTOTUNE=1 leg."""
    with flox_tpu.set_options(serve_aot_dir=None, autotune=False):
        cache.clear_all()
        yield
        cache.clear_all()
        # jax's cache dir is process-global: detach it so tests after the
        # AOT ones don't keep writing executables into a dead tmp dir
        aot.deconfigure()


def run(coro):
    return asyncio.run(coro)


def _payload(n=64, ngroups=5, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    values = rng.normal(size=n).astype(dtype)
    labels = rng.integers(0, ngroups, size=n)
    return values, labels


class TestCoalescing:
    def test_identical_requests_one_dispatch_all_correct(self):
        """Acceptance: K concurrent identical-key requests -> exactly one
        device dispatch, K correct results."""
        values, labels = _payload()
        expect, egroups = groupby_reduce(values, labels, func="sum")
        K = 8

        async def main():
            d = Dispatcher()
            before = METRICS.get("serve.dispatches")
            results = await asyncio.gather(
                *[d.submit(func="sum", array=values, by=labels) for _ in range(K)]
            )
            await d.close()
            return results, METRICS.get("serve.dispatches") - before

        results, dispatches = run(main())
        assert dispatches == 1
        for r in results:
            np.testing.assert_array_equal(r.result, np.asarray(expect))
            np.testing.assert_array_equal(r.groups, np.asarray(egroups))
        # first arrival created the leaf; the other K-1 attached to it
        assert sorted(r.coalesced for r in results) == [False] + [True] * (K - 1)
        assert METRICS.get("serve.coalesced") == K - 1
        # a waiter attaching to an in-flight leaf waited 0, never negative
        assert all(r.queue_ms >= 0 for r in results)

    def test_different_payloads_do_not_coalesce(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher(microbatch_max=1)  # isolate coalescing from batching
            before = METRICS.get("serve.dispatches")
            await asyncio.gather(
                d.submit(func="sum", array=values, by=labels),
                d.submit(func="sum", array=values + 1.0, by=labels),
            )
            await d.close()
            return METRICS.get("serve.dispatches") - before

        assert run(main()) == 2

    def test_different_option_scopes_do_not_coalesce(self):
        """A pinned knob changes the compiled program: requests only share
        a dispatch when their execution-relevant options agree."""
        values, labels = _payload()

        async def main():
            d = Dispatcher(microbatch_max=1)
            before = METRICS.get("serve.dispatches")
            results = await asyncio.gather(
                d.submit(func="sum", array=values, by=labels),
                d.submit(
                    func="sum", array=values, by=labels,
                    options={"default_engine": "numpy"},
                ),
            )
            await d.close()
            return results, METRICS.get("serve.dispatches") - before

        results, dispatches = run(main())
        assert dispatches == 2
        np.testing.assert_allclose(results[0].result, results[1].result)

    def test_ambient_scope_is_part_of_the_program_key(self):
        """A submit made under an ambient options.scoped() must not share
        a dispatch with an unscoped identical request: ambient knobs like
        default_engine change results without appearing in the request's
        own overlay."""
        values, labels = _payload()

        async def main():
            d = Dispatcher(microbatch_max=1)
            before = METRICS.get("serve.dispatches")

            async def scoped_submit():
                with scoped(default_engine="numpy"):
                    return await d.submit(func="sum", array=values, by=labels)

            results = await asyncio.gather(
                scoped_submit(), d.submit(func="sum", array=values, by=labels)
            )
            await d.close()
            return results, METRICS.get("serve.dispatches") - before

        results, dispatches = run(main())
        assert dispatches == 2
        np.testing.assert_allclose(results[0].result, results[1].result)

    def test_execution_error_fans_out_to_every_waiter(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher()
            results = await asyncio.gather(
                *[
                    d.submit(func="definitely-not-a-reduction", array=values, by=labels)
                    for _ in range(3)
                ],
                return_exceptions=True,
            )
            await d.close()
            return results

        results = run(main())
        assert len(results) == 3
        assert all(isinstance(r, Exception) for r in results)
        assert METRICS.get("serve.errors") == 1  # one failed dispatch, 3 waiters


class TestMicroBatching:
    def test_batched_rows_bit_identical_to_solo(self):
        values, labels = _payload()
        payloads = [values + i for i in range(4)]
        solo = [np.asarray(groupby_reduce(p, labels, func="sum")[0]) for p in payloads]

        async def main():
            d = Dispatcher(batch_window=0.05)
            before = METRICS.get("serve.dispatches")
            results = await asyncio.gather(
                *[d.submit(func="sum", array=p, by=labels) for p in payloads]
            )
            await d.close()
            return results, METRICS.get("serve.dispatches") - before

        results, dispatches = run(main())
        assert dispatches == 1
        assert [r.batch_size for r in results] == [4, 4, 4, 4]
        for r, expect in zip(results, solo):
            np.testing.assert_array_equal(r.result, expect)

    def test_batch_respects_microbatch_max(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher(microbatch_max=2, batch_window=0.05)
            before = METRICS.get("serve.dispatches")
            await asyncio.gather(
                *[d.submit(func="sum", array=values + i, by=labels) for i in range(4)]
            )
            await d.close()
            return METRICS.get("serve.dispatches") - before

        assert run(main()) == 2

    def test_oversized_and_unbatchable_dispatch_alone(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher(batch_window=0.05)
            with set_options(serve_microbatch_max_elems=8):
                big = await asyncio.gather(
                    d.submit(func="sum", array=values, by=labels),
                    d.submit(func="sum", array=values + 1, by=labels),
                )
            quant = await asyncio.gather(
                d.submit(func="quantile", array=values, by=labels,
                         finalize_kwargs={"q": 0.5}),
                d.submit(func="quantile", array=values + 1, by=labels,
                         finalize_kwargs={"q": 0.5}),
            )
            await d.close()
            return big, quant

        big, quant = run(main())
        assert [r.batch_size for r in big] == [1, 1]
        assert [r.batch_size for r in quant] == [1, 1]
        expect = np.asarray(
            groupby_reduce(values, labels, func="quantile", finalize_kwargs={"q": 0.5})[0]
        )
        np.testing.assert_array_equal(quant[0].result, expect)


class TestConcurrencyCorrectness:
    def test_mixed_scopes_concurrent_equals_sequential(self):
        """N concurrent requests with mixed option scopes == the same
        requests run sequentially, bit for bit."""
        requests = []
        for i in range(12):
            values, labels = _payload(seed=i, ngroups=3 + i % 4)
            requests.append(
                AggregationRequest(
                    func=["sum", "nanmean", "max", "prod"][i % 4],
                    array=values,
                    by=labels,
                    options=(
                        {} if i % 3 == 0
                        else {"default_engine": ["numpy", "jax"][i % 2]}
                    ),
                )
            )

        sequential = []
        for req in requests:
            with scoped(**req.options):
                result, groups = groupby_reduce(req.array, req.by, func=req.func)
            sequential.append((np.asarray(result), np.asarray(groups)))

        async def main():
            d = Dispatcher()
            out = await asyncio.gather(*[d.submit(req) for req in requests])
            await d.close()
            return out

        for served, (expect, egroups) in zip(run(main()), sequential):
            np.testing.assert_array_equal(served.result, expect)
            np.testing.assert_array_equal(served.groups, egroups)

    def test_pending_registry_empties_after_serving(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher()
            await asyncio.gather(
                *[d.submit(func="sum", array=values + i, by=labels) for i in range(4)]
            )
            await d.close()

        run(main())
        stats = cache.stats()
        assert stats["serve_pending"] == 0
        assert stats["serve_coalesce"] == 0
        assert stats["serve_batches"] == 0


class TestAdmissionControl:
    def test_load_shed_beyond_queue_depth(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher(queue_depth=2, batch_window=0.05)
            results = await asyncio.gather(
                *[d.submit(func="sum", array=values + i, by=labels) for i in range(5)],
                return_exceptions=True,
            )
            await d.close()
            return results

        results = run(main())
        shed = [r for r in results if isinstance(r, LoadShedError)]
        served = [r for r in results if not isinstance(r, Exception)]
        assert len(shed) == 3 and len(served) == 2
        assert METRICS.get("serve.shed") == 3

    def test_queue_depth_zero_disables_admission_control(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher(queue_depth=0)
            results = await asyncio.gather(
                *[d.submit(func="sum", array=values + i, by=labels) for i in range(8)]
            )
            await d.close()
            return results

        assert len(run(main())) == 8

    def test_expired_request_cancelled_without_poisoning_queue(self):
        """A deadline that expires while queued raises DeadlineExceededError
        for that waiter; an all-expired batch is abandoned (never
        dispatched); subsequent requests on the same dispatcher serve
        normally."""
        values, labels = _payload()
        expect = np.asarray(groupby_reduce(values, labels, func="sum")[0])

        async def main():
            d = Dispatcher(batch_window=0.2)
            before = METRICS.get("serve.dispatches")
            with pytest.raises(DeadlineExceededError):
                await d.submit(func="sum", array=values, by=labels, deadline=0.01)
            await d.close()  # the abandoned batch's window elapses
            abandoned_dispatches = METRICS.get("serve.dispatches") - before
            after = await d.submit(func="sum", array=values, by=labels)
            await d.close()
            return abandoned_dispatches, after

        abandoned_dispatches, after = run(main())
        assert abandoned_dispatches == 0
        assert METRICS.get("serve.batches_abandoned") == 1
        assert METRICS.get("serve.deadline_exceeded") == 1
        np.testing.assert_array_equal(after.result, expect)
        assert cache.stats()["serve_pending"] == 0

    def test_one_expired_waiter_does_not_cancel_peers(self):
        """A coalesced waiter timing out must not cancel the shared leaf:
        the surviving waiter still gets its result."""
        values, labels = _payload()
        expect = np.asarray(groupby_reduce(values, labels, func="sum")[0])

        async def main():
            d = Dispatcher(batch_window=0.15)
            patient = asyncio.create_task(
                d.submit(func="sum", array=values, by=labels)
            )
            await asyncio.sleep(0)  # let the leaf enqueue
            with pytest.raises(DeadlineExceededError):
                await d.submit(func="sum", array=values, by=labels, deadline=0.01)
            result = await patient
            await d.close()
            return result

        result = run(main())
        np.testing.assert_array_equal(result.result, expect)


class TestScopedOptions:
    def test_overlay_reads_and_restores(self):
        base = OPTIONS["default_engine"]
        with scoped(default_engine="numpy"):
            assert OPTIONS["default_engine"] == "numpy"
            assert OPTIONS.get("default_engine") == "numpy"
        assert OPTIONS["default_engine"] == base

    def test_nested_scopes_innermost_wins(self):
        with scoped(default_engine="numpy", telemetry=True):
            with scoped(default_engine="jax"):
                assert OPTIONS["default_engine"] == "jax"
                assert OPTIONS["telemetry"] is True  # outer overlay visible
            assert OPTIONS["default_engine"] == "numpy"

    def test_validation_at_entry(self):
        with pytest.raises(ValueError):
            scoped(default_engine="fortran")
        with pytest.raises(ValueError):
            scoped(not_an_option=1)

    def test_explicitly_set_respects_scope(self):
        if "FLOX_TPU_STREAM_PREFETCH" in os.environ:
            pytest.skip("depth pinned by the environment")
        assert not explicitly_set("stream_prefetch")
        with scoped(stream_prefetch=3):
            assert explicitly_set("stream_prefetch")
        assert not explicitly_set("stream_prefetch")

    def test_set_options_inside_scope_restores_global_base(self):
        """set_options under an active scope snapshots the GLOBAL value:
        the overlay must never leak into the process dict on exit."""
        base = OPTIONS["stream_prefetch"]
        with scoped(stream_prefetch=7):
            with set_options(stream_prefetch=5):
                # scope overlay still wins reads inside the scope
                assert OPTIONS["stream_prefetch"] == 7
            assert dict.__getitem__(OPTIONS, "stream_prefetch") == base
        assert OPTIONS["stream_prefetch"] == base

    def test_threads_start_unscoped(self):
        seen = {}

        def worker():
            seen["engine"] = OPTIONS["default_engine"]

        with scoped(default_engine="numpy"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["engine"] == dict.__getitem__(OPTIONS, "default_engine")

    def test_asyncio_tasks_inherit_creating_scope(self):
        async def probe():
            return OPTIONS["default_engine"]

        async def main():
            with scoped(default_engine="numpy"):
                inside = asyncio.create_task(probe())
            outside = asyncio.create_task(probe())
            return await inside, await outside

        inside, outside = run(main())
        assert inside == "numpy"
        assert outside == dict.__getitem__(OPTIONS, "default_engine")

    def test_concurrent_scopes_isolated(self):
        async def hold(engine, barrier):
            with scoped(default_engine=engine):
                await barrier.wait()
                return OPTIONS["default_engine"]

        async def main():
            barrier = asyncio.Event()
            tasks = [
                asyncio.create_task(hold("numpy", barrier)),
                asyncio.create_task(hold("jax", barrier)),
            ]
            await asyncio.sleep(0)
            barrier.set()
            return await asyncio.gather(*tasks)

        assert run(main()) == ["numpy", "jax"]


class TestLRUProgramCaches:
    def test_lru_evicts_one_stale_entry(self):
        lru = LRUCache(maxsize=3)
        for i in range(3):
            lru[i] = f"p{i}"
        assert lru.get(0) == "p0"  # renew 0: now 1 is the stalest
        lru[3] = "p3"
        assert lru.evictions == 1
        assert 1 not in lru
        assert set(lru.keys()) == {0, 2, 3}
        assert len(lru) == 3

    def test_lru_mapping_surface(self):
        lru = LRUCache(maxsize=4)
        lru["a"] = 1
        assert lru["a"] == 1 and "a" in lru
        assert lru.get("missing", 7) == 7
        assert lru.items() == [("a", 1)] and lru.values() == [1]
        assert lru.pop("a") == 1 and lru.pop("a", None) is None
        with pytest.raises(KeyError):
            lru["gone"]
        with pytest.raises(ValueError):
            LRUCache(maxsize=0)

    def test_program_caches_are_lru_with_stats_counter(self):
        from flox_tpu.parallel.mapreduce import _PROGRAM_CACHE
        from flox_tpu.streaming import _STEP_CACHE

        from flox_tpu.fusion import _FUSED_PROGRAM_CACHE

        assert isinstance(_PROGRAM_CACHE, LRUCache)
        assert isinstance(_STEP_CACHE, LRUCache)
        assert isinstance(_FUSED_PROGRAM_CACHE, LRUCache)
        stats = cache.stats()
        assert stats["evictions"] == {
            "mesh_programs": 0, "stream_steps": 0, "fused_programs": 0
        }
        # sustained mixed traffic past capacity: hot key survives because
        # every get() renews it — the old clear() dropped it 4 times here
        _STEP_CACHE["hot"] = "hot-program"
        for i in range(_STEP_CACHE.maxsize + 4):
            _STEP_CACHE[("cold", i)] = i
            assert _STEP_CACHE.get("hot") == "hot-program"
        assert _STEP_CACHE.evictions == 5
        assert cache.stats()["evictions"]["stream_steps"] == 5

    def test_clear_all_resets_serve_tables(self):
        from flox_tpu.serve.aot import _MANIFEST_MEMO
        from flox_tpu.serve.dispatcher import _COALESCE_CACHE, _PENDING_REGISTRY

        _MANIFEST_MEMO["d"] = {"func": "sum"}
        _PENDING_REGISTRY[99] = object()
        _COALESCE_CACHE[("k",)] = object()
        cache.clear_all()
        assert not _MANIFEST_MEMO and not _PENDING_REGISTRY and not _COALESCE_CACHE
        stats = cache.stats()
        for key in ("serve_pending", "serve_coalesce", "serve_batches",
                    "serve_aot_manifest"):
            assert stats[key] == 0


class TestAOT:
    def test_record_reduce_roundtrips_through_manifest(self, tmp_path):
        with set_options(serve_aot_dir=str(tmp_path)):
            recorded = aot.record_reduce(
                func="sum", shape=(8,), dtype="float64", by_shape=(8,),
                by_dtype="int64", ngroups=2, agg_kwargs={"fill_value": None},
                options={},
            )
            assert recorded
            # duplicate spec: memoized, not re-recorded
            assert not aot.record_reduce(
                func="sum", shape=(8,), dtype="float64", by_shape=(8,),
                by_dtype="int64", ngroups=2, agg_kwargs={"fill_value": None},
                options={},
            )
            payload = json.loads((tmp_path / "manifest.json").read_text())
            assert payload["version"] == 1 and len(payload["programs"]) == 1
            cache.clear_all()  # fresh "process": empty memo
            assert aot.warmup() == 1
            assert cache.stats()["serve_aot_manifest"] == 1

    def test_unreplayable_specs_are_skipped(self, tmp_path):
        with set_options(serve_aot_dir=str(tmp_path)):
            assert not aot.record_reduce(
                func=lambda x: x, shape=(4,), dtype="float64", by_shape=(4,),
                by_dtype="int64", ngroups=1, agg_kwargs={}, options={},
            )
            assert not aot.record_reduce(
                func="sum", shape=(4,), dtype="float64", by_shape=(4,),
                by_dtype="int64", ngroups=1,
                agg_kwargs={"finalize_kwargs": {"fn": lambda x: x}}, options={},
            )
        # and with persistence off, recording is a no-op entirely
        assert not aot.record_reduce(
            func="sum", shape=(4,), dtype="float64", by_shape=(4,),
            by_dtype="int64", ngroups=1, agg_kwargs={}, options={},
        )

    def test_corrupt_manifest_warns_and_serves(self, tmp_path, caplog):
        (tmp_path / "manifest.json").write_text("{not json")
        with set_options(serve_aot_dir=str(tmp_path)):
            assert aot.warmup() == 0
            assert any("unreadable AOT manifest" in r.message for r in caplog.records)
            # a corrupt manifest must not block NEW recordings either
            assert aot.record_reduce(
                func="sum", shape=(4,), dtype="float64", by_shape=(4,),
                by_dtype="int64", ngroups=1, agg_kwargs={}, options={},
            )

    def test_manifest_save_merges_across_processes(self, tmp_path):
        """Two replicas sharing one AOT dir union their manifests: a save
        from a process that never loaded must not clobber the other's."""
        with set_options(serve_aot_dir=str(tmp_path)):
            aot.record_reduce(
                func="sum", shape=(8,), dtype="float64", by_shape=(8,),
                by_dtype="int64", ngroups=2, agg_kwargs={}, options={},
            )
            cache.clear_all()  # fresh "process" with an empty memo
            aot.record_reduce(
                func="max", shape=(16,), dtype="float32", by_shape=(16,),
                by_dtype="int64", ngroups=4, agg_kwargs={}, options={},
            )
            payload = json.loads((tmp_path / "manifest.json").read_text())
            funcs = {spec["func"] for spec in payload["programs"].values()}
            assert funcs == {"sum", "max"}

    def test_dispatcher_records_served_programs(self, tmp_path):
        values, labels = _payload()
        with set_options(serve_aot_dir=str(tmp_path)):
            async def main():
                d = Dispatcher()
                await d.submit(func="sum", array=values, by=labels)
                await d.close()

            run(main())
            payload = json.loads((tmp_path / "manifest.json").read_text())
            (spec,) = payload["programs"].values()
            assert spec["func"] == "sum"
            assert tuple(spec["shape"]) == values.shape
            assert spec["ngroups"] == len(np.unique(labels))

    @pytest.mark.slow
    def test_two_process_smoke_warm_restart_zero_compiles(self, tmp_path):
        """The acceptance criterion, via the JSON-lines protocol: process
        A compiles and persists; process B restarts against the same dir,
        warms up, and serves its first request with jax.compiles == 0."""
        outs = _run_serve_cli(tmp_path)
        assert outs["a"]["response"]["ok"], outs["a"]
        assert outs["a"]["stats"]["counters"]["jax.compiles"] >= 1
        assert outs["b"]["warmup"]["compiles"] == 0
        assert outs["b"]["response"]["ok"], outs["b"]
        assert outs["b"]["stats"]["counters"]["jax.compiles"] == 0
        assert outs["b"]["response"]["result"] == outs["a"]["response"]["result"]


def _run_serve_cli(tmp_path):
    """Drive ``python -m flox_tpu.serve`` twice against one AOT dir."""
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", FLOX_TPU_TELEMETRY="1",
    )
    env.pop("FLOX_TPU_TELEMETRY_EXPORT_PATH", None)
    lines = "\n".join(
        [
            json.dumps(
                {
                    "id": "r", "func": "sum",
                    "array": [1.0, 2.0, 4.0, 8.0], "by": [0, 0, 1, 1],
                }
            ),
            json.dumps({"op": "drain"}),
            json.dumps({"op": "stats"}),
        ]
    )
    outs = {}
    for name, extra in (("a", []), ("b", ["--warmup"])):
        proc = subprocess.run(
            [sys.executable, "-m", "flox_tpu.serve",
             "--aot-dir", str(tmp_path), *extra],
            input=lines, cwd=REPO, env=env,
            capture_output=True, text=True, timeout=240,
        )
        assert proc.returncode == 0, proc.stderr
        records = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
        out = {}
        for rec in records:
            if "warmed" in rec:
                out["warmup"] = rec
            elif rec.get("op") == "stats":
                out["stats"] = rec
            elif rec.get("id") == "r":
                out["response"] = rec
        outs[name] = out
    return outs


class TestProtocol:
    def test_jsonl_loop_serves_and_reports_errors(self, tmp_path):
        script = tmp_path / "requests.jsonl"
        script.write_text(
            "\n".join(
                [
                    json.dumps(
                        {"id": "ok", "func": "sum",
                         "array": [1.0, 2.0, 4.0], "by": [0, 1, 1]}
                    ),
                    json.dumps(
                        {"id": "exec", "func": "no_such_agg",
                         "array": [1.0, 2.0], "by": [0, 1]}
                    ),
                    "this is not json",
                    json.dumps({"id": "bad", "func": "sum", "bogus_field": 1}),
                    json.dumps({"op": "nonsense"}),
                    json.dumps({"op": "drain"}),
                    json.dumps({"op": "stats"}),
                ]
            )
            + "\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # the CI telemetry leg exports to a shared jsonl: keep this
        # subprocess out of it (two writers would interleave mid-line)
        env.pop("FLOX_TPU_TELEMETRY", None)
        env.pop("FLOX_TPU_TELEMETRY_EXPORT_PATH", None)
        proc = subprocess.run(
            [sys.executable, "-m", "flox_tpu.serve", "--input", str(script)],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=240,
        )
        assert proc.returncode == 0, proc.stderr
        records = {
            rec.get("id", rec.get("op")): rec
            for rec in (json.loads(l) for l in proc.stdout.splitlines() if l.strip())
        }
        assert records["ok"]["ok"] and records["ok"]["result"] == [1.0, 6.0]
        # a well-formed envelope whose EXECUTION fails reports the real
        # exception class, never "protocol" (that would send clients
        # debugging their JSON instead of their aggregation)
        assert not records["exec"]["ok"]
        assert records["exec"]["error"] != "protocol"
        assert records["line-3"]["error"] == "protocol"  # malformed JSON
        assert records["bad"]["error"] == "protocol"
        assert "bogus_field" in records["bad"]["message"]
        assert records["line-5"]["error"] == "protocol"  # unknown op
        assert records["drain"]["ok"]
        # the well-formed requests reached the dispatcher; the protocol
        # failures were rejected before admission
        assert records["stats"]["counters"]["serve.requests"] == 2
        assert records["stats"]["cache"]["serve_pending"] == 0

    def test_traceparent_propagates_and_echoes_over_protocol(self, tmp_path):
        """Fleet trace propagation (ISSUE 13): a request carrying a W3C
        traceparent is answered with the SAME trace id and a fresh parent
        span for this hop; requests without one gain no new fields."""
        trace32, span16 = "ab" * 16, "cd" * 8
        script = tmp_path / "requests.jsonl"
        script.write_text(
            "\n".join(
                [
                    json.dumps(
                        {"id": "traced", "func": "sum",
                         "array": [1.0, 2.0, 4.0], "by": [0, 1, 1],
                         "traceparent": f"00-{trace32}-{span16}-01"}
                    ),
                    json.dumps(
                        {"id": "plain", "func": "sum",
                         "array": [1.0, 2.0, 8.0], "by": [0, 1, 1]}
                    ),
                    json.dumps({"op": "drain"}),
                ]
            )
            + "\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", FLOX_TPU_TELEMETRY="1")
        env.pop("FLOX_TPU_TELEMETRY_EXPORT_PATH", None)
        proc = subprocess.run(
            [sys.executable, "-m", "flox_tpu.serve", "--input", str(script),
             "--replica-id", "rep-a"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=240,
        )
        assert proc.returncode == 0, proc.stderr
        records = {
            rec.get("id", rec.get("op")): rec
            for rec in (json.loads(l) for l in proc.stdout.splitlines() if l.strip())
        }
        traced = records["traced"]
        assert traced["ok"] and traced["trace_id"] == trace32
        echoed = traced["traceparent"].split("-")
        assert echoed[0] == "00" and echoed[1] == trace32
        assert echoed[2] != span16  # this replica's hop, not the caller's
        assert "traceparent" not in records["plain"]
