"""Regression intents ported from the reference's tests/test_core.py.

Each test reproduces the *behavior* a reference regression test locks in
(cited per test), re-expressed against this framework's API. These close
the sweep gaps a name-level audit of the suites surfaced (VERDICT r1 weak
#7: reference regressions without a counterpart here).
"""

import numpy as np
import pandas as pd
import pytest

from flox_tpu import groupby_reduce
from flox_tpu.factorize import factorize_, factorize_single


def test_alignment_error(engine):
    # reference test_core.py:118 — by/array shape mismatch raises
    with pytest.raises(ValueError):
        groupby_reduce(np.ones(12), np.ones(5), func="mean", engine=engine)


@pytest.mark.parametrize("func", ["argmax", "nanargmax", "argmin", "nanargmin"])
@pytest.mark.parametrize("size", [(12,), (2, 12)])
def test_arg_reduction_dtype_is_int(engine, size, func):
    # reference test_core.py:391 — argreductions return an integer dtype
    rng = np.random.default_rng(12345)
    array = rng.random(size)
    by = np.ones(size[-1])
    if "nanarg" in func and len(size) > 1:
        array[1, [1, 4, 5]] = np.nan
    actual, _ = groupby_reduce(array, by, func=func, engine=engine)
    assert actual.dtype.kind == "i"
    expected = np.expand_dims(getattr(np, func)(array, axis=-1), -1)
    np.testing.assert_array_equal(np.asarray(actual), expected)


@pytest.mark.parametrize("func", ["sum", "nanmean"])
def test_empty_bins(engine, func):
    # reference test_core.py:1239 — bins that catch nothing get fill_value
    array = np.ones((2, 3, 2))
    by = np.broadcast_to([0, 1], array.shape)
    actual, _ = groupby_reduce(
        array,
        by,
        func=func,
        expected_groups=[-1, 0, 1, 2],
        isbin=True,
        engine=engine,
        axis=(0, 1, 2),
        fill_value=np.nan,
    )
    expected = np.array([1.0 if func == "nanmean" else 6.0, 1.0 if func == "nanmean" else 6.0, np.nan])
    np.testing.assert_allclose(np.asarray(actual, dtype=float), expected, equal_nan=True)


def test_datetime_binning():
    # reference test_core.py:1256 — binning datetimes == pd.cut
    time_bins = pd.date_range(start="2010-08-01", end="2010-08-15", freq="24h")
    by = pd.date_range("2010-08-01", "2010-08-15", freq="15min")
    intervals = pd.IntervalIndex.from_arrays(time_bins[:-1], time_bins[1:])

    codes, groups = factorize_single(by.to_numpy(), intervals)
    expected = pd.cut(by, time_bins).codes.copy().astype(codes.dtype)
    # pd.cut marks the left-open first edge -1; digitize-binning agrees on
    # everything in range, and out-of-range must be missing (<0 or dropped)
    in_range = expected >= 0
    np.testing.assert_array_equal(codes[in_range], expected[in_range])
    assert (codes[~in_range] < 0).all() or (codes[~in_range] >= len(intervals)).all()


def test_factorize_values_outside_bins():
    # reference test_core.py:1367 — out-of-bin values get missing codes in
    # the raveled multi-by product grid
    bins = pd.IntervalIndex.from_breaks(np.arange(2, 8, 1))
    codes, found, group_shape, ngroups, size, props = factorize_(
        (np.arange(10).reshape(5, 2), np.arange(10).reshape(5, 2)),
        axes=(0, 1),
        expected_groups=(bins, bins),
    )
    expected = np.array([[-1, -1], [-1, 0], [6, 12], [18, 24], [-1, -1]])
    np.testing.assert_array_equal(codes, expected)
    assert group_shape == (5, 5) and ngroups == 25


def test_validate_expected_groups(engine):
    # reference test_core.py:1441 — one expected_groups for two bys raises
    with pytest.raises((ValueError, TypeError)):
        groupby_reduce(
            np.ones((10,)),
            np.ones((10,)),
            np.ones((10,)),
            expected_groups=[0, 1, 2],
            func="mean",
            engine=engine,
        )


def test_factorize_reindex_sorting_strings():
    # reference test_core.py:1465 — codes against an unsorted expected
    # string index, sorted and unsorted
    by = np.array(["El-Nino", "La-Nina", "boo", "Neutral"])
    expect = pd.Index(["El-Nino", "Neutral", "foo", "La-Nina"])

    codes_sorted, groups_sorted = factorize_single(by, expect, sort=True)
    assert list(groups_sorted) == sorted(expect)
    np.testing.assert_array_equal(codes_sorted, [0, 1, -1, 2])

    codes_unsorted, groups_unsorted = factorize_single(by, expect, sort=False)
    assert list(groups_unsorted) == list(expect)
    np.testing.assert_array_equal(codes_unsorted, [0, 3, -1, 1])


def test_factorize_reindex_sorting_ints():
    # reference test_core.py:1486 — out-of-range ints are missing; a
    # descending expected index is honored when sort=False
    by = np.array([-10, 1, 10, 2, 3, 5])
    expect = pd.Index(np.array([0, 1, 2, 3, 4, 5], np.int64))

    for sort in (True, False):
        codes, _ = factorize_single(by, expect, sort=sort)
        np.testing.assert_array_equal(codes, [-1, 1, -1, 2, 3, 5])

    desc = pd.Index(np.arange(5, -1, -1))
    codes, groups = factorize_single(by, desc, sort=False)
    np.testing.assert_array_equal(codes, [-1, 4, -1, 3, 2, 0])
    codes, groups = factorize_single(by, desc, sort=True)
    np.testing.assert_array_equal(codes, [-1, 1, -1, 2, 3, 5])


@pytest.mark.parametrize("dtype", ["U3", object])
def test_count_string(engine, dtype):
    # reference test_core.py:1979 — count of string data per group
    array = np.array(["ABC", "DEF", "GHI", "JKL", "MNO", "PQR"], dtype=dtype)
    by = np.array([0, 0, 1, 2, 1, 0])
    actual, _ = groupby_reduce(array, by, func="count", engine=engine)
    np.testing.assert_array_equal(np.asarray(actual), [3, 2, 1])


@pytest.mark.parametrize("func", ["first", "last", "nanfirst", "nanlast"])
@pytest.mark.parametrize("kind", ["datetime", "timedelta"])
def test_datetime_timedelta_first_last(engine, func, kind):
    # reference test_core.py:2157 — first/last preserve datetime64/
    # timedelta64, and an empty expected group fills with NaT
    dt = pd.date_range("2001-01-01", freq="D", periods=5).values
    if kind == "timedelta":
        dt = dt - dt[0]
    nat = np.datetime64("NaT") if kind == "datetime" else np.timedelta64("NaT")
    idx = 0 if "first" in func else -1
    idx1 = 2 if "first" in func else -1

    by = np.ones(dt.shape, dtype=int)
    actual, _ = groupby_reduce(dt, by, func=func, engine=engine)
    assert np.asarray(actual).dtype == dt.dtype
    np.testing.assert_array_equal(np.asarray(actual), dt[[idx]])

    by = np.array([0, 2, 3, 3, 3])
    actual, _ = groupby_reduce(
        dt, by, expected_groups=[0, 1, 2, 3], func=func, engine=engine
    )
    np.testing.assert_array_equal(
        np.asarray(actual), np.array([dt[0], nat, dt[1], dt[idx1]], dtype=dt.dtype)
    )


@pytest.mark.parametrize("func", ["var", "std", "nanvar", "nanstd"])
@pytest.mark.parametrize("exponent", [3, 6, 9])
def test_std_var_precision(engine, func, exponent):
    # reference test_core.py:2293 — the single-pass Chan merge keeps small
    # variances stable under a large additive offset
    size = 1000
    offset = 10.0**exponent
    array = np.linspace(-1, 1, size)
    labels = np.arange(size) % 2

    no_offset, _ = groupby_reduce(array, labels, engine=engine, func=func)
    with_offset, _ = groupby_reduce(array + offset, labels, engine=engine, func=func)

    npf = getattr(np, func if func.startswith("nan") else "nan" + func)
    expected = np.array([npf(array[::2]), npf(array[1::2])])
    tol = dict(rtol=3e-8, atol=1e-9)
    np.testing.assert_allclose(np.asarray(no_offset), expected, **tol)
    np.testing.assert_allclose(np.asarray(with_offset), np.asarray(no_offset), **tol)


@pytest.mark.parametrize("q", [0.5, [0.5], [0.25, 0.75]])
def test_multiple_quantiles_eager(engine, q):
    # reference test_core.py:1956 — scalar vs vector q shapes on the core path
    rng = np.random.default_rng(0)
    array = rng.normal(size=(3, 40))
    by = rng.integers(0, 4, 40)
    actual, groups = groupby_reduce(
        array, by, func="quantile", finalize_kwargs={"q": q}, engine=engine
    )
    want_shape = (3, 4) if np.isscalar(q) else (len(q), 3, 4)
    assert np.asarray(actual).shape == want_shape
    qs = np.atleast_1d(q)
    for i, g in enumerate(groups):
        want = np.quantile(array[:, by == g], qs, axis=-1)
        got = np.asarray(actual)[..., i]
        np.testing.assert_allclose(
            got if not np.isscalar(q) else got[None], want, rtol=1e-12
        )


def test_bool_sum_returns_int(engine):
    # reference test_core.py:1273 — sum/count of bools promote to int
    array = np.array([True, True, False, True, False, True])
    by = np.array([0, 0, 0, 1, 1, 1])
    for func, want in [("sum", [2, 2]), ("count", [3, 3]), ("any", [True, True]), ("all", [False, False])]:
        actual, _ = groupby_reduce(array, by, func=func, engine=engine)
        np.testing.assert_array_equal(np.asarray(actual), want)
        if func in ("sum", "count"):
            assert np.asarray(actual).dtype.kind in "iu"
        else:
            assert np.asarray(actual).dtype.kind == "b"
