"""Resident dataset registry tests (flox_tpu/serve/registry.py).

The contracts under test:

* **bit-identity** — a registry-referenced request returns arrays
  bit-identical to the same data submitted inline, across eager + mesh
  execution, dense + sort engines, row-range and boolean-mask selectors,
  and fused multi-statistic sets;
* **fast path** — a registry hit skips factorize (no ``factorize`` span)
  and H2D staging (``bytes.h2d`` delta == 0), and never rehashes the
  payload (the entry's put-time fingerprint IS the coalescing identity);
* **HBM budget / LRU** — past ``registry_budget_bytes`` the stalest
  unpinned entry is evicted (counted on ``registry.evictions``); a pinned
  (in-flight) entry is never evicted mid-dispatch;
* **fault domain** — an unknown ``dataset=`` answers a typed
  :class:`UnknownDatasetError` (code ``unknown_dataset``, not
  ``execution``); ``del_dataset`` with an in-flight request is safe
  (refcount pin keeps the buffers alive until the dispatch settles);
  device-loss recovery re-pins every registered dataset from its host
  spill copy (``restage_all``);
* **protocol** — ``put_dataset`` / ``del_dataset`` / ``list_datasets``
  round-trip over the ``python -m flox_tpu.serve`` JSON-lines loop;
* **state registration** — the registry empties under
  ``cache.clear_all()`` and surfaces in ``cache.stats()["registry"]``
  (floxlint FLX008 covers the static half).
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import flox_tpu
from flox_tpu import cache, telemetry
from flox_tpu.core import groupby_reduce
from flox_tpu.factorize import Prefactorized, prefactorize
from flox_tpu.fusion import groupby_aggregate_many
from flox_tpu.parallel import make_mesh
from flox_tpu.serve import AggregationRequest, Dispatcher, UnknownDatasetError, aot
from flox_tpu.serve import registry
from flox_tpu.telemetry import METRICS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    """Serving state, counters, and the dataset registry reset per test;
    AOT persistence off (the AOT test opts in); autotune pinned off so a
    mid-test decision flip cannot break bit-identity assertions."""
    with flox_tpu.set_options(serve_aot_dir=None, autotune=False):
        cache.clear_all()
        yield
        cache.clear_all()
        aot.deconfigure()


def run(coro):
    return asyncio.run(coro)


def _payload(n=256, ngroups=7, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    values = rng.normal(size=n).astype(dtype)
    labels = rng.integers(0, ngroups, size=n)
    return values, labels


async def _one(d: Dispatcher, **kw):
    res = await d.submit(AggregationRequest(**kw))
    return res


def _submit(**kw):
    async def main():
        d = Dispatcher()
        try:
            return await _one(d, **kw)
        finally:
            await d.close()

    return run(main())


class TestBitIdentity:
    @pytest.mark.parametrize("engine", [None, "sort"])
    @pytest.mark.parametrize("func", ["sum", "nanmean", "max"])
    def test_registry_matches_inline(self, func, engine):
        values, labels = _payload()
        registry.put("ds", array=values, by=labels)
        inline = _submit(func=func, array=values, by=labels, engine=engine)
        hit = _submit(func=func, dataset="ds", engine=engine)
        np.testing.assert_array_equal(
            np.asarray(hit.result), np.asarray(inline.result)
        )
        np.testing.assert_array_equal(
            np.asarray(hit.groups), np.asarray(inline.groups)
        )

    def test_row_range_selector(self):
        """A selector view keeps the put-time group universe (no
        re-factorize — that IS the fast path, and a stable ngroups keeps
        the compiled program shared across selectors), so the inline
        equivalence pins ``expected_groups`` to it."""
        values, labels = _payload(n=512)
        registry.put("ds", array=values, by=labels)
        universe = np.unique(labels)
        hit = _submit(func="sum", dataset="ds", rows=[64, 400])
        expect, egroups = groupby_reduce(
            values[64:400], labels[64:400], func="sum", expected_groups=universe
        )
        np.testing.assert_array_equal(np.asarray(hit.result), np.asarray(expect))
        np.testing.assert_array_equal(np.asarray(hit.groups), np.asarray(egroups))

    def test_boolean_mask_selector(self):
        values, labels = _payload(n=512)
        registry.put("ds", array=values, by=labels)
        mask = (np.arange(512) % 3) == 0
        hit = _submit(func="nanmean", dataset="ds", mask=mask.tolist())
        expect, _ = groupby_reduce(
            values[mask], labels[mask], func="nanmean",
            expected_groups=np.unique(labels),
        )
        np.testing.assert_array_equal(np.asarray(hit.result), np.asarray(expect))

    def test_fused_multi_stat(self):
        values, labels = _payload()
        registry.put("ds", array=values, by=labels)
        funcs = ("sum", "mean", "max")
        hit = _submit(func=list(funcs), dataset="ds")
        expect, _ = groupby_aggregate_many(values, labels, funcs=funcs)
        for f in funcs:
            np.testing.assert_array_equal(
                np.asarray(hit.result[f]), np.asarray(expect[f])
            )

    def test_mesh_prefactorized_matches_raw(self):
        """The mesh leg of the matrix: prefactorized labels (the registry's
        factorize-once artifact) through the SPMD map-reduce path equal the
        raw-label call bit-for-bit."""
        values, labels = _payload(n=264)
        mesh = make_mesh()
        raw, _ = groupby_reduce(
            values, labels, func="sum", method="map-reduce", mesh=mesh
        )
        pf = prefactorize(labels)
        assert isinstance(pf, Prefactorized)
        via_pf, _ = groupby_reduce(
            values, pf, func="sum", method="map-reduce", mesh=mesh
        )
        np.testing.assert_array_equal(np.asarray(via_pf), np.asarray(raw))

    def test_labels_resident_inline_array(self):
        """A labels-only entry (no data array) still serves: the request
        inlines its own array over the resident precomputed codes."""
        values, labels = _payload()
        registry.put("labels-only", by=labels)
        hit = _submit(func="mean", dataset="labels-only", array=values)
        expect, _ = groupby_reduce(values, labels, func="mean")
        np.testing.assert_array_equal(np.asarray(hit.result), np.asarray(expect))

    def test_data_required_when_entry_has_none(self):
        _, labels = _payload()
        registry.put("labels-only", by=labels)
        with pytest.raises(ValueError, match="holds no data array"):
            _submit(func="mean", dataset="labels-only")


class TestFastPath:
    def test_hit_skips_factorize_and_h2d(self):
        values, labels = _payload(n=1024)
        with flox_tpu.set_options(telemetry=True):
            registry.put("ds", array=values, by=labels)

            async def main():
                d = Dispatcher()
                try:
                    await _one(d, func="sum", dataset="ds")  # compile + warm
                    telemetry.drain()
                    h2d0 = METRICS.get("bytes.h2d")
                    hits0 = METRICS.get("registry.hits")
                    await _one(d, func="sum", dataset="ds")
                    return (
                        [r["name"] for r in telemetry.drain() if r.get("type") == "span"],
                        METRICS.get("bytes.h2d") - h2d0,
                        METRICS.get("registry.hits") - hits0,
                    )
                finally:
                    await d.close()

            span_names, h2d_delta, hits_delta = run(main())
        assert "factorize" not in span_names
        assert h2d_delta == 0
        assert hits_delta == 1

    def test_hit_path_never_hashes_payload(self, monkeypatch):
        """A full-resident hit reuses the entry's stored fingerprint as
        both program-key and coalescing identity — zero digest calls."""
        from flox_tpu.serve import dispatcher as dmod

        values, labels = _payload()
        registry.put("ds", array=values, by=labels)
        calls = []
        real = dmod._digest_payload

        async def counting(arr):
            calls.append(arr.nbytes)
            return await real(arr)

        monkeypatch.setattr(dmod, "_digest_payload", counting)
        res = _submit(func="sum", dataset="ds")
        assert calls == []
        expect, _ = groupby_reduce(values, labels, func="sum")
        np.testing.assert_array_equal(np.asarray(res.result), np.asarray(expect))

    def test_inline_digest_memoized_per_request_object(self, monkeypatch):
        """A resubmitted request object (library retry loops) never rehashes
        an unchanged payload."""
        from flox_tpu.serve import dispatcher as dmod

        values, labels = _payload()
        req = AggregationRequest(func="sum", array=values, by=labels)

        async def main():
            d = Dispatcher()
            try:
                await d.submit(req)
                assert getattr(req, "_payload_digests", None) is not None

                async def boom(arr):  # pragma: no cover - must not run
                    raise AssertionError("payload rehashed on resubmit")

                monkeypatch.setattr(dmod, "_digest_payload", boom)
                return await d.submit(req)
            finally:
                await d.close()

        res = run(main())
        expect, _ = groupby_reduce(values, labels, func="sum")
        np.testing.assert_array_equal(np.asarray(res.result), np.asarray(expect))

    def test_registry_hits_coalesce(self):
        """K concurrent identical dataset references share ONE dispatch —
        the PR 7 coalescing contract holds on the fingerprint-keyed path."""
        values, labels = _payload()
        registry.put("ds", array=values, by=labels)
        K = 6

        async def main():
            d = Dispatcher()
            await _one(d, func="sum", dataset="ds")  # compile outside count
            before = METRICS.get("serve.dispatches")
            results = await asyncio.gather(
                *[_one(d, func="sum", dataset="ds") for _ in range(K)]
            )
            await d.close()
            return results, METRICS.get("serve.dispatches") - before

        results, dispatches = run(main())
        assert dispatches == 1
        first = np.asarray(results[0].result)
        for r in results[1:]:
            np.testing.assert_array_equal(np.asarray(r.result), first)
        # every coalesced waiter released its pin; the batch released its own
        assert registry.resolve("ds").pins == 0

    def test_aot_manifest_records_dataset_and_warms(self, tmp_path):
        """A registry dispatch lands in the AOT manifest (stamped with the
        dataset name, outside the spec digest) and warmup replays it —
        program identity is shapes/dtypes/ngroups, never residency."""
        values, labels = _payload()
        with flox_tpu.set_options(serve_aot_dir=str(tmp_path)):
            registry.put("ds", array=values, by=labels)
            _submit(func="sum", dataset="ds")
            mpath = aot.save_manifest()
            specs = json.loads(mpath.read_text())["programs"].values()
            assert any(s.get("dataset") == "ds" for s in specs)
            assert aot.warmup() >= 1


class TestBudgetAndEviction:
    def test_lru_evicts_stalest_past_budget(self):
        values, labels = _payload(n=4096, dtype=np.float32)
        one_entry = registry.put("a", array=values, by=labels)["nbytes"]
        with flox_tpu.set_options(registry_budget_bytes=int(one_entry * 1.5)):
            ev0 = METRICS.get("registry.evictions")
            info = registry.put("b", array=values + 1, by=labels)
            assert info["evicted"] == ["a"]
            assert METRICS.get("registry.evictions") - ev0 == 1
            with pytest.raises(UnknownDatasetError):
                registry.resolve("a")
            assert registry.resolve("b").name == "b"

    def test_pinned_entry_survives_eviction(self):
        values, labels = _payload(n=4096, dtype=np.float32)
        one_entry = registry.put("a", array=values, by=labels)["nbytes"]
        entry_a = registry.resolve("a")
        registry.pin(entry_a)
        try:
            with flox_tpu.set_options(registry_budget_bytes=int(one_entry * 1.5)):
                info = registry.put("b", array=values + 1, by=labels)
                # the only evictable candidate is pinned: nothing evicted,
                # total stays over budget rather than killing in-flight work
                assert info["evicted"] == []
            assert registry.resolve("a").name == "a"
        finally:
            registry.unpin(entry_a)
        # unpinned, the next over-budget put takes it ("b" was just renewed)
        with flox_tpu.set_options(registry_budget_bytes=int(one_entry * 1.5)):
            registry.resolve("b")  # renew b so a is stalest
            info = registry.put("c", array=values + 2, by=labels)
            assert "a" in info["evicted"]

    def test_budget_zero_is_unenforced(self):
        values, labels = _payload()
        with flox_tpu.set_options(registry_budget_bytes=0):
            registry.put("a", array=values, by=labels)
            info = registry.put("b", array=values + 1, by=labels)
        assert info["evicted"] == []
        assert len(registry.list_datasets()) == 2

    def test_registry_knob_validation(self):
        with pytest.raises(ValueError):
            flox_tpu.set_options(registry_budget_fraction=0.0)
        with pytest.raises(ValueError):
            flox_tpu.set_options(registry_budget_bytes=-1)
        with pytest.raises(ValueError):
            flox_tpu.set_options(registry_shard_threshold_bytes=-5)


class TestFaultDomain:
    def test_unknown_dataset_typed_error(self):
        misses0 = METRICS.get("registry.misses")
        with pytest.raises(UnknownDatasetError) as exc:
            _submit(func="sum", dataset="never-put")
        assert exc.value.code == "unknown_dataset"
        assert METRICS.get("registry.misses") - misses0 == 1

    def test_delete_with_inflight_request_is_safe(self):
        """del_dataset between submit and completion: the batch's refcount
        pin keeps the entry's buffers alive, the in-flight request answers
        correctly, and later references get the typed error."""
        values, labels = _payload(n=2048)
        registry.put("ds", array=values, by=labels)
        expect, _ = groupby_reduce(values, labels, func="sum")

        async def main():
            d = Dispatcher()
            try:
                task = asyncio.ensure_future(_one(d, func="sum", dataset="ds"))
                # let the submit resolve + pin + enqueue, then yank the entry
                for _ in range(3):
                    await asyncio.sleep(0)
                assert registry.delete("ds") is True
                res = await task
                return res
            finally:
                await d.close()

        res = run(main())
        np.testing.assert_array_equal(np.asarray(res.result), np.asarray(expect))
        with pytest.raises(UnknownDatasetError):
            _submit(func="sum", dataset="ds")

    def test_selector_validation(self):
        values, labels = _payload()
        registry.put("ds", array=values, by=labels)
        with pytest.raises(ValueError, match="not both"):
            _submit(func="sum", dataset="ds", rows=[0, 8],
                    mask=[True] * len(values))
        with pytest.raises(ValueError, match="require a 'dataset'"):
            _submit(func="sum", array=values, by=labels, rows=[0, 8])
        with pytest.raises(ValueError, match="fixed at put time"):
            _submit(func="sum", dataset="ds", by=labels)

    def test_restage_all_after_device_loss(self):
        """Device-loss recovery re-pins registered datasets from host spill
        copies: after a backend teardown, restage_all() rebuilds device
        residency and results stay bit-identical."""
        from flox_tpu import device

        values, labels = _payload()
        registry.put("ds", array=values, by=labels)
        before = _submit(func="sum", dataset="ds")
        device.reinitialize()
        assert registry.restage_all() == 1
        after = _submit(func="sum", dataset="ds")
        np.testing.assert_array_equal(
            np.asarray(after.result), np.asarray(before.result)
        )


class TestStateRegistration:
    def test_stats_and_clear_all(self):
        values, labels = _payload()
        registry.put("ds", array=values, by=labels)
        # the cost ledger (like every observe_cost site) records only while
        # telemetry is on
        with flox_tpu.set_options(telemetry=True):
            _submit(func="sum", dataset="ds")
        st = cache.stats()
        assert st["registry"]["datasets"] == 1
        assert st["registry"]["bytes"] > 0
        # per-dataset cost attribution rides the same ledger as per-program
        assert "ds" in st["cost_by_dataset"]
        assert st["cost_by_dataset"]["ds"]["dispatches"] >= 1
        cache.clear_all()
        assert registry.list_datasets() == []
        assert cache.stats()["registry"]["datasets"] == 0
        assert METRICS.get("registry.datasets") == 0

    def test_debug_table_shape(self):
        values, labels = _payload()
        registry.put("ds", array=values, by=labels)
        table = registry.debug_table()
        assert table["bytes"] > 0
        assert table["datasets"][0]["name"] == "ds"
        assert table["datasets"][0]["nbytes"] > 0
        assert "budget_bytes" in table and "evictions" in table

    def test_put_validation(self):
        with pytest.raises(ValueError, match="requires 'by'"):
            registry.put("ds", array=np.ones(8))
        with pytest.raises(ValueError, match="do not align"):
            registry.put("ds", array=np.ones(8), by=np.zeros(9, dtype=np.int64))
        with pytest.raises(ValueError):
            registry.put("", by=np.zeros(8, dtype=np.int64))


class TestProtocol:
    def test_put_del_list_roundtrip_cli(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu", FLOX_TPU_TELEMETRY="1")
        env.pop("FLOX_TPU_TELEMETRY_EXPORT_PATH", None)
        values = [1.0, 2.0, 4.0, 8.0, 16.0, 32.0]
        labels = [0, 0, 1, 1, 2, 2]
        lines = "\n".join([
            json.dumps({"op": "put_dataset", "name": "t",
                        "array": values, "by": labels}),
            json.dumps({"op": "list_datasets"}),
            json.dumps({"id": "hit", "func": "sum", "dataset": "t"}),
            json.dumps({"id": "rows", "func": "sum", "dataset": "t",
                        "rows": [0, 4]}),
            json.dumps({"id": "inline", "func": "sum",
                        "array": values, "by": labels}),
            json.dumps({"id": "missing", "func": "sum", "dataset": "nope"}),
            json.dumps({"id": "bad", "func": "sum", "dataset": "t",
                        "by": labels}),
            json.dumps({"op": "del_dataset", "name": "t"}),
            json.dumps({"id": "gone", "func": "sum", "dataset": "t"}),
            json.dumps({"op": "drain"}),
        ])
        proc = subprocess.run(
            [sys.executable, "-m", "flox_tpu.serve"],
            input=lines, cwd=REPO, env=env,
            capture_output=True, text=True, timeout=240,
        )
        assert proc.returncode == 0, proc.stderr
        recs = {}
        for raw in proc.stdout.splitlines():
            rec = json.loads(raw)
            recs[rec.get("id") or rec.get("op")] = rec
        put = recs["put_dataset"]
        assert put["ok"] and put["name"] == "t" and put["nbytes"] > 0
        assert put["ngroups"] == 3
        listed = recs["list_datasets"]
        assert listed["ok"] and listed["datasets"][0]["name"] == "t"
        assert listed["stats"]["datasets"] == 1
        assert recs["hit"]["ok"] and recs["inline"]["ok"]
        assert recs["hit"]["result"] == recs["inline"]["result"]
        # the selector keeps the put-time group universe: group 2 is absent
        # from rows [0, 4) and lands on the sum identity
        assert recs["rows"]["ok"] and recs["rows"]["result"] == [3.0, 12.0, 0.0]
        assert recs["missing"]["ok"] is False
        assert recs["missing"]["code"] == "unknown_dataset"
        # inlining 'by' alongside a dataset reference is a protocol error
        assert recs["bad"]["ok"] is False and recs["bad"]["code"] == "protocol"
        assert recs["del_dataset"]["ok"] and recs["del_dataset"]["deleted"]
        assert recs["gone"]["code"] == "unknown_dataset"

    def test_put_dataset_error_is_answered_not_fatal(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        lines = "\n".join([
            json.dumps({"op": "put_dataset", "name": "t", "array": [1.0]}),
            json.dumps({"id": "r", "func": "sum",
                        "array": [1.0, 2.0], "by": [0, 1]}),
            json.dumps({"op": "drain"}),
        ])
        proc = subprocess.run(
            [sys.executable, "-m", "flox_tpu.serve"],
            input=lines, cwd=REPO, env=env,
            capture_output=True, text=True, timeout=240,
        )
        assert proc.returncode == 0, proc.stderr
        recs = [json.loads(l) for l in proc.stdout.splitlines()]
        put = next(r for r in recs if r.get("op") == "put_dataset")
        assert put["ok"] is False and "by" in put["message"]
        # the loop survived the bad put: the next request still answers
        good = next(r for r in recs if r.get("id") == "r")
        assert good["ok"] and good["result"] == [1.0, 2.0]
