"""Serve chaos suite (ISSUE 12): the serve-plane fault domain under
deterministic injection.

The contracts under test, each driven by ``faults.serve_inject``:

* **request quarantine** — a poisoned micro-batch member fails ALONE: the
  batch bisects on the power-of-two ladder, healthy peers get results
  bit-identical to solo runs, only the poisoned member sees the typed
  error;
* **per-program circuit breakers** — repeated fatal failures on one
  program key open its breaker: further identical-program submits
  fast-fail with ``CircuitOpenError`` (``code="circuit_open"`` +
  ``retry_after_ms``) WITHOUT a device dispatch (asserted on
  ``serve.dispatches``); after the cooldown a half-open probe closes it;
* **device-loss recovery** — an injected ``DEVICE_LOST`` fails in-flight
  waiters with ``DeviceLostError``, flips readiness to 503
  (``device-lost``), reinitializes the backend, replays the AOT warmup
  manifest, flips readiness back — and the post-recovery warm dispatch
  reports ``jax.compiles == 0``;
* **dispatch watchdog** — a hung dispatch fails its waiters within the
  ``serve_watchdog_timeout`` budget instead of wedging the queue (the
  next request serves normally);
* **graceful drain** — SIGTERM during an in-flight request answers the
  request, emits the shutdown ack, flight-dumps, and exits 0 (subprocess
  smoke; ``{"op": "shutdown"}`` rides the same path);
* **quiescence** — with the whole fault domain enabled but no fault
  injected, results are bit-identical to direct library calls.
"""

from __future__ import annotations

import asyncio
import json
import os
import select
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import flox_tpu
from flox_tpu import cache, exposition, faults
from flox_tpu.core import groupby_reduce
from flox_tpu.serve import (
    CircuitOpenError,
    DeviceLostError,
    Dispatcher,
    DrainingError,
    WatchdogTimeoutError,
    payload_digest,
)
from flox_tpu.telemetry import METRICS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    """Serve/breaker state and counters reset per test; AOT off unless a
    test opts in; the autotuner pinned off so decision flips cannot break
    bit-identity assertions under the CI FLOX_TPU_AUTOTUNE=1 leg."""
    with flox_tpu.set_options(serve_aot_dir=None, autotune=False):
        cache.clear_all()
        exposition.set_ready(True)
        yield
        cache.clear_all()
        exposition.set_ready(False)
        from flox_tpu.serve import aot

        aot.deconfigure()


def run(coro):
    return asyncio.run(coro)


def _payload(n=64, ngroups=5, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=n), rng.integers(0, ngroups, size=n)


class TestQuarantine:
    def test_poisoned_member_fails_alone_peers_bit_identical(self):
        """Acceptance: one poisoned member inside a 4-leaf micro-batch gets
        the typed error; the 3 healthy peers' results are bit-identical to
        solo runs."""
        _, labels = _payload()
        payloads = [np.arange(64, dtype=np.float64) + 10 * i for i in range(4)]
        solo = [np.asarray(groupby_reduce(p, labels, func="sum")[0]) for p in payloads]
        poisoned = 2

        async def main():
            d = Dispatcher(batch_window=0.05)
            with faults.serve_inject(
                poison_digests=[payload_digest(payloads[poisoned])]
            ) as plan:
                results = await asyncio.gather(
                    *[d.submit(func="sum", array=p, by=labels) for p in payloads],
                    return_exceptions=True,
                )
                await d.close()
            return results, list(plan.log)

        results, log = run(main())
        for i, (got, expect) in enumerate(zip(results, solo)):
            if i == poisoned:
                assert isinstance(got, faults.SimulatedCompileError), got
            else:
                assert not isinstance(got, Exception), got
                assert np.asarray(got.result).tobytes() == expect.tobytes()
        assert METRICS.get("serve.quarantine_splits") >= 1
        assert METRICS.get("serve.quarantined") == 1
        # the bisection is visible in the plan log: the poison fired for
        # every dispatch containing the member, healthy sub-batches ran
        assert sum(1 for kind, *_ in log if kind == "poison") >= 2
        # determinism: the same plan against the same submits replays
        results2, log2 = run(main())
        assert [type(r).__name__ for r in results2] == [
            type(r).__name__ for r in results
        ]
        assert [kind for kind, *_ in log2] == [kind for kind, *_ in log]

    def test_poisoned_coalesced_batch_of_two(self):
        """The 2-leaf edge of the ladder: one healthy, one poisoned."""
        _, labels = _payload()
        good = np.arange(64, dtype=np.float64)
        bad = good + 1
        expect = np.asarray(groupby_reduce(good, labels, func="sum")[0])

        async def main():
            d = Dispatcher(batch_window=0.05)
            with faults.serve_inject(poison_digests=[payload_digest(bad)]):
                ok, err = await asyncio.gather(
                    d.submit(func="sum", array=good, by=labels),
                    d.submit(func="sum", array=bad, by=labels),
                    return_exceptions=True,
                )
                await d.close()
            return ok, err

        ok, err = run(main())
        assert np.asarray(ok.result).tobytes() == expect.tobytes()
        assert isinstance(err, faults.SimulatedCompileError)

    def test_queue_healthy_after_quarantine(self):
        values, labels = _payload()
        expect = np.asarray(groupby_reduce(values, labels, func="sum")[0])

        async def main():
            d = Dispatcher(batch_window=0.05)
            with faults.serve_inject(poison_digests=[payload_digest(values)]):
                with pytest.raises(faults.SimulatedCompileError):
                    await d.submit(func="sum", array=values, by=labels)
                await d.close()
            after = await d.submit(func="sum", array=values, by=labels)
            await d.close()
            return after

        after = run(main())
        assert np.asarray(after.result).tobytes() == expect.tobytes()
        assert cache.stats()["serve_pending"] == 0
        assert cache.stats()["serve_coalesce"] == 0


class TestCircuitBreaker:
    def test_breaker_opens_and_fast_fails_without_dispatch(self):
        """Acceptance: an open breaker fast-fails with no device dispatch
        (``serve.dispatches`` unchanged) and a typed error carrying the
        program label + cooldown."""
        values, labels = _payload()

        async def main():
            d = Dispatcher(microbatch_max=1)
            with flox_tpu.set_options(
                serve_breaker_threshold=2, serve_breaker_cooldown=60.0
            ):
                with faults.serve_inject(fail_compile_for=["sum"]):
                    for _ in range(2):
                        with pytest.raises(faults.SimulatedCompileError):
                            await d.submit(func="sum", array=values, by=labels)
                        await d.close()
                dispatches = METRICS.get("serve.dispatches")
                with pytest.raises(CircuitOpenError) as info:
                    await d.submit(func="sum", array=values, by=labels)
                await d.close()
                return dispatches, METRICS.get("serve.dispatches"), info.value

        before, after, exc = run(main())
        assert after == before  # fast-fail: no dispatch burned
        assert exc.code == "circuit_open"
        assert exc.retry_after_ms is not None and exc.retry_after_ms > 0
        assert exc.program is not None and exc.program.startswith("sum#")
        assert METRICS.get("serve.breaker_opened") == 1
        assert METRICS.get("serve.breaker_fastfail") == 1
        stats = cache.stats()["serve_breakers"]
        assert stats["open"] == 1 and stats["total"] == 1
        (tripped,) = stats["tripped"].values()
        assert tripped["state"] == "open" and tripped["failures"] == 2

    def test_half_open_probe_closes_breaker(self):
        values, labels = _payload()
        expect = np.asarray(groupby_reduce(values, labels, func="sum")[0])

        async def main():
            d = Dispatcher(microbatch_max=1)
            with flox_tpu.set_options(
                serve_breaker_threshold=1, serve_breaker_cooldown=0.05
            ):
                with faults.serve_inject(fail_compile_for=["sum"]):
                    with pytest.raises(faults.SimulatedCompileError):
                        await d.submit(func="sum", array=values, by=labels)
                    await d.close()
                    with pytest.raises(CircuitOpenError):
                        await d.submit(func="sum", array=values, by=labels)
                await asyncio.sleep(0.08)  # cooldown elapses, fault gone
                probe = await d.submit(func="sum", array=values, by=labels)
                await d.close()
                after = await d.submit(func="sum", array=values, by=labels)
                await d.close()
                return probe, after

        probe, after = run(main())
        assert np.asarray(probe.result).tobytes() == expect.tobytes()
        assert np.asarray(after.result).tobytes() == expect.tobytes()
        assert METRICS.get("serve.breaker_half_open") == 1
        assert METRICS.get("serve.breaker_closed") == 1
        assert cache.stats()["serve_breakers"]["total"] == 0

    def test_failed_probe_reopens(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher(microbatch_max=1)
            with flox_tpu.set_options(
                serve_breaker_threshold=1, serve_breaker_cooldown=0.05
            ):
                with faults.serve_inject(fail_compile_for=["sum"]):
                    with pytest.raises(faults.SimulatedCompileError):
                        await d.submit(func="sum", array=values, by=labels)
                    await d.close()
                    await asyncio.sleep(0.08)
                    # the probe is admitted — and fails again
                    with pytest.raises(faults.SimulatedCompileError):
                        await d.submit(func="sum", array=values, by=labels)
                    await d.close()
                    # straight back to open, fresh cooldown: fast-fail
                    with pytest.raises(CircuitOpenError):
                        await d.submit(func="sum", array=values, by=labels)
                await d.close()

        run(main())
        assert METRICS.get("serve.breaker_reopened") == 1
        assert cache.stats()["serve_breakers"]["open"] == 1

    def test_inconclusive_probe_rearms_instead_of_wedging(self):
        """A half-open probe that ends WITHOUT a verdict (here: device loss
        under the probe's dispatch) must re-arm the probe slot — not leave
        ``probing=True`` forever fast-failing the key permanently."""
        values, labels = _payload()
        expect = np.asarray(groupby_reduce(values, labels, func="sum")[0])

        async def main():
            d = Dispatcher(microbatch_max=1)
            with flox_tpu.set_options(
                serve_breaker_threshold=1, serve_breaker_cooldown=0.05
            ):
                with faults.serve_inject(fail_compile_for=["sum"], fail_times=1):
                    with pytest.raises(faults.SimulatedCompileError):
                        await d.submit(func="sum", array=values, by=labels)
                    await d.close()
                await asyncio.sleep(0.08)  # cooldown elapses
                with faults.serve_inject(device_loss_at=[1]):
                    # the admitted probe dies with the device: no verdict
                    with pytest.raises(DeviceLostError):
                        await d.submit(func="sum", array=values, by=labels)
                    await d.close()  # recovery completes
                # the NEXT request becomes a fresh probe and closes the
                # breaker — a leaked probe slot would CircuitOpenError here
                after = await d.submit(func="sum", array=values, by=labels)
                await d.close()
                return after

        after = run(main())
        assert np.asarray(after.result).tobytes() == expect.tobytes()
        assert cache.stats()["serve_breakers"]["total"] == 0
        assert METRICS.get("serve.breaker_closed") == 1

    def test_threshold_zero_disables_breakers(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher(microbatch_max=1)
            with flox_tpu.set_options(serve_breaker_threshold=0):
                with faults.serve_inject(fail_compile_for=["sum"]):
                    for _ in range(4):
                        with pytest.raises(faults.SimulatedCompileError):
                            await d.submit(func="sum", array=values, by=labels)
                        await d.close()

        run(main())
        assert cache.stats()["serve_breakers"]["total"] == 0
        assert METRICS.get("serve.breaker_opened") == 0

    def test_different_program_keys_have_independent_breakers(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher(microbatch_max=1)
            with flox_tpu.set_options(
                serve_breaker_threshold=1, serve_breaker_cooldown=60.0
            ):
                with faults.serve_inject(fail_compile_for=["sum"]):
                    with pytest.raises(faults.SimulatedCompileError):
                        await d.submit(func="sum", array=values, by=labels)
                    await d.close()
                    with pytest.raises(CircuitOpenError):
                        await d.submit(func="sum", array=values, by=labels)
                    # a different program key is untouched by sum's breaker
                    ok = await d.submit(func="mean", array=values, by=labels)
                    await d.close()
                    return ok

        ok = run(main())
        expect, _ = groupby_reduce(*_mean_args(), func="mean")
        np.testing.assert_array_equal(ok.result, np.asarray(expect))


def _mean_args():
    values, labels = _payload()
    return values, labels


class TestDeviceLossRecovery:
    def test_full_cycle_readyz_and_zero_compile_warm_dispatch(self, tmp_path):
        """Acceptance: injected device loss -> in-flight waiters fail with
        DeviceLostError, readiness flips 503 (device-lost) then back to
        200, and the post-recovery warm dispatch provokes 0 new backend
        compiles (AOT warmup replayed against the persistent cache)."""
        values, labels = _payload()
        readiness: dict[str, bool] = {}

        async def main():
            with flox_tpu.set_options(
                serve_aot_dir=str(tmp_path), telemetry=True
            ):
                d = Dispatcher(microbatch_max=1)
                # request A: compiles, persists the executable + manifest
                a = await d.submit(func="sum", array=values, by=labels)
                await d.close()
                with faults.serve_inject(device_loss_at=[1]):
                    with pytest.raises(DeviceLostError) as info:
                        await d.submit(func="sum", array=values, by=labels)
                    readiness["during"] = exposition.ready()
                    reason = exposition.ready_reason()
                    await d.close()  # the batch task finishes the recovery
                readiness["after"] = exposition.ready()
                compiles0 = METRICS.get("jax.compiles")
                c = await d.submit(func="sum", array=values, by=labels)
                await d.close()
                return a, info.value, reason, METRICS.get("jax.compiles") - compiles0, c

        a, exc, reason, compile_delta, c = run(main())
        assert exc.code == "device_lost"
        assert readiness["during"] is False and reason == "device-lost"
        assert readiness["after"] is True
        assert compile_delta == 0, "post-recovery warm dispatch recompiled"
        assert np.asarray(c.result).tobytes() == np.asarray(a.result).tobytes()
        assert METRICS.get("serve.device_lost") == 1
        assert METRICS.get("serve.recoveries") == 1
        assert METRICS.get("serve.aot_warmed") >= 1  # manifest replayed

    def test_device_loss_does_not_open_breaker(self):
        values, labels = _payload()

        async def main():
            with flox_tpu.set_options(
                serve_breaker_threshold=1, telemetry=True
            ):
                d = Dispatcher(microbatch_max=1)
                with faults.serve_inject(device_loss_at=[1]):
                    with pytest.raises(DeviceLostError):
                        await d.submit(func="sum", array=values, by=labels)
                    await d.close()
                ok = await d.submit(func="sum", array=values, by=labels)
                await d.close()
                return ok

        ok = run(main())
        assert ok is not None
        assert cache.stats()["serve_breakers"]["total"] == 0


class TestWatchdog:
    def test_hung_dispatch_fails_waiters_within_budget(self):
        """Acceptance: a hung dispatch fails its waiters within the
        watchdog budget instead of blocking the queue."""
        values, labels = _payload()

        async def main():
            d = Dispatcher(microbatch_max=1, batch_window=0.0)
            with flox_tpu.set_options(serve_watchdog_timeout=0.15):
                with faults.serve_inject(hang_at=[1], hang_seconds=1.0):
                    t0 = time.perf_counter()
                    with pytest.raises(WatchdogTimeoutError) as info:
                        await d.submit(func="sum", array=values, by=labels)
                    elapsed = time.perf_counter() - t0
                    # the queue keeps moving while the hung thread sleeps on
                    after = await d.submit(func="sum", array=values + 1, by=labels)
                    await d.close()
            return info.value, elapsed, after

        exc, elapsed, after = run(main())
        assert exc.code == "watchdog_timeout"
        assert elapsed < 0.8, f"waiters hung for {elapsed:.2f}s past the budget"
        assert METRICS.get("serve.watchdog_fired") == 1
        expect_after = np.asarray(groupby_reduce(values + 1, labels, func="sum")[0])
        assert np.asarray(after.result).tobytes() == expect_after.tobytes()

    def test_watchdog_counts_toward_breaker(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher(microbatch_max=1)
            with flox_tpu.set_options(
                serve_watchdog_timeout=0.1,
                serve_breaker_threshold=1,
                serve_breaker_cooldown=60.0,
            ):
                with faults.serve_inject(hang_at=[1], hang_seconds=0.5):
                    with pytest.raises(WatchdogTimeoutError):
                        await d.submit(func="sum", array=values, by=labels)
                with pytest.raises(CircuitOpenError):
                    await d.submit(func="sum", array=values, by=labels)
                await d.close()

        run(main())
        assert cache.stats()["serve_breakers"]["open"] == 1

    def test_watchdog_zero_disables(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher(microbatch_max=1)
            with flox_tpu.set_options(serve_watchdog_timeout=0.0):
                with faults.serve_inject(hang_at=[1], hang_seconds=0.2):
                    return await d.submit(func="sum", array=values, by=labels)

        assert run(main()) is not None
        assert METRICS.get("serve.watchdog_fired") == 0


class TestDrain:
    def test_begin_drain_rejects_new_submits_typed(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher()
            ok = await d.submit(func="sum", array=values, by=labels)
            d.begin_drain()
            assert d.draining
            with pytest.raises(DrainingError) as info:
                await d.submit(func="sum", array=values, by=labels)
            await d.close()
            return ok, info.value

        ok, exc = run(main())
        assert ok is not None
        assert exc.code == "draining"
        assert METRICS.get("serve.drains") == 1
        assert METRICS.get("serve.drain_rejected") == 1

    def test_ready_reason_tracks_drain_and_recovery_states(self):
        exposition.set_ready(True)
        assert exposition.ready() and exposition.ready_reason() == "warming"
        exposition.set_ready(False, reason="draining")
        assert not exposition.ready()
        assert exposition.ready_reason() == "draining"
        exposition.set_ready(False, reason="device-lost")
        assert exposition.ready_reason() == "device-lost"
        exposition.set_ready(True)
        assert exposition.ready_reason() == "warming"

    def test_sigterm_graceful_drain_subprocess(self, tmp_path):
        """Acceptance: SIGTERM during an in-flight request exits 0 AFTER
        answering it, with the shutdown ack and a drain flight dump."""
        flight = tmp_path / "flight.jsonl"
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            FLOX_TPU_TELEMETRY="1",
            FLOX_TPU_FLIGHT_RECORDER_PATH=str(flight),
        )
        env.pop("FLOX_TPU_TELEMETRY_EXPORT_PATH", None)
        proc = subprocess.Popen(
            [sys.executable, "-m", "flox_tpu.serve", "--batch-window", "0.6"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, cwd=REPO, env=env,
        )
        try:
            reader = _RawLineReader(proc)
            # prove the loop is alive before timing anything
            proc.stdin.write(json.dumps({"op": "stats"}) + "\n")
            proc.stdin.flush()
            stats_line = reader.line(timeout=120)
            assert json.loads(stats_line)["op"] == "stats"
            # in-flight: admitted, inside the 0.6s batch window, undispatched
            proc.stdin.write(
                json.dumps(
                    {"id": "inflight", "func": "sum",
                     "array": [1.0, 2.0, 4.0, 8.0], "by": [0, 0, 1, 1]}
                )
                + "\n"
            )
            proc.stdin.flush()
            time.sleep(0.25)
            proc.send_signal(signal.SIGTERM)
            out = reader.until_eof(timeout=120)
            proc.wait(timeout=60)
            err = proc.stderr.read()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
        assert proc.returncode == 0, (proc.returncode, err)
        records = [json.loads(l) for l in (stats_line + out).splitlines() if l.strip()]
        by_id = {r.get("id", r.get("op")): r for r in records}
        assert by_id["inflight"]["ok"], by_id  # answered, not killed
        assert by_id["inflight"]["result"] == [3.0, 12.0]
        ack = by_id["shutdown"]
        assert ack["ok"] and ack["source"] == "SIGTERM" and ack["abandoned"] == 0
        dump = [json.loads(l) for l in flight.read_text().splitlines()]
        assert dump[0]["attrs"]["reason"] == "drain:SIGTERM", dump[0]

    def test_shutdown_op_drains_and_exits_zero(self, tmp_path):
        flight = tmp_path / "flight.jsonl"
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            FLOX_TPU_TELEMETRY="1",
            FLOX_TPU_FLIGHT_RECORDER_PATH=str(flight),
        )
        env.pop("FLOX_TPU_TELEMETRY_EXPORT_PATH", None)
        lines = "\n".join(
            [
                json.dumps({"id": "r", "func": "sum",
                            "array": [1.0, 2.0, 4.0, 8.0], "by": [0, 0, 1, 1]}),
                json.dumps({"op": "shutdown"}),
                json.dumps({"id": "late", "func": "sum",
                            "array": [1.0], "by": [0]}),  # after shutdown: unread
            ]
        )
        proc = subprocess.run(
            [sys.executable, "-m", "flox_tpu.serve"],
            input=lines, cwd=REPO, env=env,
            capture_output=True, text=True, timeout=240,
        )
        assert proc.returncode == 0, proc.stderr
        records = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
        by_id = {r.get("id", r.get("op")): r for r in records}
        assert by_id["r"]["ok"]
        assert by_id["shutdown"]["ok"]
        assert by_id["shutdown"]["source"] == "shutdown-op"
        assert "late" not in by_id  # admission stopped at the shutdown op
        assert flight.exists()


class _RawLineReader:
    """Bounded line reads from a live subprocess's stdout.

    Reads the RAW fd with ``os.read`` (never the TextIOWrapper — buffered
    reads strand bytes invisible to ``select``, which then waits forever on
    an fd whose data already moved into the Python-side buffer), so a
    wedged replica fails the test instead of hanging the suite."""

    def __init__(self, proc) -> None:
        self.proc = proc
        self.fd = proc.stdout.fileno()
        self.buf = b""

    def line(self, timeout: float) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            head, sep, rest = self.buf.partition(b"\n")
            if sep:
                self.buf = rest
                return head.decode() + "\n"
            ready, _, _ = select.select([self.fd], [], [], 0.2)
            if not ready:
                if self.proc.poll() is not None:
                    raise AssertionError(
                        f"serve exited early: rc={self.proc.returncode} "
                        f"stderr={self.proc.stderr.read()[-2000:]}"
                    )
                continue
            self.buf += os.read(self.fd, 65536)
        raise AssertionError(f"no line within {timeout}s (got {self.buf!r})")

    def until_eof(self, timeout: float) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ready, _, _ = select.select([self.fd], [], [], 0.2)
            if not ready:
                continue
            chunk = os.read(self.fd, 65536)
            if not chunk:
                out, self.buf = self.buf, b""
                return out.decode()
            self.buf += chunk
        raise AssertionError(f"no EOF within {timeout}s (got {self.buf!r})")


class TestTypedProtocolErrors:
    def test_error_response_carries_code_and_retry_hint(self):
        from flox_tpu.serve import __main__ as serve_main
        from flox_tpu.serve.dispatcher import LoadShedError

        resp = serve_main._error_response(
            "r1", LoadShedError("saturated", retry_after_ms=12.5)
        )
        assert resp["code"] == "load_shed"
        assert resp["retry_after_ms"] == 12.5
        assert resp["error"] == "LoadShedError"
        resp = serve_main._error_response("r2", ValueError("boom"))
        assert resp["code"] == "execution" and "retry_after_ms" not in resp
        resp = serve_main._error_response(
            "r3",
            CircuitOpenError("open", retry_after_ms=100.0, program="sum#abcd"),
        )
        assert resp["code"] == "circuit_open" and resp["program"] == "sum#abcd"

    def test_every_serve_error_has_a_distinct_code(self):
        from flox_tpu.serve import dispatcher as dp

        codes = {
            cls.code
            for cls in (
                dp.LoadShedError, dp.DeadlineExceededError, dp.CircuitOpenError,
                dp.DeviceLostError, dp.WatchdogTimeoutError, dp.DrainingError,
            )
        }
        assert len(codes) == 6  # no two failure kinds share a code

    def test_load_shed_carries_retry_hint(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher(queue_depth=1, batch_window=0.2)
            results = await asyncio.gather(
                *[d.submit(func="sum", array=values + i, by=labels) for i in range(3)],
                return_exceptions=True,
            )
            await d.close()
            return results

        shed = [r for r in run(main()) if isinstance(r, Exception)]
        assert shed and all(
            r.code == "load_shed" and r.retry_after_ms and r.retry_after_ms > 0
            for r in shed
        )


class TestQuiescentBitIdentity:
    def test_fault_domain_armed_but_quiescent_is_bit_identical(self):
        """Acceptance: watchdog + breakers enabled, zero faults injected —
        served results are bit-identical to direct library calls."""
        requests = []
        for i in range(8):
            values, labels = _payload(seed=i, ngroups=3 + i % 3)
            requests.append((["sum", "nanmean", "max", "prod"][i % 4], values, labels))
        direct = [
            np.asarray(groupby_reduce(v, l, func=f)[0]) for f, v, l in requests
        ]

        async def main():
            d = Dispatcher()
            with flox_tpu.set_options(
                serve_watchdog_timeout=30.0,
                serve_breaker_threshold=2,
                serve_breaker_cooldown=1.0,
            ):
                results = await asyncio.gather(
                    *[d.submit(func=f, array=v, by=l) for f, v, l in requests]
                )
                await d.close()
            return results

        for served, expect in zip(run(main()), direct):
            assert np.asarray(served.result).tobytes() == expect.tobytes()
        assert METRICS.get("serve.quarantine_splits") == 0
        assert METRICS.get("serve.watchdog_fired") == 0
        assert cache.stats()["serve_breakers"]["total"] == 0


class TestServeHarness:
    def test_serve_plan_nests_and_restores(self):
        assert not faults.serve_active()
        with faults.serve_inject(fail_compile_for=["sum"]):
            assert faults.serve_active()
            with faults.serve_inject(device_loss_at=[1]):
                assert faults.serve_active()
            assert faults.serve_active()
        assert not faults.serve_active()

    def test_serve_poke_noop_without_plan(self):
        faults.serve_poke("sum", ("digest",))  # must not raise

    def test_fail_times_bounds_firings(self):
        values, labels = _payload()

        async def main():
            d = Dispatcher(microbatch_max=1)
            with faults.serve_inject(fail_compile_for=["sum"], fail_times=1):
                with pytest.raises(faults.SimulatedCompileError):
                    await d.submit(func="sum", array=values, by=labels)
                await d.close()
                ok = await d.submit(func="sum", array=values, by=labels)
                await d.close()
                return ok

        assert run(main()) is not None
