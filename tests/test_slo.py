"""SLO & canary plane test suite (ISSUE 19).

The contract under test: operators declare latency / availability /
correctness / freshness objectives in a validated spec (JSON/TOML via
``OPTIONS["slo_path"]``, built-in defaults otherwise); ``slo.evaluate``
runs Google-SRE multi-window multi-burn-rate math over the always-on
metrics registry and walks a pending → firing → resolved alert state
machine (a page-severity fire triggers a flight dump + capture hint);
the background canary prober issues known-answer requests billed under
the reserved ``__canary__`` tenant — excluded from every user-facing
SLO — and a silently wrong answer burns the correctness budget while
availability correctly reads the replica as up. All of it is
deterministic under ``faults.slo_inject`` and none of it changes
results.
"""

from __future__ import annotations

import asyncio
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import flox_tpu
from flox_tpu import cache, exposition, faults, fleet, slo, telemetry
from flox_tpu.core import groupby_reduce
from flox_tpu.telemetry import METRICS


@pytest.fixture(autouse=True)
def _clean_plane():
    """Each test starts with telemetry OFF, an empty SLO plane, and no
    flight path — even under the CI instrumented leg."""
    with flox_tpu.set_options(
        telemetry=False, telemetry_export_path=None, flight_recorder_path=None,
        slo_path=None,
    ):
        cache.clear_all()  # stores/registry/SLO state must not leak across tests
        telemetry.reset()
        exposition.set_ready(False)
        yield
        cache.clear_all()
        telemetry.reset()
    exposition.stop_metrics_server()
    exposition.set_ready(False)


def _submit_canary_cycle(cycle=1):
    from flox_tpu.serve import Dispatcher

    async def go():
        dispatcher = Dispatcher()
        verdicts = await slo.canary_cycle(dispatcher, cycle)
        await dispatcher.close()
        return verdicts

    return asyncio.run(go())


# ---------------------------------------------------------------------------
# spec loading + validation
# ---------------------------------------------------------------------------


class TestSpec:
    def test_defaults_when_no_path(self):
        spec = slo.load_spec()
        names = [o["name"] for o in spec["objectives"]]
        assert names == ["latency", "availability", "correctness", "freshness"]
        assert [w["name"] for w in spec["windows"]] == ["fast", "slow"]
        fast = spec["windows"][0]
        assert (fast["short_s"], fast["long_s"], fast["burn_rate"]) == (
            300.0, 3600.0, 14.4,
        )
        assert fast["severity"] == "page"

    def test_json_path_roundtrip(self, tmp_path):
        p = tmp_path / "slo.json"
        p.write_text(json.dumps({
            "objectives": [
                {"name": "avail", "kind": "availability", "target": 0.99},
            ],
            "windows": [
                {"name": "only", "short_s": 60, "long_s": 600,
                 "burn_rate": 2.0, "severity": "page"},
            ],
        }))
        with flox_tpu.set_options(slo_path=str(p)):
            spec = slo.load_spec(force=True)
        assert spec["objectives"][0]["name"] == "avail"
        assert spec["windows"][0]["burn_rate"] == 2.0

    def test_toml_path(self, tmp_path):
        p = tmp_path / "slo.toml"
        p.write_text(
            "[[objectives]]\n"
            'name = "lat"\nkind = "latency"\ntarget = 0.95\nthreshold_ms = 50.0\n'
            "[[windows]]\n"
            'name = "w"\nshort_s = 60.0\nlong_s = 600.0\nburn_rate = 1.0\n'
        )
        try:
            spec = slo.load_spec(str(p), force=True)
        except ValueError as exc:
            # gated on interpreters without a TOML parser (< 3.11, no
            # tomli): the failure must be a clear spec error, not a bare
            # ModuleNotFoundError
            assert "TOML" in str(exc)
            return
        assert spec["objectives"][0]["threshold_ms"] == 50.0
        assert spec["windows"][0]["severity"] == "ticket"  # the default

    @pytest.mark.parametrize("bad", [
        {"objectives": []},
        {"objectives": [{"name": "x", "kind": "nope", "target": 0.9}]},
        {"objectives": [{"name": "x", "kind": "availability", "target": 1.5}]},
        {"objectives": [{"name": "a|b", "kind": "availability", "target": 0.9}]},
        {"objectives": [{"name": "x", "kind": "latency", "target": 0.9}]},  # no threshold
        {"objectives": [{"name": "x", "kind": "freshness", "target": 0.9}]},  # no staleness
        {"objectives": [{"name": "x", "kind": "availability", "target": 0.9,
                         "typo_key": 1}]},
        {"objectives": [{"name": "x", "kind": "availability", "target": 0.9}],
         "windows": [{"name": "w", "short_s": 600, "long_s": 60, "burn_rate": 1}]},
        {"objectives": [{"name": "x", "kind": "availability", "target": 0.9}],
         "surprise": True},
    ])
    def test_invalid_specs_raise(self, bad):
        with pytest.raises(ValueError, match="invalid SLO spec"):
            slo.validate_spec(bad)

    def test_unreadable_path_raises(self, tmp_path):
        with pytest.raises(ValueError, match="cannot read"):
            slo.load_spec(str(tmp_path / "missing.json"), force=True)
        garbage = tmp_path / "garbage.json"
        garbage.write_text("{nope")
        with pytest.raises(ValueError, match="cannot parse"):
            slo.load_spec(str(garbage), force=True)

    def test_per_objective_windows_override(self):
        spec = slo.validate_spec({
            "objectives": [{
                "name": "x", "kind": "availability", "target": 0.9,
                "windows": [{"name": "own", "short_s": 10, "long_s": 100,
                             "burn_rate": 3.0}],
            }],
        })
        assert spec["objectives"][0]["windows"][0]["name"] == "own"
        # the global windows stay the defaults
        assert [w["name"] for w in spec["windows"]] == ["fast", "slow"]


# ---------------------------------------------------------------------------
# burn-rate math + the alert state machine (deterministic via slo_inject)
# ---------------------------------------------------------------------------


class TestAlertLifecycle:
    def test_pending_firing_resolved(self, tmp_path):
        dump = tmp_path / "flight.jsonl"
        with flox_tpu.set_options(
            telemetry=True, flight_recorder_path=str(dump)
        ):
            with faults.slo_inject(clock0=1000.0) as plan:
                payload = slo.evaluate()
                assert payload["healthy"] is True  # idle baseline never pages
                plan.burst("availability", bad=500)
                plan.advance(60)
                payload = slo.evaluate()
                rows = {(a["objective"], a["window"]): a for a in payload["alerts"]}
                assert rows[("availability", "fast")]["state"] == "pending"
                assert payload["healthy"] is True  # pending is not yet an operator's problem
                plan.advance(60)
                payload = slo.evaluate()
                rows = {(a["objective"], a["window"]): a for a in payload["alerts"]}
                fast = rows[("availability", "fast")]
                assert fast["state"] == "firing" and fast["severity"] == "page"
                assert rows[("availability", "slow")]["state"] == "firing"
                assert payload["healthy"] is False
                obj = next(
                    o for o in payload["objectives"] if o["name"] == "availability"
                )
                assert obj["healthy"] is False
                # 100% bad traffic burns at 1/(1-0.999) = 1000x the budget
                assert fast["burn_short"] > 14.4
                assert obj["budget_remaining"] < 0
                assert METRICS.get("alert.pages") == 1
                assert METRICS.get("alert.fired") == 2
                # the page left its forensic record before any operator arrived
                assert dump.exists()
                events = [r.get("name") for r in telemetry.FLIGHT_RECORDER.records()]
                assert "alert-firing" in events and "capture-hint" in events
            # plan uninstalled: injected events vanish, deltas clamp to 0
            # burn — the incident is over and the alerts must resolve
            payload = slo.evaluate()
            assert payload["healthy"] is True
            assert all(a["state"] == "resolved" for a in payload["alerts"])
            assert METRICS.get("alert.resolved_total") == 2

    def test_one_evaluation_blip_never_fires(self):
        with faults.slo_inject(clock0=1000.0) as plan:
            slo.evaluate()
            plan.burst("availability", bad=50)
            plan.advance(60)
            payload = slo.evaluate()
            assert any(a["state"] == "pending" for a in payload["alerts"])
        # breach gone before the pending confirmed: the row is dropped,
        # not resolved — a blip never reaches an operator
        payload = slo.evaluate()
        assert payload["alerts"] == []
        assert METRICS.get("alert.fired") == 0

    def test_breach_requires_both_windows(self):
        # a burst entirely OLDER than the short window must not page:
        # burn_long is high but burn_short reads a quiet recent window
        with faults.slo_inject(clock0=1000.0) as plan:
            slo.evaluate()
            plan.burst("availability", bad=500)
            plan.advance(60)
            slo.evaluate()
            # stop burning; walk past the fast rule's short window (300s)
            plan.advance(400)
            slo.evaluate()
            payload = slo.evaluate()
            rows = {(a["objective"], a["window"]): a for a in payload["alerts"]}
            fast = rows.get(("availability", "fast"))
            assert fast is None or fast["state"] != "firing"


# ---------------------------------------------------------------------------
# SLI collectors
# ---------------------------------------------------------------------------


class TestCollectors:
    def test_latency_buckets_split_on_threshold(self):
        METRICS.observe("serve.request_ms", 5.0)       # <= 250ms: good
        METRICS.observe("serve.request_ms", 4000.0)    # > 250ms: bad
        payload = slo.evaluate()
        lat = next(o for o in payload["objectives"] if o["kind"] == "latency")
        assert (lat["good"], lat["bad"]) == (1.0, 1.0)

    def test_availability_taxonomy_excludes_drain_and_protocol(self):
        METRICS.inc("serve.requests", 10)
        METRICS.inc("serve.shed", 2)
        METRICS.inc("serve.drain_rejected", 5)   # planned: not a burn
        METRICS.inc("serve.protocol_errors", 3)  # caller's bug: not a burn
        payload = slo.evaluate()
        avail = next(o for o in payload["objectives"] if o["kind"] == "availability")
        assert (avail["good"], avail["bad"]) == (8.0, 2.0)

    def test_freshness_ticks_from_store_staleness(self, tmp_path):
        from flox_tpu.serve import stores as serve_stores

        spec_path = tmp_path / "slo.json"
        spec_path.write_text(json.dumps({
            "objectives": [{"name": "fresh", "kind": "freshness",
                            "target": 0.9, "max_staleness_s": 100.0}],
        }))
        with flox_tpu.set_options(
            store_root=str(tmp_path / "stores"), slo_path=str(spec_path)
        ):
            serve_stores.append(
                "user-store", np.array([0, 1]), np.array([1.0, 2.0]),
                slab_id="s0", create={"funcs": ["sum"], "size": 2},
            )
            serve_stores.append(
                slo.CANARY_STORE, np.array([0, 1]), np.array([1.0, 2.0]),
                slab_id="s0", create={"funcs": ["sum"], "size": 2},
            )
            payload = slo.evaluate()
            fresh = next(o for o in payload["objectives"] if o["name"] == "fresh")
            # both stores just appended: one good tick (canary excluded)
            assert (fresh["good"], fresh["bad"]) == (1.0, 0.0)
            # backdate BOTH stores past the staleness budget
            for entry in serve_stores._STORE_TABLE.values():
                entry.last_ack -= 1000.0
            payload = slo.evaluate()
            fresh = next(o for o in payload["objectives"] if o["name"] == "fresh")
            # exactly one bad tick accrued: the canary store stayed excluded
            assert (fresh["good"], fresh["bad"]) == (1.0, 1.0)

    def test_staleness_gauges_published(self, tmp_path):
        from flox_tpu.serve import stores as serve_stores

        with flox_tpu.set_options(store_root=str(tmp_path)):
            serve_stores.append(
                "gauged", np.array([0]), np.array([1.0]),
                slab_id="s0", create={"funcs": ["sum"], "size": 1},
            )
            telemetry.sample_resident_state()
            assert METRICS.get("store.staleness_s|store=gauged") >= 0.0
            assert METRICS.get("store.open_stores") >= 1.0


# ---------------------------------------------------------------------------
# the canary prober + reserved-tenant exclusion (satellite 3)
# ---------------------------------------------------------------------------


class TestCanary:
    def test_cycle_all_green_without_store_root(self):
        with flox_tpu.set_options(telemetry=True):
            verdicts = _submit_canary_cycle()
        assert verdicts["reduce"] is True
        assert verdicts["multistat"] is True
        assert verdicts["dataset"] is True
        assert verdicts["store"] is None  # skipped: no store root
        assert METRICS.get("canary.ok") == 3.0
        assert METRICS.get("canary.failures") == 0.0

    def test_store_probe_roundtrips(self, tmp_path):
        with flox_tpu.set_options(telemetry=True, store_root=str(tmp_path)):
            verdicts = _submit_canary_cycle()
            assert verdicts["store"] is True
            # the constant slab id makes cycle 2 an exactly-once replay
            verdicts = _submit_canary_cycle(cycle=2)
            assert verdicts["store"] is True

    def test_canary_billed_outside_user_slos(self):
        with flox_tpu.set_options(telemetry=True):
            _submit_canary_cycle()
            # canary traffic counts under canary.requests, never the
            # availability denominator
            assert METRICS.get("serve.requests") == 0.0
            assert METRICS.get("canary.requests") == 3.0
            # no user-facing cost row: the ledger hides the reserved tenant
            assert slo.CANARY_TENANT not in telemetry.cost_by_tenant()
            assert slo.CANARY_TENANT in telemetry.cost_by_tenant(
                include_canary=True
            )
            # the base request histogram saw nothing
            hist = METRICS.histograms().get("serve.request_ms")
            assert hist is None or hist["count"] == 0

    def test_canary_never_consumes_a_tenant_slot(self):
        with flox_tpu.set_options(telemetry=True):
            for i in range(telemetry._TENANT_MAX):
                telemetry.tenant_label(f"t{i}")
            # the table is full; real new tenants fold into _other but the
            # reserved canary label keeps resolving to itself
            assert telemetry.tenant_label("newcomer") == "_other"
            assert telemetry.tenant_label(slo.CANARY_TENANT) == slo.CANARY_TENANT
            assert slo.CANARY_TENANT not in telemetry._TENANT_LABELS

    def test_injected_wrong_answer_burns_correctness_not_availability(self):
        with flox_tpu.set_options(telemetry=True):
            with faults.slo_inject(corrupt_canary={"reduce": 1}):
                verdicts = _submit_canary_cycle()
            assert verdicts["reduce"] is False
            assert METRICS.get("canary.failures") == 1.0
            assert METRICS.get("canary.failures|op=reduce") == 1.0
            payload = slo.evaluate()
            correctness = next(
                o for o in payload["objectives"] if o["kind"] == "correctness"
            )
            availability = next(
                o for o in payload["objectives"] if o["kind"] == "availability"
            )
            assert correctness["bad"] == 1.0
            # the replica answered every request: availability saw NOTHING
            assert (availability["good"], availability["bad"]) == (0.0, 0.0)
            events = [r.get("name") for r in telemetry.FLIGHT_RECORDER.records()]
            assert "canary-failure" in events

    def test_wildcard_corruption_hits_every_op(self):
        with flox_tpu.set_options(telemetry=True):
            with faults.slo_inject(corrupt_canary={"*": -1}):
                verdicts = _submit_canary_cycle()
            assert verdicts["reduce"] is False
            assert verdicts["multistat"] is False
            assert verdicts["dataset"] is False


# ---------------------------------------------------------------------------
# surfaces: endpoints, CLI, report, flight-dump header, cache panels
# ---------------------------------------------------------------------------


class TestSurfaces:
    def _get(self, port, path):
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        )

    def test_slo_and_alerts_endpoints(self):
        with flox_tpu.set_options(telemetry=True):
            port = exposition.start_metrics_server(port=0)
            resp = self._get(port, "/slo")
            assert resp.status == 200
            payload = json.loads(resp.read())
            assert payload["healthy"] is True
            assert {o["kind"] for o in payload["objectives"]} == {
                "latency", "availability", "correctness", "freshness",
            }
            assert "replica" in payload
            resp = self._get(port, "/alerts")
            body = json.loads(resp.read())
            assert body["alerts"] == [] and body["healthy"] is True
            # seeding published the gauges before any scrape-side math
            assert METRICS.get("slo.objectives") == 4.0

    def test_bad_spec_is_a_500_not_a_silent_pass(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"objectives": [{"name": "x"}]}))
        with flox_tpu.set_options(telemetry=True, slo_path=str(bad)):
            port = exposition.start_metrics_server(port=0)
            with pytest.raises(urllib.error.HTTPError) as err:
                self._get(port, "/slo")
            assert err.value.code == 500
            assert "invalid SLO spec" in json.loads(err.value.read())["error"]
            # server start survived the bad spec, loudly
            assert METRICS.get("slo.spec_errors") >= 1.0

    def test_cli_exit_codes_gate_deploys(self, capsys):
        assert telemetry.main(["slo"]) == 0
        out = capsys.readouterr().out
        assert "availability" in out and "burn" in out
        with faults.slo_inject(clock0=1000.0) as plan:
            slo.evaluate()
            plan.burst("availability", bad=500)
            plan.advance(60)
            slo.evaluate()
            plan.advance(60)
            assert telemetry.main(["slo"]) == 2  # firing = deploy gate shut
            out = capsys.readouterr().out
            assert "FIRING" in out.upper()

    def test_cli_reads_slo_scrape_file(self, tmp_path, capsys):
        payload = slo.evaluate()
        p = tmp_path / "scrape.json"
        p.write_text(json.dumps(payload))
        assert telemetry.main(["slo", str(p)]) == 0
        assert "availability" in capsys.readouterr().out

    def test_flight_dump_header_and_report_carry_alert_state(
        self, tmp_path, capsys
    ):
        dump = tmp_path / "flight.jsonl"
        with flox_tpu.set_options(
            telemetry=True, flight_recorder_path=str(dump)
        ):
            with faults.slo_inject(clock0=1000.0) as plan:
                slo.evaluate()
                plan.burst("availability", bad=500)
                plan.advance(60)
                slo.evaluate()
                plan.advance(60)
                slo.evaluate()  # fires the page -> dumps the flight ring
                header = json.loads(dump.read_text().splitlines()[0])
                snap = header["attrs"]["alerts"]
                assert "availability/fast[page]" in snap["firing"]
        assert telemetry.main(["report", str(dump)]) == 0
        out = capsys.readouterr().out
        assert "slo / alert plane:" in out
        assert "alert-firing" in out

    def test_cache_stats_panel_and_clear_all(self):
        with faults.slo_inject(clock0=1000.0) as plan:
            slo.evaluate()
            plan.burst("availability", bad=500)
            plan.advance(60)
            slo.evaluate()
            plan.advance(60)
            slo.evaluate()
            panel = cache.stats()["slo"]
            assert panel["alerts"]["firing"] == 2
            assert panel["snapshots"] == 3
            cache.clear_all()
            assert slo.alerts() == []
            assert slo.slo_stats()["snapshots"] == 0


# ---------------------------------------------------------------------------
# fleet federation (satellite 1)
# ---------------------------------------------------------------------------


def _snapshot(name, *, datasets=(), stores=(), slo_payload=None):
    snap = fleet.ReplicaSnapshot(name=name, url=f"http://h/{name}", ok=True)
    snap.metrics = {"counters": {}, "gauges": {}, "histograms": {}, "replica": name}
    snap.datasets = {"datasets": list(datasets)}
    snap.stores = {"stores": list(stores)}
    snap.slo = slo_payload or {}
    snap.alerts = list((slo_payload or {}).get("alerts") or [])
    return snap


class TestFleetFederation:
    def test_resident_state_and_alerts_federate(self):
        s1 = _snapshot(
            "r1",
            datasets=[{"name": "ds", "nbytes": 100, "pins": 1, "hits": 7}],
            stores=[{"store": "st", "gen": 3, "nbytes": 50, "staleness_s": 12.0}],
            slo_payload={
                "healthy": False,
                "objectives": [{"name": "availability", "kind": "availability",
                                "healthy": False, "budget_remaining": -1.0}],
                "alerts": [{"objective": "availability", "window": "fast",
                            "severity": "page", "state": "firing",
                            "burn_short": 20.0, "burn_long": 15.0}],
            },
        )
        s2 = _snapshot(
            "r2",
            datasets=[{"name": "ds", "nbytes": 100, "pins": 0, "hits": 2}],
            stores=[{"store": "st", "gen": 4, "nbytes": 60, "staleness_s": 3.0}],
            slo_payload={"healthy": True, "objectives": [], "alerts": []},
        )
        view = fleet.federate([s1, s2])
        assert view["datasets"]["ds"]["bytes"] == 200
        assert view["datasets"]["ds"]["replicas"]["r1"]["pins"] == 1
        assert view["stores"]["st"]["generations"] == {"r1": 3, "r2": 4}
        assert view["stores"]["st"]["state_bytes"] == 110
        # the freshest copy speaks for the fleet
        assert view["stores"]["st"]["staleness_s"] == 3.0
        assert len(view["alerts"]) == 1
        assert view["alerts"][0]["replica"] == "r1"
        assert view["slo"]["r1"]["healthy"] is False
        assert view["slo"]["r2"]["healthy"] is True

    def test_top_views_carry_resident_and_alert_columns(self):
        s1 = _snapshot(
            "r1",
            datasets=[{"name": "ds", "nbytes": 100, "pins": 1, "hits": 7}],
            stores=[{"store": "st", "gen": 3, "nbytes": 50, "staleness_s": 12.0}],
            slo_payload={
                "healthy": False,
                "objectives": [],
                "alerts": [
                    {"objective": "availability", "window": "fast",
                     "severity": "page", "state": "firing",
                     "burn_short": 20.0, "burn_long": 15.0},
                    {"objective": "availability", "window": "slow",
                     "severity": "ticket", "state": "pending",
                     "burn_short": 2.0, "burn_long": 1.5},
                ],
            },
        )
        view = fleet.federate([s1])
        frame = fleet.render_top_json(view)
        row = frame["replicas"][0]
        assert row["datasets"] == 1 and row["dataset_bytes"] == 100
        assert row["stores"] == 1 and row["staleness_s"] == 12.0
        assert row["alerts_firing"] == 1 and row["alerts_pending"] == 1
        assert row["slo_healthy"] is False
        assert len(frame["alerts"]) == 2
        text = fleet.render_top(view)
        assert "alerts" in text          # the column header
        assert "1F/1P" in text           # firing/pending cell
        assert "availability/fast" in text

    def test_dedup_keeps_most_live_state(self):
        # one replica double-reporting an alert: firing beats resolved
        s = _snapshot("r1", slo_payload={"healthy": False, "objectives": [], "alerts": []})
        s.alerts = [
            {"objective": "o", "window": "w", "severity": "ticket",
             "state": "resolved"},
            {"objective": "o", "window": "w", "severity": "page",
             "state": "firing"},
        ]
        view = fleet.federate([s])
        assert len(view["alerts"]) == 1
        assert view["alerts"][0]["state"] == "firing"

    def test_federator_endpoints_serve_alerts_and_slo(self):
        fed = fleet.Federator([], interval=3600)
        s1 = _snapshot(
            "r1",
            slo_payload={
                "healthy": False,
                "objectives": [],
                "alerts": [{"objective": "availability", "window": "fast",
                            "severity": "page", "state": "firing"}],
            },
        )
        with fed._lock:
            fed._view = fleet.federate([s1])
        port = fed.serve(port=0)
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/alerts", timeout=5).read())
            assert body["firing"] == 1 and body["healthy"] is False
            assert body["alerts"][0]["replica"] == "r1"
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/slo", timeout=5).read())
            assert body["healthy"] is False
            assert body["replicas"]["r1"]["healthy"] is False
        finally:
            fed.stop()


# ---------------------------------------------------------------------------
# plane neutrality
# ---------------------------------------------------------------------------


class TestPlaneNeutrality:
    def test_bit_identity_with_slo_plane_enabled(self):
        vals = np.random.default_rng(3).normal(size=(4, 64)).astype(np.float64)
        codes = np.arange(64) % 7
        baseline, _ = groupby_reduce(vals, codes, func="nanmean", engine="jax")
        with flox_tpu.set_options(telemetry=True):
            with faults.slo_inject(clock0=1000.0) as plan:
                slo.evaluate()
                plan.burst("availability", bad=500)
                plan.advance(60)
                slo.evaluate()
                _submit_canary_cycle()
                lit, _ = groupby_reduce(vals, codes, func="nanmean", engine="jax")
        assert np.asarray(baseline).tobytes() == np.asarray(lit).tobytes()

    def test_evaluate_without_serve_layer_is_healthy(self):
        # a pure-library process (no dispatcher, no stores) evaluates to
        # a vacuously healthy plane, not an import error
        payload = slo.evaluate()
        assert payload["healthy"] is True
        assert all(o["good"] == 0 and o["bad"] == 0 for o in payload["objectives"])
