"""Cohort detection scenarios (reference: tests/test_cohorts.py:10-29 and
the snapshot suite — here expressed as explicit expectations on realistic
chunking patterns)."""

import numpy as np
import pytest

from flox_tpu.cohorts import chunks_from_shards, find_group_cohorts


def test_single_chunk_is_blockwise():
    labels = np.array([0, 0, 1, 1, 2])
    method, mapping = find_group_cohorts(labels, (5,))
    assert method == "blockwise"
    assert mapping == {(0,): [0, 1, 2]}


def test_one_chunk_per_label_is_blockwise():
    # sorted labels, chunk boundaries on group boundaries
    labels = np.repeat([0, 1, 2, 3], 4)
    method, mapping = find_group_cohorts(labels, (4, 4, 4, 4))
    assert method == "blockwise"
    assert mapping == {(0,): [0], (1,): [1], (2,): [2], (3,): [3]}


def test_all_labels_everywhere_is_mapreduce():
    # every chunk contains every label (random big array case)
    labels = np.tile([0, 1, 2, 3], 8)
    method, mapping = find_group_cohorts(labels, (8, 8, 8, 8))
    assert method == "map-reduce"
    assert mapping == {}


def test_periodic_labels_form_cohorts():
    # day-of-year-like pattern: each chunk sees a distinct label subset,
    # repeating across "years" -> cohorts
    nyears, nlabels, chunksize = 4, 12, 3
    labels = np.tile(np.arange(nlabels), nyears)  # 4 years of 12 months
    chunks = chunks_from_shards(len(labels), len(labels) // chunksize)
    method, mapping = find_group_cohorts(labels, chunks)
    assert method == "cohorts"
    # every label appears in exactly one cohort
    all_labels = sorted(lab for labs in mapping.values() for lab in labs)
    assert all_labels == list(range(nlabels))
    # months 0-2 always land in the same chunks -> same cohort
    for labs in mapping.values():
        assert labs in ([0, 1, 2], [3, 4, 5], [6, 7, 8], [9, 10, 11])


def test_era5_dayofyear_like():
    # hourly data, chunks of 48h: each chunk covers 2 days; day-of-year
    # groups recur yearly -> cohorts
    nhours = 24 * 365 * 2
    day = (np.arange(nhours) // 24) % 365
    chunks = chunks_from_shards(nhours, nhours // 48)
    method, mapping = find_group_cohorts(day, chunks, expected_groups=range(365))
    assert method == "cohorts"
    # each day of year recurs in a small chunk subset; cohorts stay granular
    ncohorts = len(mapping)
    assert 100 < ncohorts <= 365
    # every label assigned exactly once
    all_labels = sorted(lab for labs in mapping.values() for lab in labs)
    assert all_labels == list(range(365))


def test_chunks_from_shards():
    assert chunks_from_shards(10, 4) == (3, 3, 3, 1)
    assert chunks_from_shards(8, 4) == (2, 2, 2, 2)
    assert sum(chunks_from_shards(111, 8)) == 111


def test_auto_method_selection_on_mesh():
    # core wires find_group_cohorts when mesh given without method
    import jax

    from flox_tpu import groupby_reduce
    from flox_tpu.parallel import make_mesh

    mesh = make_mesh()
    labels = np.tile([0, 1, 2], 80)
    vals = np.arange(240.0)
    out, _ = groupby_reduce(vals, labels, func="nanmean", mesh=mesh)
    expected = [np.mean(vals[labels == g]) for g in range(3)]
    np.testing.assert_allclose(np.asarray(out), expected)


def test_merge_false_returns_per_label_cohorts():
    # chunks of 3 over a 12-cycle: labels {0,1,2} share chunks, so merge=True
    # fuses them while merge=False keeps raw per-chunk-set cohorts
    labels = np.tile(np.arange(12), 24)
    chunks = chunks_from_shards(len(labels), len(labels) // 3)
    method, merged = find_group_cohorts(labels, chunks, merge=True)
    method2, raw = find_group_cohorts(labels, chunks, merge=False)
    assert method == method2 == "cohorts"
    assert sum(len(v) for v in raw.values()) == 12
    assert len(raw) >= len(merged)


def test_cohorts_memoized():
    from flox_tpu.cohorts import _COHORTS_CACHE

    _COHORTS_CACHE.clear()
    labels = np.tile(np.arange(12), 100)
    chunks = chunks_from_shards(len(labels), 8)
    r1 = find_group_cohorts(labels, chunks)
    r2 = find_group_cohorts(labels, chunks)
    assert r1 is r2  # cache hit returns the same object


# --- the remaining reference snapshot scenarios (test_cohorts.py:10-29,
# asv_bench/benchmarks/cohorts.py) as explicit expectations -----------------


def test_oisst_daily_dayofyear():
    # OISST: ~40 years of daily data in chunks of 10 days; each dayofyear
    # label recurs yearly in a small chunk subset -> cohorts
    ndays = 365 * 40
    day = np.arange(ndays) % 365
    chunks = chunks_from_shards(ndays, ndays // 10)
    method, mapping = find_group_cohorts(day, chunks, expected_groups=range(365))
    assert method == "cohorts"
    labels = sorted(lab for labs in mapping.values() for lab in labs)
    assert labels == list(range(365))


def test_perfect_monthly():
    # monthly data chunked by 4: quarters repeat exactly -> 3 clean cohorts
    nyears = 20
    month = np.arange(12 * nyears) % 12
    chunks = chunks_from_shards(len(month), len(month) // 4)
    method, mapping = find_group_cohorts(month, chunks, expected_groups=range(12))
    assert method == "cohorts"
    assert sorted(map(sorted, mapping.values())) == [
        [0, 1, 2, 3], [4, 5, 6, 7], [8, 9, 10, 11]
    ]


def test_perfect_blockwise_resampling():
    # downsampling to a frequency aligned with chunk boundaries: every
    # output group lives in exactly one chunk -> blockwise
    n = 240
    by = np.arange(n) // 24  # daily groups over hourly data
    chunks = chunks_from_shards(n, n // 24)  # chunk == day
    method, mapping = find_group_cohorts(by, chunks, expected_groups=range(10))
    assert method == "blockwise"
    assert len(mapping) == 10


def test_era5_google_per_timestep_chunks():
    # ERA5-Google: chunks of 1 along time; every chunk holds exactly one
    # label occurrence but labels span many chunks -> cohorts (the
    # chunksize-1 branch of the reference ladder, cohorts.py:192-199)
    n = 365 * 2
    day = np.arange(n) % 365
    chunks = chunks_from_shards(n, n)  # one element per chunk
    method, mapping = find_group_cohorts(day, chunks, expected_groups=range(365))
    assert method == "cohorts"
    # each label's cohort = its two yearly chunk positions
    assert all(len(cset) == 2 for cset in mapping)


def test_nwm_2d_labels():
    # NWM county zonal stats: 2-D integer label map flattened; ~900 labels
    # scattered over spatial chunks with high overlap -> map-reduce
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 900, size=(450, 360)).reshape(-1)
    chunks = chunks_from_shards(labels.size, 25)
    method, mapping = find_group_cohorts(labels, chunks, expected_groups=range(900))
    assert method == "map-reduce"
    assert mapping == {}


def test_random_big_array():
    # RandomBigArray: 5000 random labels, every chunk sees a wide spread ->
    # containment is dense -> map-reduce
    rng = np.random.default_rng(1)
    labels = rng.integers(0, 5000, size=200_000)
    chunks = chunks_from_shards(labels.size, 20)
    method, mapping = find_group_cohorts(labels, chunks, expected_groups=range(5000))
    assert method == "map-reduce"
    assert mapping == {}


def test_era5_monthhour():
    # grouping by (month, hour) products: 288 labels recurring daily; with
    # 48h chunks each label recurs in half the chunks of its month pair
    nhours = 24 * 365
    hour = np.arange(nhours) % 24
    month = ((np.arange(nhours) // 24) % 365 // 30.44).astype(np.int64) % 12
    mh = month * 24 + hour
    chunks = chunks_from_shards(nhours, nhours // 48)
    method, mapping = find_group_cohorts(mh, chunks, expected_groups=range(288))
    assert method == "cohorts"
    labels = sorted(lab for labs in mapping.values() for lab in labs)
    assert labels == list(range(288))


# --- full-output snapshot pinning (VERDICT r3 #9; parity: the reference's
# syrupy snapshots, tests/test_cohorts.py:10-29 + __snapshots__/*.ambr).
# Any change in detection output fails loudly; regenerate intentionally with
# FLOX_UPDATE_SNAPSHOTS=1 python -m pytest tests/test_cohorts.py -k snapshot


def _snapshot_path(name):
    import os

    return os.path.join(
        os.path.dirname(__file__), "__snapshots__", "cohorts", f"{name}.json"
    )


def _canonical(method, mapping):
    return {
        "method": method,
        "cohorts": {
            ",".join(map(str, k)): sorted(int(x) for x in v)
            for k, v in sorted(mapping.items())
        },
    }


from cohort_scenarios import SCENARIOS


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_snapshot_cohorts(name):
    import json
    import os

    labels, chunks, size = SCENARIOS[name]()
    method, mapping = find_group_cohorts(labels, chunks, expected_groups=range(size))
    got = _canonical(method, mapping)
    path = _snapshot_path(name)
    if os.environ.get("FLOX_UPDATE_SNAPSHOTS"):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(got, f, indent=0, sort_keys=True)
            f.write("\n")
        pytest.skip(f"snapshot for {name} regenerated")
    with open(path) as f:
        want = json.load(f)
    assert got["method"] == want["method"], name
    assert got["cohorts"] == want["cohorts"], name
