"""Cohort detection scenarios (reference: tests/test_cohorts.py:10-29 and
the snapshot suite — here expressed as explicit expectations on realistic
chunking patterns)."""

import numpy as np
import pytest

from flox_tpu.cohorts import chunks_from_shards, find_group_cohorts


def test_single_chunk_is_blockwise():
    labels = np.array([0, 0, 1, 1, 2])
    method, mapping = find_group_cohorts(labels, (5,))
    assert method == "blockwise"
    assert mapping == {(0,): [0, 1, 2]}


def test_one_chunk_per_label_is_blockwise():
    # sorted labels, chunk boundaries on group boundaries
    labels = np.repeat([0, 1, 2, 3], 4)
    method, mapping = find_group_cohorts(labels, (4, 4, 4, 4))
    assert method == "blockwise"
    assert mapping == {(0,): [0], (1,): [1], (2,): [2], (3,): [3]}


def test_all_labels_everywhere_is_mapreduce():
    # every chunk contains every label (random big array case)
    labels = np.tile([0, 1, 2, 3], 8)
    method, mapping = find_group_cohorts(labels, (8, 8, 8, 8))
    assert method == "map-reduce"
    assert mapping == {}


def test_periodic_labels_form_cohorts():
    # day-of-year-like pattern: each chunk sees a distinct label subset,
    # repeating across "years" -> cohorts
    nyears, nlabels, chunksize = 4, 12, 3
    labels = np.tile(np.arange(nlabels), nyears)  # 4 years of 12 months
    chunks = chunks_from_shards(len(labels), len(labels) // chunksize)
    method, mapping = find_group_cohorts(labels, chunks)
    assert method == "cohorts"
    # every label appears in exactly one cohort
    all_labels = sorted(lab for labs in mapping.values() for lab in labs)
    assert all_labels == list(range(nlabels))
    # months 0-2 always land in the same chunks -> same cohort
    for labs in mapping.values():
        assert labs in ([0, 1, 2], [3, 4, 5], [6, 7, 8], [9, 10, 11])


def test_era5_dayofyear_like():
    # hourly data, chunks of 48h: each chunk covers 2 days; day-of-year
    # groups recur yearly -> cohorts
    nhours = 24 * 365 * 2
    day = (np.arange(nhours) // 24) % 365
    chunks = chunks_from_shards(nhours, nhours // 48)
    method, mapping = find_group_cohorts(day, chunks, expected_groups=range(365))
    assert method == "cohorts"
    # each day of year recurs in a small chunk subset; cohorts stay granular
    ncohorts = len(mapping)
    assert 100 < ncohorts <= 365
    # every label assigned exactly once
    all_labels = sorted(lab for labs in mapping.values() for lab in labs)
    assert all_labels == list(range(365))


def test_chunks_from_shards():
    assert chunks_from_shards(10, 4) == (3, 3, 3, 1)
    assert chunks_from_shards(8, 4) == (2, 2, 2, 2)
    assert sum(chunks_from_shards(111, 8)) == 111


def test_auto_method_selection_on_mesh():
    # core wires find_group_cohorts when mesh given without method
    import jax

    from flox_tpu import groupby_reduce
    from flox_tpu.parallel import make_mesh

    mesh = make_mesh()
    labels = np.tile([0, 1, 2], 80)
    vals = np.arange(240.0)
    out, _ = groupby_reduce(vals, labels, func="nanmean", mesh=mesh)
    expected = [np.mean(vals[labels == g]) for g in range(3)]
    np.testing.assert_allclose(np.asarray(out), expected)


def test_merge_false_returns_per_label_cohorts():
    # chunks of 3 over a 12-cycle: labels {0,1,2} share chunks, so merge=True
    # fuses them while merge=False keeps raw per-chunk-set cohorts
    labels = np.tile(np.arange(12), 24)
    chunks = chunks_from_shards(len(labels), len(labels) // 3)
    method, merged = find_group_cohorts(labels, chunks, merge=True)
    method2, raw = find_group_cohorts(labels, chunks, merge=False)
    assert method == method2 == "cohorts"
    assert sum(len(v) for v in raw.values()) == 12
    assert len(raw) >= len(merged)


def test_cohorts_memoized():
    from flox_tpu.cohorts import _COHORTS_CACHE

    _COHORTS_CACHE.clear()
    labels = np.tile(np.arange(12), 100)
    chunks = chunks_from_shards(len(labels), 8)
    r1 = find_group_cohorts(labels, chunks)
    r2 = find_group_cohorts(labels, chunks)
    assert r1 is r2  # cache hit returns the same object
