"""Widened property-based invariants (VERDICT #7; reference
tests/test_properties.py:187-332 + strategies.py:52-190).

Beyond test_properties.py: dtype breadth (int8..int64, f32, complex,
datetime64), N up to 1000, NaN labels, the mesh path, the
scans-vs-per-group-loop oracle, and first/last duality ON the mesh.

Shapes are drawn from a fixed menu so jit/shard_map program caches hit —
the property space explores data/labels/dtypes, not trace shapes.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")

from hypothesis import assume, given, settings, strategies as st
from hypothesis.extra.numpy import arrays

from flox_tpu.core import groupby_reduce
from flox_tpu.scan import groupby_scan

# fixed shape menu: every (n, size) pair compiles once, then 200+ examples replay
N_CHOICES = [1, 2, 3, 7, 31, 64, 257, 1000]
NLABELS = 6

INT_KINDS = ["int8", "int16", "int32", "int64"]
FUNCS_INT = ["sum", "nansum", "min", "max", "count", "first", "last", "mean", "var"]
FUNCS_FLOAT = ["sum", "nansum", "mean", "nanmean", "min", "nanmin", "max", "nanmax",
               "var", "nanvar", "count", "first", "last", "nanfirst", "nanlast"]
FUNCS_COMPLEX = ["sum", "nansum", "mean", "nanmean", "count", "first", "last"]
FUNCS_DT = ["min", "max", "nanmin", "nanmax", "count", "first", "last",
            "nanfirst", "nanlast", "mean", "nanmean"]


@st.composite
def labels_strategy(draw, n, with_nan_labels=True):
    opts = [float(g) for g in range(NLABELS)]
    if with_nan_labels:
        opts.append(np.nan)
    labels = draw(arrays(np.float64, (n,), elements=st.sampled_from(opts)))
    assume(not np.all(np.isnan(labels)))  # zero groups is a defined error
    return labels


@st.composite
def typed_case(draw):
    n = draw(st.sampled_from(N_CHOICES))
    labels = draw(labels_strategy(n))
    kind = draw(st.sampled_from(INT_KINDS + ["float32", "float64", "complex128", "datetime64"]))
    if kind in INT_KINDS:
        info = np.iinfo(kind)
        bound = min(int(info.max), 2**40 // (n + 1))  # sums stay exact in i64/f64
        vals = draw(arrays(np.dtype(kind), (n,), elements=st.integers(max(-bound, int(info.min)), bound)))
        funcs = FUNCS_INT
    elif kind == "float32":
        vals = draw(arrays(np.float32, (n,), elements=st.one_of(
            st.floats(-1e3, 1e3, width=32, allow_nan=False), st.just(np.float32(np.nan)))))
        funcs = FUNCS_FLOAT
    elif kind == "float64":
        vals = draw(arrays(np.float64, (n,), elements=st.one_of(
            st.floats(-1e6, 1e6, allow_nan=False), st.just(np.nan))))
        funcs = FUNCS_FLOAT
    elif kind == "complex128":
        fl = st.floats(-1e6, 1e6, allow_nan=False)
        vals = draw(arrays(np.complex128, (n,), elements=st.builds(complex, fl, fl)))
        funcs = FUNCS_COMPLEX
    else:  # datetime64[ns]
        ns = st.one_of(
            st.integers(0, 10**15), st.just(np.iinfo(np.int64).min)  # NaT
        )
        vals = draw(arrays(np.int64, (n,), elements=ns)).view("datetime64[ns]")
        funcs = FUNCS_DT
    func = draw(st.sampled_from(funcs))
    return vals, labels, kind, func


def _tol(kind, func):
    if kind == "float32":
        return dict(rtol=2e-3, atol=2e-3)  # different summation trees in f32
    if kind == "datetime64" and func in ("mean", "nanmean"):
        return dict(rtol=0, atol=0)  # compared as int ns after identical rounding
    if func in ("var", "nanvar"):
        return dict(rtol=1e-8, atol=1e-6)
    return dict(rtol=1e-10, atol=1e-10)


@settings(max_examples=250, deadline=None)
@given(case=typed_case())
def test_engines_agree_wide(case):
    """jax engine == numpy engine over the full dtype surface, NaN labels
    included (the reference's chunked==eager analogue, :187-219)."""
    vals, labels, kind, func = case
    a, ga = groupby_reduce(vals, labels, func=func, engine="jax")
    b, gb = groupby_reduce(vals, labels, func=func, engine="numpy")
    np.testing.assert_array_equal(ga, gb)
    a, b = np.asarray(a), np.asarray(b)
    if a.dtype.kind in "Mm" or b.dtype.kind in "Mm":
        np.testing.assert_array_equal(a, b)
    elif a.dtype.kind == "c":
        np.testing.assert_allclose(a, b, **_tol(kind, func), equal_nan=True)
    else:
        np.testing.assert_allclose(
            a.astype(np.float64), b.astype(np.float64), **_tol(kind, func), equal_nan=True
        )


@st.composite
def mesh_case(draw):
    n = draw(st.sampled_from([64, 96, 256]))
    labels = draw(labels_strategy(n))
    vals = draw(arrays(np.float64, (n,), elements=st.one_of(
        st.floats(-1e6, 1e6, allow_nan=False), st.just(np.nan))))
    func = draw(st.sampled_from(
        ["sum", "nansum", "mean", "nanmean", "max", "nanmax", "min", "nanmin",
         "var", "nanvar", "count", "nanargmax", "nanargmin"]))
    method = draw(st.sampled_from(["map-reduce", "cohorts"]))
    return vals, labels, func, method


@pytest.fixture(scope="module")
def mesh8():
    from flox_tpu.parallel import make_mesh

    return make_mesh(8)


@settings(max_examples=200, deadline=None)
@given(case=mesh_case())
def test_mesh_equals_eager(case, mesh8):
    """Every mesh method reproduces the eager result on arbitrary data —
    the reference proves the same for its dask methods via the sync
    scheduler (test_core.py:65)."""
    vals, labels, func, method = case
    eager, ge = groupby_reduce(vals, labels, func=func, engine="jax")
    mesh_r, gm = groupby_reduce(vals, labels, func=func, method=method, mesh=mesh8)
    np.testing.assert_array_equal(ge, gm)
    np.testing.assert_allclose(
        np.asarray(mesh_r).astype(np.float64), np.asarray(eager).astype(np.float64),
        rtol=1e-10, atol=1e-10, equal_nan=True,
    )


@settings(max_examples=200, deadline=None)
@given(
    n=st.sampled_from([64, 96, 256]),
    data=st.data(),
)
def test_first_last_duality_on_mesh(n, data, mesh8):
    """nanfirst == nanlast of the reversed axis, ON the mesh (the reference
    checks this eagerly, :295-332; here the carry/ownership logic is what's
    under test)."""
    labels = data.draw(labels_strategy(n))
    vals = data.draw(arrays(np.float64, (n,), elements=st.one_of(
        st.floats(-1e6, 1e6, allow_nan=False), st.just(np.nan))))
    f, gf = groupby_reduce(vals, labels, func="nanfirst", method="map-reduce", mesh=mesh8)
    l, gl = groupby_reduce(vals[::-1].copy(), labels[::-1].copy(), func="nanlast",
                           method="map-reduce", mesh=mesh8)
    np.testing.assert_array_equal(gf, gl)
    np.testing.assert_allclose(np.asarray(f), np.asarray(l), equal_nan=True)


@settings(max_examples=250, deadline=None)
@given(
    n=st.sampled_from(N_CHOICES),
    data=st.data(),
    func=st.sampled_from(["cumsum", "nancumsum", "ffill", "bfill"]),
)
def test_scan_vs_per_group_loop(n, data, func):
    """Scans against a per-group numpy loop oracle (reference
    test_properties.py:227-265)."""
    labels_f = data.draw(labels_strategy(n, with_nan_labels=False))
    labels = labels_f.astype(np.int64)
    vals = data.draw(arrays(np.float64, (n,), elements=st.one_of(
        st.floats(-1e6, 1e6, allow_nan=False), st.just(np.nan))))
    got = np.asarray(groupby_scan(vals, labels, func=func, engine="numpy"))

    expected = np.empty_like(vals)
    for g in np.unique(labels):
        sel = np.flatnonzero(labels == g)
        grp = vals[sel]
        if func == "cumsum":
            expected[sel] = np.cumsum(grp)
        elif func == "nancumsum":
            expected[sel] = np.nancumsum(grp)
        elif func in ("ffill", "bfill"):
            arr = grp.copy() if func == "ffill" else grp[::-1].copy()
            last = np.nan
            for i, v in enumerate(arr):
                if np.isnan(v):
                    arr[i] = last
                else:
                    last = v
            expected[sel] = arr if func == "ffill" else arr[::-1]
    np.testing.assert_allclose(got, expected, rtol=1e-12, equal_nan=True)


@settings(max_examples=200, deadline=None)
@given(
    n=st.sampled_from([64, 96]),
    data=st.data(),
    func=st.sampled_from(["cumsum", "nancumsum", "ffill", "bfill"]),
)
def test_scan_mesh_equals_eager(n, data, func, mesh8):
    labels_f = data.draw(labels_strategy(n, with_nan_labels=False))
    labels = labels_f.astype(np.int64)
    vals = data.draw(arrays(np.float64, (n,), elements=st.one_of(
        st.floats(-1e6, 1e6, allow_nan=False), st.just(np.nan))))
    eager = np.asarray(groupby_scan(vals, labels, func=func))
    mesh_r = np.asarray(groupby_scan(vals, labels, func=func, method="blelloch", mesh=mesh8))
    np.testing.assert_allclose(mesh_r, eager, rtol=1e-10, atol=1e-12, equal_nan=True)
