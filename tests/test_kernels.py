"""Unit tests for the jax engine kernels against per-group numpy oracles.

Oracle strategy mirrors the reference's (tests/test_core.py:86-113): apply
the plain numpy function to each group's masked slice.
"""

import numpy as np
import pytest
import scipy.stats

from flox_tpu import engine_numpy as engine_numpy_mod
from flox_tpu import kernels


def oracle(func, values, codes, size, **kw):
    """Per-group loop with plain numpy — the independent reference result."""
    np_func = {
        "sum": np.sum,
        "nansum": np.nansum,
        "prod": np.prod,
        "nanprod": np.nanprod,
        "max": np.max,
        "nanmax": np.nanmax,
        "min": np.min,
        "nanmin": np.nanmin,
        "mean": np.mean,
        "nanmean": np.nanmean,
        "var": np.var,
        "nanvar": np.nanvar,
        "std": np.std,
        "nanstd": np.nanstd,
        "median": np.median,
        "nanmedian": np.nanmedian,
        "all": np.all,
        "any": np.any,
        "argmax": np.argmax,
        "argmin": np.argmin,
        "nanargmax": np.nanargmax,
        "nanargmin": np.nanargmin,
    }[func]
    out = []
    for g in range(size):
        grp = values[..., codes == g]
        if grp.shape[-1] == 0:
            out.append(np.full(values.shape[:-1], np.nan))
            continue
        with np.errstate(invalid="ignore"), np.testing.suppress_warnings() as sup:
            sup.filter(RuntimeWarning)
            if func.startswith(("arg", "nanarg")):
                res = np.apply_along_axis(lambda s: np_func(s), -1, grp)
                # convert group-local index to flat index
                flat_positions = np.flatnonzero(codes == g)
                res = flat_positions[res]
            else:
                res = np_func(grp, axis=-1, **kw)
        out.append(res)
    return np.stack(out, axis=-1).astype(np.float64)


RNG = np.random.default_rng(42)


@pytest.fixture(params=["1d", "2d", "nan", "empty-group", "nan-labels"])
def case(request):
    n, size = 57, 5
    codes = RNG.integers(0, size, n).astype(np.int64)
    values = RNG.normal(size=(n,)).astype(np.float64)
    if request.param == "2d":
        values = RNG.normal(size=(3, n))
    elif request.param == "nan":
        values[RNG.random(n) < 0.3] = np.nan
    elif request.param == "empty-group":
        codes[codes == 2] = 1  # group 2 has no members
    elif request.param == "nan-labels":
        codes[RNG.random(n) < 0.2] = -1
    return values, codes, size


SIMPLE_FUNCS = [
    "sum", "nansum", "prod", "nanprod", "max", "nanmax", "min", "nanmin",
    "mean", "nanmean", "var", "nanvar", "std", "nanstd",
]


@pytest.mark.parametrize("func", SIMPLE_FUNCS)
def test_simple_reductions(case, func):
    values, codes, size = case
    got = np.asarray(kernels.generic_kernel(func, codes, values, size=size, fill_value=np.nan))
    expected = np.full(values.shape[:-1] + (size,), np.nan)
    for g in range(size):
        sel = codes == g
        if not sel.any():
            continue
        grp = values[..., sel]
        with np.errstate(invalid="ignore"), np.testing.suppress_warnings() as sup:
            sup.filter(RuntimeWarning)
            expected[..., g] = getattr(np, func)(grp, axis=-1)
    np.testing.assert_allclose(got, expected, rtol=1e-12, atol=1e-12)


def test_count(case):
    values, codes, size = case
    got = np.asarray(kernels.generic_kernel("nanlen", codes, values, size=size))
    expected = np.zeros(values.shape[:-1] + (size,))
    for g in range(size):
        grp = values[..., codes == g]
        expected[..., g] = np.sum(~np.isnan(grp), axis=-1)
    np.testing.assert_array_equal(got, expected)


@pytest.mark.parametrize("func", ["argmax", "argmin", "nanargmax", "nanargmin"])
def test_argreductions(case, func):
    values, codes, size = case
    got = np.asarray(kernels.generic_kernel(func, codes, values, size=size, fill_value=-1))
    expected = np.full(values.shape[:-1] + (size,), -1, dtype=np.int64)
    for g in range(size):
        sel = np.flatnonzero(codes == g)
        if sel.size == 0:
            continue
        grp = values[..., sel]
        with np.errstate(invalid="ignore"):
            if func.startswith("nanarg"):
                valid = ~np.all(np.isnan(grp), axis=-1)
                local = np.full(grp.shape[:-1], 0, dtype=np.int64)
                safe = np.where(np.isnan(grp), -np.inf if "max" in func else np.inf, grp)
                local = np.argmax(safe, -1) if "max" in func else np.argmin(safe, -1)
                res = np.where(valid, sel[local], -1)
            else:
                local = np.argmax(grp, -1) if "max" in func else np.argmin(grp, -1)
                res = sel[local]
        expected[..., g] = res
    np.testing.assert_array_equal(got, expected)


@pytest.mark.parametrize("func", ["first", "last", "nanfirst", "nanlast"])
def test_first_last(case, func):
    values, codes, size = case
    got = np.asarray(kernels.generic_kernel(func, codes, values, size=size, fill_value=np.nan))
    expected = np.full(values.shape[:-1] + (size,), np.nan)
    for g in range(size):
        sel = np.flatnonzero(codes == g)
        if sel.size == 0:
            continue
        grp = values[..., sel]
        if func.startswith("nan"):
            valid = ~np.isnan(grp)
            order = range(grp.shape[-1]) if "first" in func else range(grp.shape[-1] - 1, -1, -1)
            res = np.full(grp.shape[:-1], np.nan)
            done = np.zeros(grp.shape[:-1], dtype=bool)
            for i in order:
                pick = valid[..., i] & ~done
                res = np.where(pick, grp[..., i], res)
                done |= valid[..., i]
        else:
            res = grp[..., 0] if func == "first" else grp[..., -1]
        expected[..., g] = res
    np.testing.assert_allclose(got, expected, rtol=0, atol=0, equal_nan=True)


@pytest.mark.parametrize("q", [0.5, 0.9, [0.25, 0.75]])
def test_quantile(case, q):
    values, codes, size = case
    got = np.asarray(kernels.generic_kernel("nanquantile", codes, values, size=size, q=q))
    qs = np.atleast_1d(q)
    expected = np.full((len(qs),) + values.shape[:-1] + (size,), np.nan)
    for g in range(size):
        grp = values[..., codes == g]
        if grp.shape[-1] == 0 or np.all(np.isnan(grp)):
            continue
        with np.testing.suppress_warnings() as sup:
            sup.filter(RuntimeWarning)
            expected[..., g] = np.nanquantile(grp, qs, axis=-1)
    if np.ndim(q) == 0:
        expected = expected[0]
    np.testing.assert_allclose(got, expected, rtol=1e-12, atol=1e-12, equal_nan=True)


def test_median(case):
    values, codes, size = case
    got = np.asarray(kernels.generic_kernel("nanmedian", codes, values, size=size))
    expected = np.full(values.shape[:-1] + (size,), np.nan)
    for g in range(size):
        grp = values[..., codes == g]
        if grp.shape[-1] == 0 or np.all(np.isnan(grp)):
            continue
        with np.testing.suppress_warnings() as sup:
            sup.filter(RuntimeWarning)
            expected[..., g] = np.nanmedian(grp, axis=-1)
    np.testing.assert_allclose(got, expected, rtol=1e-12, atol=1e-12, equal_nan=True)


def test_mode():
    codes = np.array([0, 0, 0, 1, 1, 1, 1, 2, 0])
    values = np.array([3.0, 1.0, 3.0, 5.0, 5.0, 2.0, 2.0, 7.0, 1.0])
    got = np.asarray(kernels.generic_kernel("mode", codes, values, size=3))
    # group 0: [3,1,3,1] -> tie between 1 (x2) and 3 (x2) -> smallest = 1
    # group 1: [5,5,2,2] -> tie -> 2 ; group 2: [7] -> 7
    np.testing.assert_array_equal(got, [1.0, 2.0, 7.0])


def test_nanmode():
    codes = np.array([0, 0, 0, 1, 1])
    values = np.array([np.nan, 2.0, 2.0, np.nan, np.nan])
    got = np.asarray(kernels.generic_kernel("nanmode", codes, values, size=2, fill_value=np.nan))
    np.testing.assert_allclose(got, [2.0, np.nan], equal_nan=True)


def test_bool_all_any():
    codes = np.array([0, 0, 1, 1, 2])
    values = np.array([True, False, True, True, False])
    got_all = np.asarray(kernels.generic_kernel("all", codes, values, size=4))
    got_any = np.asarray(kernels.generic_kernel("any", codes, values, size=4))
    np.testing.assert_array_equal(got_all, [False, True, False, True])
    np.testing.assert_array_equal(got_any, [True, True, False, False])


def test_cumsum():
    codes = np.array([0, 1, 0, 1, 0])
    values = np.array([1.0, 10.0, 2.0, 20.0, 3.0])
    got = np.asarray(kernels.generic_kernel("cumsum", codes, values, size=2))
    np.testing.assert_allclose(got, [1.0, 10.0, 3.0, 30.0, 6.0])


def test_nancumsum_2d():
    codes = np.array([0, 1, 0, 1])
    values = np.array([[1.0, np.nan, 2.0, 5.0], [4.0, 1.0, np.nan, 1.0]])
    got = np.asarray(kernels.generic_kernel("nancumsum", codes, values, size=2))
    np.testing.assert_allclose(got, [[1.0, 0.0, 3.0, 5.0], [4.0, 1.0, 4.0, 2.0]])


def test_ffill_bfill():
    codes = np.array([0, 1, 0, 1, 0, 1])
    values = np.array([np.nan, 1.0, 2.0, np.nan, np.nan, np.nan])
    got_f = np.asarray(kernels.generic_kernel("ffill", codes, values, size=2))
    np.testing.assert_allclose(got_f, [np.nan, 1.0, 2.0, 1.0, 2.0, 1.0], equal_nan=True)
    got_b = np.asarray(kernels.generic_kernel("bfill", codes, values, size=2))
    np.testing.assert_allclose(got_b, [2.0, 1.0, 2.0, np.nan, np.nan, np.nan], equal_nan=True)


def test_var_chunk_triple():
    codes = np.array([0, 0, 1, 1, 1])
    values = np.array([1.0, 3.0, 2.0, 4.0, 6.0])
    ma = kernels.generic_kernel("var_chunk", codes, values, size=2)
    m2, total, cnt = (np.asarray(a) for a in ma)
    np.testing.assert_allclose(total, [4.0, 12.0])
    np.testing.assert_allclose(cnt, [2.0, 3.0])
    np.testing.assert_allclose(m2, [2.0, 8.0])  # sum (x - mean)^2


def test_nan_labels_excluded():
    codes = np.array([0, -1, 0, 1])
    values = np.array([1.0, 100.0, 2.0, 3.0])
    got = np.asarray(kernels.generic_kernel("sum", codes, values, size=2))
    np.testing.assert_allclose(got, [3.0, 3.0])


class TestMatmulPath:
    """The one-hot-GEMM segment-sum path must agree with scatter exactly
    in semantics (incl. NaN propagation and missing labels)."""

    def _both(self, func, codes, values, size, **kw):
        import flox_tpu

        with flox_tpu.set_options(segment_sum_impl="matmul"):
            a = np.asarray(kernels.generic_kernel(func, codes, values, size=size, **kw))
        with flox_tpu.set_options(segment_sum_impl="scatter"):
            b = np.asarray(kernels.generic_kernel(func, codes, values, size=size, **kw))
        return a, b

    @pytest.mark.parametrize("func", ["sum", "nansum", "mean", "nanmean", "var", "nanvar"])
    def test_agrees_with_scatter(self, func):
        rng = np.random.default_rng(3)
        codes = rng.integers(0, 5, 200)
        values = rng.normal(size=(3, 200))
        values[..., rng.random(200) < 0.2] = np.nan
        codes[rng.random(200) < 0.1] = -1
        a, b = self._both(func, codes, values, 5)
        np.testing.assert_allclose(a, b, rtol=1e-10, atol=1e-12, equal_nan=True)

    def test_nan_does_not_poison_other_groups(self):
        # non-skipna sum: NaN must hit only its own group (0*NaN hazard)
        codes = np.array([0, 0, 1, 1])
        values = np.array([1.0, np.nan, 2.0, 3.0])
        a, b = self._both("sum", codes, values, 2)
        np.testing.assert_allclose(a, [np.nan, 5.0], equal_nan=True)
        np.testing.assert_allclose(b, [np.nan, 5.0], equal_nan=True)

    def test_missing_labels_drop(self):
        codes = np.array([0, -1, 0, 1])
        values = np.array([1.0, 100.0, 2.0, 3.0])
        a, _ = self._both("sum", codes, values, 2)
        np.testing.assert_allclose(a, [3.0, 3.0])

    @pytest.mark.parametrize("func", ["sum", "nansum", "nanmean"])
    def test_wide_k_blocked(self, func):
        # K wide enough to trigger the lax.map column-blocking (incl. the
        # non-multiple-of-kb padding path) must match scatter exactly
        import flox_tpu

        rng = np.random.default_rng(7)
        n, k = 1000, 300  # kb floors to 128 at the minimum block budget
        codes = rng.integers(0, 6, n)
        values = rng.normal(size=(k, n))
        values[rng.random((k, n)) < 0.05] = np.nan
        values[0, :3] = np.inf
        with flox_tpu.set_options(matmul_block_bytes=2**20):
            a, b = self._both(func, codes, values, 6)
        np.testing.assert_allclose(a, b, rtol=1e-10, atol=1e-12, equal_nan=True)

    def test_huge_n_falls_back_to_scatter(self):
        # blocking bounds K, not N: when even a 128-lane block exceeds the
        # HBM ceiling, the path must refuse (shape-only check, no alloc)
        class Fake:
            shape = (2**24, 64)
            ndim = 2
            dtype = np.dtype("float32")

        assert kernels._use_matmul_path("sum", Fake(), 12) is False

        class FakeOk(Fake):
            shape = (2**16, 64)

        assert kernels._use_matmul_path("sum", FakeOk(), 12) is True

    def test_empty_input(self):
        # zero-length reductions must not divide by zero in the block sizing
        codes = np.zeros(0, dtype=np.int64)
        values = np.zeros((3, 0))
        a, b = self._both("sum", codes, values, 2)
        np.testing.assert_allclose(a, b)
        np.testing.assert_allclose(a, np.zeros((3, 2)))


def test_matmul_path_inf_exact():
    # inf must stay local to its group and column (0*inf hazard in the GEMM)
    import flox_tpu

    codes = np.array([0, 1, 0, 1])
    values = np.array([[np.inf, 1.0, 2.0, 3.0],
                       [1.0, -np.inf, np.inf, 4.0],
                       [1.0, 2.0, 3.0, 4.0]])
    with flox_tpu.set_options(segment_sum_impl="matmul"):
        a = np.asarray(kernels.generic_kernel("sum", codes, values, size=2))
    with flox_tpu.set_options(segment_sum_impl="scatter"):
        b = np.asarray(kernels.generic_kernel("sum", codes, values, size=2))
    expected = np.array([[np.inf, 4.0], [np.inf, -np.inf + 4.0], [4.0, 6.0]])
    np.testing.assert_array_equal(a, expected)
    np.testing.assert_array_equal(b, expected)


def test_options_invalidate_jit_cache():
    # toggling matmul_path must not serve a stale compiled bundle
    import flox_tpu
    from flox_tpu.core import groupby_reduce

    codes = np.array([0, 1] * 50)
    vals = np.arange(100.0).reshape(2, 50).repeat(2, axis=1)[:, :100].reshape(2, 100)
    with flox_tpu.set_options(segment_sum_impl="matmul"):
        a, _ = groupby_reduce(vals, codes, func="sum", engine="jax")
    with flox_tpu.set_options(segment_sum_impl="scatter"):
        b, _ = groupby_reduce(vals, codes, func="sum", engine="jax")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-12)


class TestPallasPath:
    """Pallas segment-sum (interpret mode off-TPU) vs scatter."""

    def _both(self, func, codes, values, size, **kw):
        import flox_tpu

        with flox_tpu.set_options(segment_sum_impl="pallas"):
            a = np.asarray(kernels.generic_kernel(func, codes, values, size=size, **kw))
        with flox_tpu.set_options(segment_sum_impl="scatter"):
            b = np.asarray(kernels.generic_kernel(func, codes, values, size=size, **kw))
        return a, b

    @pytest.mark.parametrize("func", ["sum", "nansum", "nanmean"])
    def test_agrees_with_scatter(self, func):
        rng = np.random.default_rng(4)
        codes = rng.integers(0, 5, 64)
        values = rng.normal(size=(2, 64)).astype(np.float32)
        values[..., rng.random(64) < 0.2] = np.nan
        codes[rng.random(64) < 0.1] = -1
        a, b = self._both(func, codes, values, 5)
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6, equal_nan=True)

    def test_inf_exact(self):
        codes = np.array([0, 1, 0, 1] * 4)
        values = np.zeros((2, 16), dtype=np.float32)
        values[0, 0] = np.inf
        values[1, 1] = -np.inf
        a, b = self._both("sum", codes, values, 2)
        np.testing.assert_array_equal(a, b)


class TestPallasMinMax:
    """Pallas VPU select-reduce min/max (interpret mode off-TPU) vs scatter."""

    def _both(self, func, codes, values, size, **kw):
        import flox_tpu

        with flox_tpu.set_options(segment_minmax_impl="pallas"):
            a = np.asarray(kernels.generic_kernel(func, codes, values, size=size, **kw))
        with flox_tpu.set_options(segment_minmax_impl="scatter"):
            b = np.asarray(kernels.generic_kernel(func, codes, values, size=size, **kw))
        return a, b

    @pytest.mark.parametrize("func", ["max", "min", "nanmax", "nanmin"])
    def test_agrees_with_scatter(self, func):
        rng = np.random.default_rng(11)
        codes = rng.integers(0, 5, 77)
        values = rng.normal(size=(2, 77)).astype(np.float32)
        values[..., rng.random(77) < 0.2] = np.nan
        codes[rng.random(77) < 0.1] = -1
        codes[codes == 3] = 1  # empty group
        a, b = self._both(func, codes, values, 5, fill_value=np.nan)
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=0, equal_nan=True)

    def test_int32(self):
        rng = np.random.default_rng(12)
        codes = rng.integers(0, 4, 130)
        values = rng.integers(-1000, 1000, size=(3, 130)).astype(np.int32)
        a, b = self._both("max", codes, values, 4)
        np.testing.assert_array_equal(a, b)

    @pytest.mark.parametrize("func", ["max", "nanmin"])
    def test_bfloat16(self, func):
        import jax.numpy as jnp

        rng = np.random.default_rng(15)
        codes = rng.integers(0, 4, 150)
        values = jnp.asarray(rng.normal(size=(2, 150)).astype(np.float32)).astype(
            jnp.bfloat16
        )
        if func == "nanmin":
            values = values.at[..., ::7].set(jnp.nan)
        a, b = self._both(func, codes, values, 4)
        assert a.dtype == np.asarray(values).dtype
        np.testing.assert_array_equal(a, b)

    def test_ragged_direct_vs_oracle(self):
        # non-divisible shapes through the raw kernel against a numpy loop
        from flox_tpu.pallas_kernels import segment_minmax_pallas

        rng = np.random.default_rng(13)
        n, k, size = 301, 135, 6
        values = rng.normal(size=(n, k)).astype(np.float32)
        codes = rng.integers(-1, size, n).astype(np.int32)
        got = np.asarray(
            segment_minmax_pallas(values, codes, size, "min", interpret=True)
        )
        for g in range(size):
            grp = values[codes == g]
            want = grp.min(0) if len(grp) else np.full(k, np.inf, np.float32)
            np.testing.assert_array_equal(got[g], want)

    def test_group_cap_falls_back(self):
        import flox_tpu

        rng = np.random.default_rng(14)
        codes = rng.integers(0, 5, 64)
        values = rng.normal(size=64).astype(np.float32)
        with flox_tpu.set_options(
            segment_minmax_impl="pallas", pallas_minmax_num_groups_max=3
        ):
            # over the cap: resolves to scatter, still correct
            out = np.asarray(kernels.generic_kernel("max", codes, values, size=5))
        for g in range(5):
            np.testing.assert_allclose(out[g], values[codes == g].max(), rtol=1e-6)


class TestPallasScan:
    """Pallas triangular-matmul grouped cumsum (interpret mode) vs the
    sort-based segmented path and per-group numpy loops."""

    def _oracle(self, func, values, codes):
        out = np.empty_like(values, dtype=np.float64)
        for g in np.unique(codes):
            m = codes == g
            grp = values[..., m].astype(np.float64)
            out[..., m] = np.cumsum(np.nan_to_num(grp, nan=0.0), -1) if func == "nancumsum" else np.cumsum(grp, -1)
        return out

    @pytest.mark.parametrize("func", ["cumsum", "nancumsum"])
    @pytest.mark.parametrize("shape", [(257,), (3, 300)])
    def test_vs_oracle_and_segmented(self, func, shape):
        import flox_tpu

        rng = np.random.default_rng(21)
        n = shape[-1]
        codes = rng.integers(0, 5, n)
        codes[rng.random(n) < 0.1] = -1  # missing labels scan among themselves
        values = rng.normal(size=shape).astype(np.float32)
        values[rng.random(shape) < 0.15] = np.nan
        with flox_tpu.set_options(scan_impl="pallas"):
            a = np.asarray(kernels.generic_kernel(func, codes, values, size=5))
        with flox_tpu.set_options(scan_impl="segmented"):
            b = np.asarray(kernels.generic_kernel(func, codes, values, size=5))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6, equal_nan=True)
        ok = codes >= 0
        want = self._oracle(func, values, codes)
        np.testing.assert_allclose(a[..., ok], want[..., ok], rtol=1e-5, atol=1e-5, equal_nan=True)

    def test_nan_poisons_rest_of_group_across_tiles(self):
        # non-skipna: a NaN early in a group must poison every later element
        # of that group (including across the 128-lane tile boundary), and
        # ONLY that group
        from flox_tpu.pallas_kernels import segment_cumsum_pallas

        n = 400
        codes = (np.arange(n) % 3).astype(np.int32)
        values = np.ones(n, dtype=np.float32)
        values[30] = np.nan  # group 0, first tile
        got = np.asarray(segment_cumsum_pallas(values, codes, 3, skipna=False, interpret=True))
        g0 = np.flatnonzero(codes == 0)
        before = g0[g0 < 30]
        after = g0[g0 >= 30]
        assert np.isfinite(got[before]).all()
        assert np.isnan(got[after]).all()
        others = codes != 0
        assert np.isfinite(got[others]).all()

    @pytest.mark.parametrize("func", ["cumsum", "nancumsum"])
    def test_inf_semantics(self, func):
        # r2 advisor (high): ±inf used to survive the zero-fill and poison
        # every group through inf×0=NaN in the masked matmuls. One inf must
        # stay inside its own group and follow IEEE prefix semantics —
        # including across tile boundaries through the marker carries.
        from flox_tpu.pallas_kernels import segment_cumsum_pallas

        n = 1200  # 3 tiles of 512 — markers must ride the carry rows
        codes = (np.arange(n) % 3).astype(np.int32)
        values = np.ones(n, dtype=np.float32)
        values[30] = np.inf     # group 0: +inf from here on...
        values[900] = -np.inf   # ...then +inf + -inf = NaN (tile 2)
        values[61] = -np.inf    # group 1: -inf from here on
        values[50] = np.nan     # group 2: NaN poisons (cumsum only)
        got = np.asarray(
            segment_cumsum_pallas(values, codes, 3, skipna=(func == "nancumsum"), interpret=True)
        )
        f = np.nancumsum if func == "nancumsum" else np.cumsum
        want = np.empty(n, np.float64)
        for g in range(3):
            m = codes == g
            want[m] = f(values[m].astype(np.float64))
        np.testing.assert_allclose(got, want, rtol=1e-6, equal_nan=True)

    def test_carry_overflow_does_not_poison_other_groups(self):
        # an all-finite running sum that overflows f32 in the carry must
        # report +inf for ITS group's later lanes only — not NaN everywhere
        # through inf×0 in the one-hot gather
        from flox_tpu.pallas_kernels import segment_cumsum_pallas

        n = 1100
        codes = (np.arange(n) % 2).astype(np.int32)
        values = np.ones(n, dtype=np.float32)
        values[codes == 0] = 3e38  # group 0 overflows within the first tile
        got = np.asarray(segment_cumsum_pallas(values, codes, 2, skipna=False, interpret=True))
        g1 = got[codes == 1]
        np.testing.assert_allclose(g1, np.arange(1, len(g1) + 1), rtol=1e-6)
        g0 = got[codes == 0]
        assert np.isposinf(g0[-1])  # overflowed group saturates at +inf
        assert not np.isnan(g0).any()

    def test_opposite_sign_overflow_keeps_first_inf(self):
        # +overflow, carry reset, then a would-be -overflow of the reset
        # carry: IEEE keeps +inf (a true +inf running sum absorbs finite
        # negatives) — must not turn into NaN via both markers
        from flox_tpu.pallas_kernels import segment_cumsum_pallas

        n = 1100
        vals = np.full(n, 3e38, np.float32)
        vals[400:] = -3e38
        codes = np.zeros(n, dtype=np.int32)
        got = np.asarray(segment_cumsum_pallas(vals, codes, 1, skipna=False, interpret=True))
        assert np.isposinf(got[1])  # overflows at the second element
        assert np.isposinf(got[-1])
        assert not np.isnan(got).any()

    def test_overflow_then_opposite_inf_value_is_nan(self):
        # in-tile arithmetic +overflow followed by a -inf VALUE: the true
        # sequential sum is +inf + (-inf) = NaN from that element on
        from flox_tpu.pallas_kernels import segment_cumsum_pallas

        vals = np.full(200, 0.0, np.float32)
        vals[0] = 3e38
        vals[1] = 3e38
        vals[5] = -np.inf
        codes = np.zeros(200, dtype=np.int32)
        got = np.asarray(segment_cumsum_pallas(vals, codes, 1, skipna=False, interpret=True))
        assert np.isposinf(got[1]) and np.isposinf(got[4])
        assert np.isnan(got[5:]).all()
        # ...and the reverse order stays -inf (a -inf running sum cannot
        # re-overflow positive)
        vals2 = np.full(200, 3e38, np.float32)
        vals2[0] = -np.inf
        got2 = np.asarray(segment_cumsum_pallas(vals2, codes, 1, skipna=False, interpret=True))
        assert np.isneginf(got2).all()

    def test_all_finite_tile_after_inf_tile(self):
        # the carried-marker-only branch (no local nonfinite in the tile)
        from flox_tpu.pallas_kernels import segment_cumsum_pallas

        n = 1100
        codes = np.zeros(n, dtype=np.int32)
        values = np.ones(n, dtype=np.float32)
        values[3] = np.inf  # tile 0; tiles 1-2 are all finite
        got = np.asarray(segment_cumsum_pallas(values, codes, 1, skipna=False, interpret=True))
        assert np.isfinite(got[:3]).all()
        assert np.isposinf(got[3:]).all()

    def test_bf16_accumulates_f32(self):
        import jax.numpy as jnp

        from flox_tpu.pallas_kernels import segment_cumsum_pallas

        n = 2000
        vals = jnp.ones(n, jnp.bfloat16)
        codes = np.zeros(n, dtype=np.int32)
        got = np.asarray(segment_cumsum_pallas(vals, codes, 1, skipna=False, interpret=True).astype(jnp.float32))
        # a bf16 running sum would saturate at 256; f32 accumulation keeps
        # counting (each element individually rounds to its bf16 neighbour)
        assert got[-1] > 1900

    def test_group_cap_falls_back(self):
        import flox_tpu

        rng = np.random.default_rng(22)
        codes = rng.integers(0, 5, 64)
        values = rng.normal(size=64).astype(np.float32)
        with flox_tpu.set_options(scan_impl="pallas", pallas_scan_num_groups_max=3):
            out = np.asarray(kernels.generic_kernel("cumsum", codes, values, size=5))
        np.testing.assert_allclose(out, self._oracle("cumsum", values, codes), rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("skipna", [False, True])
    def test_nonfinite_state_is_sticky_under_adversarial_magnitudes(self, skipna):
        # mixed-sign values within a tile-width factor of f32 max: the MXU
        # (or interpret-mode) tree reduction may overflow to ±inf or form
        # NaN from opposite-sign inf partials in ANY order. Whatever event
        # fires, the group-state model requires it to be sticky — a lane
        # that reports nonfinite must never be followed by a finite lane of
        # the same group (ADVICE r3: a tree-reduction NaN with no inf lane
        # previously slipped the _clean branch and silently reverted).
        from flox_tpu.pallas_kernels import segment_cumsum_pallas

        rng = np.random.default_rng(99)
        n = 1600
        codes = (np.arange(n) % 3).astype(np.int32)
        values = (rng.choice([-1.0, 1.0], n) * rng.uniform(1e38, 3e38, n)).astype(
            np.float32
        )
        got = np.asarray(
            segment_cumsum_pallas(values, codes, 3, skipna=skipna, interpret=True)
        )
        for g in range(3):
            lane_ok = np.isfinite(got[codes == g])
            first_bad = np.argmax(~lane_ok) if (~lane_ok).any() else len(lane_ok)
            assert lane_ok[:first_bad].all()
            assert not lane_ok[first_bad:].any()


def test_pallas_kahan_accuracy():
    # compensated f32 accumulation lands within one output-ulp of the f64
    # oracle; plain accumulation drifts by multiple ulps
    from flox_tpu.pallas_kernels import segment_sum_pallas

    rng = np.random.default_rng(0)
    n = 100_000
    data = rng.normal(1e4, 1, size=(n, 1)).astype(np.float32)
    codes = np.zeros(n, dtype=np.int32)
    oracle = data.astype(np.float64).sum()
    plain = float(np.asarray(segment_sum_pallas(data, codes, 1, interpret=True, accum="plain"))[0, 0])
    kahan = float(np.asarray(segment_sum_pallas(data, codes, 1, interpret=True, accum="kahan"))[0, 0])
    ulp = np.spacing(np.float32(oracle)).astype(np.float64)
    assert abs(kahan - oracle) <= ulp
    assert abs(kahan - oracle) <= abs(plain - oracle)


class TestPallasDoubleDouble:
    """The dd (2×f32) accumulation mode: the strict-parity answer to the
    'bit-exact float64 means' north star on hardware without f64."""

    def test_dd_is_correctly_rounded_f64(self):
        # dd must land on the f32-rounding of the exact f64 sum — not just
        # within an ulp — on a workload where plain f32 visibly drifts
        from flox_tpu.pallas_kernels import segment_sum_pallas

        rng = np.random.default_rng(1)
        n = 200_000
        data = rng.normal(1e4, 1, size=(n, 1)).astype(np.float32)
        codes = (np.arange(n) % 3).astype(np.int32)
        got = np.asarray(segment_sum_pallas(data, codes, 3, interpret=True, accum="dd"))
        for g in range(3):
            oracle = data[codes == g].astype(np.float64).sum()
            assert got[g, 0] == np.float32(oracle), (g, got[g, 0], oracle)

    def test_dd_cancellation(self):
        # catastrophic cancellation across tiles: pairs (x, -x) plus a tiny
        # residual — the lo word must carry the residual that plain/Kahan
        # f32 sums round away when the running sum is large
        from flox_tpu.pallas_kernels import segment_sum_pallas

        n = 4096
        data = np.zeros((n, 1), np.float32)
        data[: n // 2, 0] = 3e7
        data[n // 2 :, 0] = -3e7
        data[0, 0] += 1.0  # exact in f32 at 3e7 scale
        codes = np.zeros(n, dtype=np.int32)
        oracle = data.astype(np.float64).sum()  # == 1.0
        got = float(np.asarray(segment_sum_pallas(data, codes, 1, interpret=True, accum="dd"))[0, 0])
        assert got == np.float32(oracle), (got, oracle)

    def test_unknown_accum_rejected(self):
        # a typo like "khan" must raise, not silently select plain
        # accumulation at lower-than-requested accuracy (ADVICE r3)
        from flox_tpu.pallas_kernels import segment_sum_pallas

        data = np.ones((8, 1), np.float32)
        codes = np.zeros(8, np.int32)
        with pytest.raises(ValueError, match="accum"):
            segment_sum_pallas(data, codes, 1, interpret=True, accum="khan")

    def test_dd_matches_options_knob(self):
        import flox_tpu
        from flox_tpu.pallas_kernels import segment_sum_pallas

        rng = np.random.default_rng(2)
        data = rng.normal(size=(1000, 2)).astype(np.float32)
        codes = (np.arange(1000) % 4).astype(np.int32)
        with flox_tpu.set_options(pallas_accum="dd"):
            via_opt = np.asarray(segment_sum_pallas(data, codes, 4, interpret=True))
        explicit = np.asarray(segment_sum_pallas(data, codes, 4, interpret=True, accum="dd"))
        np.testing.assert_array_equal(via_opt, explicit)

    def test_dd_nonfinite_semantics_preserved(self):
        # the marker machinery is orthogonal to the accumulation discipline
        from flox_tpu.pallas_kernels import segment_sum_pallas

        data = np.ones((600, 1), np.float32)
        data[10, 0] = np.inf
        data[20, 0] = np.nan
        codes = (np.arange(600) % 3).astype(np.int32)
        got = np.asarray(segment_sum_pallas(data, codes, 3, interpret=True, accum="dd"))
        assert np.isposinf(got[1, 0])  # 10 % 3 == 1
        assert np.isnan(got[2, 0])  # 20 % 3 == 2
        assert np.isfinite(got[0, 0])

    def test_dd_large_values_still_split_exactly(self):
        # 2e34 is below the split-overflow bound (f32max/4097 ≈ 8.3e34), so
        # the Dekker split still applies and the sum is exact
        from flox_tpu.pallas_kernels import segment_sum_pallas

        data = np.full((256, 1), 2e34, np.float32)
        codes = np.zeros(256, dtype=np.int32)
        oracle = data.astype(np.float64).sum()
        got = float(np.asarray(segment_sum_pallas(data, codes, 1, interpret=True, accum="dd"))[0, 0])
        assert got == np.float32(oracle)

    def test_dd_huge_values_skip_split(self):
        # above the bound the guard keeps values whole: no overflow garbage,
        # f32-grade accuracy (the documented reordered-summation boundary)
        from flox_tpu.pallas_kernels import segment_sum_pallas

        data = np.full((256, 1), 1e35, np.float32)
        codes = np.zeros(256, dtype=np.int32)
        oracle = data.astype(np.float64).sum()
        got = float(np.asarray(segment_sum_pallas(data, codes, 1, interpret=True, accum="dd"))[0, 0])
        assert np.isfinite(got)
        np.testing.assert_allclose(got, oracle, rtol=1e-5)


@pytest.mark.parametrize(
    "method",
    ["linear", "hazen", "weibull", "interpolated_inverted_cdf",
     "median_unbiased", "normal_unbiased", "lower", "higher", "midpoint"],
)
def test_quantile_methods_match_numpy(method):
    # the jax engine's (alpha, beta) families must match np.quantile exactly
    rng = np.random.default_rng(5)
    codes = rng.integers(0, 4, 50)
    values = rng.normal(size=50)
    a = np.asarray(kernels.generic_kernel("quantile", codes, values, size=4, q=0.3, method=method))
    expected = np.stack(
        [np.quantile(values[codes == g], 0.3, method=method) for g in range(4)]
    )
    np.testing.assert_allclose(a, expected, rtol=1e-12, atol=1e-12)


def test_quantile_nearest_half_to_even():
    # np.quantile 'nearest' rounds the virtual index half-to-even
    values = np.array([0.0, 1.0, 2.0, 3.0])
    codes = np.zeros(4, dtype=np.int64)
    got = float(np.asarray(
        kernels.generic_kernel("quantile", codes, values, size=1, q=0.5, method="nearest")
    )[0])
    assert got == np.quantile(values, 0.5, method="nearest")


def test_nan_fill_promotes_int_data():
    # NaN fill on integer input must produce NaN, not a truncated 0
    codes = np.array([0, 0, 0])
    values = np.array([5, 7, 9], dtype=np.int64)
    for func in ["first", "max", "mode"]:
        a = np.asarray(kernels.generic_kernel(func, codes, values, size=2, fill_value=np.nan))
        b = np.asarray(engine_numpy_mod.generic_kernel(func, codes, values, size=2, fill_value=np.nan))
        assert np.isnan(a[1]) and np.isnan(b[1]), func


def test_complex_nan_fill_keeps_imaginary():
    from flox_tpu import engine_numpy

    vals = np.array([1 + 2j, 3 - 1j, 2 + 2j])
    codes = np.array([0, 0, 0])
    b = np.asarray(engine_numpy.generic_kernel("sum", codes, vals, size=2, fill_value=np.nan))
    assert b.dtype.kind == "c" and b[0] == 6 + 3j and np.isnan(b[1].real)


def test_pallas_probe_failure_falls_back(monkeypatch):
    # if the pallas kernel cannot lower on the real backend, auto/pallas
    # policies must degrade to the XLA paths instead of failing the reduction
    import flox_tpu
    from flox_tpu import kernels as K
    from flox_tpu import pallas_kernels

    monkeypatch.setattr(K, "_PALLAS_PROBE_RESULT", [])
    def boom(*a, **k):
        raise RuntimeError("lowering failed")
    monkeypatch.setattr(pallas_kernels, "segment_sum_pallas", boom)
    monkeypatch.setattr("jax.default_backend", lambda: "tpu")
    import jax.numpy as jnp

    with flox_tpu.set_options(segment_sum_impl="pallas"):
        assert K._segment_sum_impl(jnp.zeros((64, 4), jnp.float32), 12) == "scatter"
    monkeypatch.setattr(K, "_PALLAS_PROBE_RESULT", [])
    with flox_tpu.set_options(segment_sum_impl="auto"):
        # auto degrades pallas -> matmul (guards pass) on a TPU backend
        assert K._segment_sum_impl(jnp.zeros((64, 4), jnp.float32), 12) == "matmul"


def test_quantile_bf16_large_group():
    # index arithmetic must not run in bf16 (cannot represent odd counts >256)
    import jax.numpy as jnp

    n = 301
    values = jnp.arange(n, dtype=jnp.bfloat16)
    codes = np.zeros(n, dtype=np.int64)
    got = kernels.generic_kernel("quantile", codes, values, size=1, q=0.9, method="lower")
    expected = np.quantile(np.arange(n, dtype=np.float64), 0.9, method="lower")
    assert float(np.asarray(got.astype(jnp.float32))[0]) == expected


class TestBf16Accumulation:
    """bf16/f16 mantissas cannot count past 256; every additive path must
    accumulate in f32 (kernels._acc_dtype) while presenting the input dtype.
    Regression for the round-1 advisor finding (nanmean of 2000 bf16 values
    returned the saturated partial instead of the mean)."""

    N = 2000  # far beyond bf16's exact-integer range

    def _data(self, dtype):
        import jax.numpy as jnp

        x = jnp.linspace(0.0, 1.0, self.N).astype(dtype)
        codes = np.zeros(self.N, dtype=np.int64)
        return x, codes

    @pytest.mark.parametrize("dtype_name", ["bfloat16", "float16"])
    @pytest.mark.parametrize(
        "func,expect,tol",
        [("nanmean", 0.5, 0.01), ("nansum", 1000.0, 10.0),
         ("nanvar", 1 / 12, 0.005), ("nanstd", (1 / 12) ** 0.5, 0.01)],
    )
    def test_eager(self, dtype_name, func, expect, tol):
        import jax.numpy as jnp

        from flox_tpu import groupby_reduce

        x, codes = self._data(jnp.dtype(dtype_name))
        out, _ = groupby_reduce(x, codes, func=func)
        assert str(out.dtype) == dtype_name  # result dtype contract kept
        assert abs(float(np.asarray(out.astype(jnp.float32))[0]) - expect) < tol

    @pytest.mark.parametrize("impl", ["scatter", "matmul", "pallas"])
    def test_segment_sum_impls(self, impl):
        import jax.numpy as jnp

        import flox_tpu

        x, codes = self._data(jnp.bfloat16)
        with flox_tpu.set_options(segment_sum_impl=impl):
            out = kernels.generic_kernel("nansum", codes, x, size=1)
        assert abs(float(np.asarray(out.astype(jnp.float32))[0]) - 1000.0) < 10.0

    def test_pallas_returns_f32_accumulator(self):
        import jax.numpy as jnp

        from flox_tpu.pallas_kernels import segment_sum_pallas

        x, codes = self._data(jnp.bfloat16)
        out = segment_sum_pallas(x[:, None] * jnp.ones((1, 128), jnp.bfloat16),
                                 codes, 1, interpret=True)
        assert out.dtype == jnp.float32
        assert abs(float(out[0, 0]) - 1000.0) < 1.0

    @pytest.mark.parametrize("method", ["map-reduce", "cohorts"])
    @pytest.mark.parametrize("func,expect,tol",
                             [("nanmean", 0.5, 0.01), ("nanvar", 1 / 12, 0.005)])
    def test_mesh_intermediates_travel_f32(self, method, func, expect, tol):
        import jax.numpy as jnp

        from flox_tpu import groupby_reduce
        from flox_tpu.parallel import make_mesh

        x, codes = self._data(jnp.bfloat16)
        out, _ = groupby_reduce(x, codes, func=func, method=method, mesh=make_mesh(8))
        assert str(out.dtype) == "bfloat16"
        assert abs(float(np.asarray(out.astype(jnp.float32))[0]) - expect) < tol

    def test_cumsum_running_sum(self):
        import jax.numpy as jnp

        from flox_tpu import groupby_scan

        x, codes = self._data(jnp.bfloat16)
        out = groupby_scan(x, codes, func="nancumsum")
        assert str(out.dtype) == "bfloat16"
        assert abs(float(np.asarray(out.astype(jnp.float32))[-1]) - 1000.0) < 10.0

    def test_int_nan_fill_promotion_survives(self):
        # the cast-back must not undo the NaN-fill promotion for int data
        vals = np.array([1, 2, 3], dtype=np.int32)
        codes = np.array([0, 0, 0])
        out = np.asarray(kernels.generic_kernel("nansum", codes, vals, size=2, fill_value=np.nan))
        assert out.dtype.kind == "f" and out[0] == 6 and np.isnan(out[1])


class TestFusedNanmean:
    """Single-pass nanmean on the marker paths: counts come from
    rowcount(codes) - nan_c so the data streams HBM once."""

    def _case(self):
        # float32: the pallas path only lowers f32/bf16, and the whole point
        # is exercising the FUSED kernels, not a silent scatter fallback
        rng = np.random.default_rng(0)
        n, k, size = 4000, 16, 12
        data = rng.normal(size=(k, n)).astype(np.float32)
        data[:, ::7] = np.nan
        data[0, 5] = np.inf
        data[1, 6] = -np.inf
        data[2, 10] = np.inf
        data[2, 11] = -np.inf
        codes = rng.integers(0, size, n)
        import warnings

        out = np.empty((k, size))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for g in range(size):
                out[:, g] = np.nanmean(data[:, codes == g].astype(np.float64), axis=1)
        return data, codes, size, out

    @pytest.mark.parametrize("impl", ["scatter", "matmul", "pallas"])
    def test_vs_oracle_with_nonfinite(self, impl):
        import flox_tpu
        from flox_tpu.kernels import _segment_sum_impl
        import jax.numpy as jnp

        data, codes, size, expected = self._case()
        with flox_tpu.set_options(segment_sum_impl=impl):
            # guard against vacuous fallback: the policy must resolve to the
            # impl under test for this f32 workload
            assert _segment_sum_impl(jnp.asarray(data).T, size) == impl or impl == "scatter"
            got = np.asarray(kernels.generic_kernel("nanmean", codes, data, size=size))
        np.testing.assert_allclose(got, expected, rtol=2e-6, atol=2e-6, equal_nan=True)

    @pytest.mark.parametrize("impl", ["matmul", "pallas"])
    def test_impls_match_scatter_exactly_for_counts(self, impl):
        # empty groups and all-NaN groups must behave identically to scatter
        import flox_tpu

        # >= 8 rows so the pallas size guard does not silently fall back
        vals = np.tile(np.array([1.0, np.nan, np.nan, 4.0], dtype=np.float32), 4)
        codes = np.tile(np.array([0, 1, 1, 0]), 4)
        with flox_tpu.set_options(segment_sum_impl="scatter"):
            ref = np.asarray(kernels.generic_kernel("nanmean", codes, vals, size=3))
        with flox_tpu.set_options(segment_sum_impl=impl):
            got = np.asarray(kernels.generic_kernel("nanmean", codes, vals, size=3))
        np.testing.assert_allclose(got, ref, equal_nan=True)
        assert got[0] == 2.5 and np.isnan(got[1]) and np.isnan(got[2])

    def test_skipna_reapply_keeps_inf_rules(self):
        from flox_tpu.utils import reapply_nonfinite
        import jax.numpy as jnp

        sums = jnp.array([1.0, 2.0, 3.0, 4.0])
        nan_c = jnp.array([1.0, 0.0, 0.0, 1.0])
        pos_c = jnp.array([0.0, 1.0, 1.0, 0.0])
        neg_c = jnp.array([0.0, 0.0, 1.0, 0.0])
        out = np.asarray(reapply_nonfinite(sums, nan_c, pos_c, neg_c, skipna=True))
        # NaN markers ignored; +inf -> inf; ±inf -> NaN
        assert out[0] == 1.0 and np.isposinf(out[1]) and np.isnan(out[2]) and out[3] == 4.0


class TestFusedVariance:
    """The variance family shares the fused marker-count sum (one data pass
    for total+counts; the dev² pass follows)."""

    @pytest.mark.parametrize("impl", ["scatter", "matmul", "pallas"])
    @pytest.mark.parametrize("func", ["nanvar", "nanstd"])
    def test_vs_oracle(self, impl, func):
        import warnings

        import flox_tpu

        rng = np.random.default_rng(1)
        data = rng.normal(size=(16, 4000)).astype(np.float32)
        data[:, ::7] = np.nan
        codes = rng.integers(0, 12, 4000)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            base = np.stack(
                [np.nanvar(data[:, codes == g].astype(np.float64), axis=1) for g in range(12)], -1
            )
        expected = np.sqrt(base) if func == "nanstd" else base
        with flox_tpu.set_options(segment_sum_impl=impl):
            got = np.asarray(kernels.generic_kernel(func, codes, data, size=12))
        np.testing.assert_allclose(got, expected, rtol=2e-4, atol=1e-6, equal_nan=True)

    @pytest.mark.parametrize("impl", ["matmul", "pallas"])
    def test_var_chunk_triple_matches_scatter(self, impl):
        import flox_tpu

        rng = np.random.default_rng(2)
        data = rng.normal(size=(4, 512)).astype(np.float32)
        data[:, ::5] = np.nan
        codes = rng.integers(0, 6, 512)
        with flox_tpu.set_options(segment_sum_impl="scatter"):
            ref = kernels.generic_kernel("var_chunk", codes, data, size=6, skipna=True)
        with flox_tpu.set_options(segment_sum_impl=impl):
            got = kernels.generic_kernel("var_chunk", codes, data, size=6, skipna=True)
        for a, b in zip(ref.arrays, got.arrays):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-5, atol=1e-6)


def test_var_int_dtype_request_keeps_nan_mask():
    # review regression: the NaN mask must come from the PRE-cast data — an
    # int dtype request would destroy NaNs before the mask sees them
    out = np.asarray(
        kernels.generic_kernel(
            "nanvar", np.array([0, 0, 0]), np.array([1.0, np.nan, 3.0]), size=1, dtype=np.int32
        )
    )
    assert abs(out[0] - 1.0) < 1e-12
    ch = kernels.generic_kernel(
        "var_chunk", np.array([0, 0, 0]), np.array([1.0, np.nan, 3.0]),
        size=1, dtype=np.int32, skipna=True,
    )
    assert float(np.asarray(ch.arrays[2])[0]) == 2.0


class TestRadixSelectQuantile:
    """quantile_impl="select": sort-free MSB radix bisection must be
    BIT-IDENTICAL to the two-key-sort path (both produce exact order
    statistics, then share the interpolation code)."""

    METHODS = ("linear", "lower", "higher", "nearest", "midpoint",
               "hazen", "weibull", "interpolated_inverted_cdf",
               "median_unbiased", "normal_unbiased")

    def _both(self, func, codes, data, size, **kw):
        import flox_tpu

        a = np.asarray(kernels.generic_kernel(func, codes, data, size=size, **kw))
        with flox_tpu.set_options(quantile_impl="select"):
            b = np.asarray(kernels.generic_kernel(func, codes, data, size=size, **kw))
        return a, b

    @pytest.mark.parametrize("method", METHODS)
    @pytest.mark.parametrize("func", ["quantile", "nanquantile"])
    def test_all_methods_bit_exact(self, func, method):
        rng = np.random.default_rng(11)
        n = 3000
        codes = rng.integers(0, 6, n)
        data = np.round(rng.normal(size=n), 2)  # heavy duplicates
        # NaNs confined to groups 0-1: propagate mode ("quantile") must
        # still select REAL values in groups 2-5 — NaN everywhere would
        # make the skipna=False leg vacuously pass on all-NaN outputs
        nan_rows = (rng.random(n) < 0.4) & (codes <= 1)
        data[nan_rows] = np.nan
        data[3], data[9] = np.inf, -np.inf
        a, b = self._both(func, codes, data, 6, q=0.7, method=method)
        np.testing.assert_array_equal(a, b)
        # groups 2-5 hold no NaN values, so even propagate mode must have
        # selected real values there (the comparison is not all-NaN-vs-all-NaN)
        assert not np.isnan(a[2:]).any()

    def test_vector_q_2d_f32(self):
        rng = np.random.default_rng(12)
        codes = rng.integers(0, 5, 700)
        data = rng.normal(size=(3, 700)).astype(np.float32)
        data[:, rng.random(700) < 0.2] = np.nan
        a, b = self._both("nanquantile", codes, data, 5, q=[0.0, 0.25, 0.9, 1.0])
        np.testing.assert_array_equal(a, b)

    def test_empty_and_allnan_groups(self):
        codes = np.array([0, 0, 2, 2, 3])
        data = np.array([1.0, 2.0, np.nan, np.nan, 5.0])
        a, b = self._both("nanquantile", codes, data, 5, q=0.5)
        np.testing.assert_array_equal(a, b)
        assert np.isnan(b[[1, 2, 4]]).all()  # empty g1/g4, all-NaN g2
        np.testing.assert_allclose(b[[0, 3]], [1.5, 5.0])

    def test_bf16_sixteen_bit_radix(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(13)
        codes = rng.integers(0, 4, 400)
        data = jnp.asarray(rng.normal(size=400), jnp.bfloat16)
        a, b = self._both("nanquantile", codes, data, 4, q=0.5)
        np.testing.assert_array_equal(
            np.asarray(a, np.float32), np.asarray(b, np.float32)
        )

    def test_median_and_missing_labels(self):
        rng = np.random.default_rng(14)
        codes = rng.integers(-1, 4, 900)  # -1 = missing, must drop out
        data = rng.normal(size=900)
        a, b = self._both("nanmedian", codes, data, 4)
        np.testing.assert_array_equal(a, b)

    def test_oracle_linear(self):
        # independent anchor: select matches np.nanquantile directly
        import flox_tpu

        rng = np.random.default_rng(15)
        codes = rng.integers(0, 3, 500)
        data = rng.normal(size=500)
        with flox_tpu.set_options(quantile_impl="select"):
            got = np.asarray(
                kernels.generic_kernel("nanquantile", codes, data, size=3, q=0.3)
            )
        want = np.array([np.nanquantile(data[codes == g], 0.3) for g in range(3)])
        np.testing.assert_allclose(got, want, rtol=1e-12)

    @pytest.mark.parametrize("dt", [np.int32, np.int64, np.int8, np.uint16])
    def test_integer_dtype_request_bit_exact(self, dt):
        # an explicit integer dtype skips the float cast: the monotonic key
        # must order two's-complement negatives correctly (review finding)
        rng = np.random.default_rng(21)
        codes = rng.integers(0, 4, 600)
        lo = -120 if np.issubdtype(dt, np.signedinteger) else 0
        data = rng.integers(lo, 120, 600).astype(dt)
        for method in ("lower", "linear"):
            a, b = self._both(
                "quantile", codes, data, 4, q=0.4, method=method, dtype=dt
            )
            np.testing.assert_array_equal(a, b)
