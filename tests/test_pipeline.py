"""Unit tests for the streaming pipeline machinery (flox_tpu/pipeline.py)
and the cache registry contract (flox_tpu/cache.py).

The streaming-level guarantees (prefetch on/off bit-identity per entry
point, error propagation through real streams) live in test_streaming.py;
this file pins the building blocks: in-order bounded prefetch, teardown,
donation probing, and that ``clear_all`` really empties every module-level
cache it names.
"""

from __future__ import annotations

import ast
import importlib
import inspect
import textwrap
import threading
import time

import numpy as np
import pytest

from flox_tpu.pipeline import DispatchThrottle, _SlabPrefetcher, stream_slabs


class TestSlabPrefetcher:
    def test_delivers_in_order_under_concurrency(self):
        import random

        rng = random.Random(0)
        delays = [rng.uniform(0, 0.01) for _ in range(40)]

        def stage(i):
            time.sleep(delays[i])
            return i

        assert list(_SlabPrefetcher(stage, range(40), depth=4)) == list(range(40))

    def test_bounded_in_flight(self):
        in_flight = []
        peak = [0]
        lock = threading.Lock()

        def stage(i):
            with lock:
                in_flight.append(i)
                peak[0] = max(peak[0], len(in_flight))
            time.sleep(0.005)
            with lock:
                in_flight.remove(i)
            return i

        consumed = []
        for item in _SlabPrefetcher(stage, range(20), depth=3):
            consumed.append(item)
            time.sleep(0.002)
        assert consumed == list(range(20))
        # depth staging threads + nothing runaway
        assert peak[0] <= 3

    def test_error_surfaces_at_position_and_tears_down(self):
        def stage(i):
            if i == 3:
                raise ValueError("bad slab 3")
            return i

        pf = _SlabPrefetcher(stage, range(10), depth=2)
        got = []
        with pytest.raises(ValueError, match="bad slab 3"):
            for item in pf:
                got.append(item)
        assert got == [0, 1, 2]
        assert pf._pool is None  # shut down, nothing left staging

    def test_close_midstream_leaves_no_threads(self):
        def stage(i):
            time.sleep(0.005)
            return i

        pf = _SlabPrefetcher(stage, range(100), depth=4)
        assert next(pf) == 0
        pf.close()
        time.sleep(0.1)
        assert not [t for t in threading.enumerate() if "flox-tpu-stage" in t.name]


class TestStreamSlabs:
    @staticmethod
    def _materialize(it):
        # snapshot per-slab state DURING iteration: stream_slabs drops the
        # device references once the consumer moves on (no HBM pinning)
        return [
            (s.start, s.stop, np.asarray(s.data), np.asarray(s.codes),
             s.codes_host, None if s.offset is None else int(s.offset))
            for s in it
        ]

    def test_pad_and_tail(self):
        codes = np.arange(10, dtype=np.int32)
        data = np.arange(10.0)
        slabs = self._materialize(stream_slabs(
            lambda s, e: data[s:e], codes, n=10, batch_len=4, lead_shape=(),
            prefetch=0, with_offset=True,
        ))
        assert [(s[0], s[1]) for s in slabs] == [(0, 4), (4, 8), (8, 10)]
        # padded tail: data zero-filled, codes -1-filled, device shape constant
        assert all(s[2].shape == (4,) for s in slabs)
        assert slabs[-1][2].tolist() == [8.0, 9.0, 0.0, 0.0]
        assert slabs[-1][3].tolist() == [8, 9, -1, -1]
        # codes_host stays the unpadded view
        assert slabs[-1][4].tolist() == [8, 9]
        assert slabs[-1][5] == 8

    def test_no_pad_ragged_tail_and_reverse(self):
        codes = np.arange(10, dtype=np.int32)
        data = np.arange(10.0)
        slabs = self._materialize(stream_slabs(
            lambda s, e: data[s:e], codes, n=10, batch_len=4, lead_shape=(),
            prefetch=2, pad=False, reverse=True,
        ))
        assert [(s[0], s[1]) for s in slabs] == [(8, 10), (4, 8), (0, 4)]
        assert slabs[0][2].shape == (2,)  # ragged tail, streamed first

    def test_prefetched_matches_sync_bytes(self):
        rng = np.random.default_rng(0)
        data = rng.normal(size=(3, 100))
        codes = rng.integers(0, 5, 100).astype(np.int32)

        def collect(depth):
            return [
                (np.asarray(s.data).tobytes(), np.asarray(s.codes).tobytes())
                for s in stream_slabs(
                    lambda st, e: data[:, st:e], codes, n=100, batch_len=33,
                    lead_shape=(3,), prefetch=depth,
                )
            ]

        assert collect(0) == collect(3)

    @pytest.mark.parametrize("depth", [0, 2])
    def test_skip_drops_leading_stream_order(self, depth):
        # checkpoint resume: skip=k drops the first k slabs in STREAM order
        codes = np.arange(10, dtype=np.int32)
        data = np.arange(10.0)

        def starts(**kw):
            return [
                s.start for s in stream_slabs(
                    lambda st, e: data[st:e], codes, n=10, batch_len=4,
                    lead_shape=(), prefetch=depth, **kw,
                )
            ]

        assert starts(skip=1) == [4, 8]
        # reversed streams: the "first" slabs are the trailing batches
        assert starts(skip=1, reverse=True, pad=False) == [4, 0]
        assert starts(skip=3) == []

    @pytest.mark.parametrize("depth", [0, 2])
    def test_loader_contract_shape_violation(self, depth):
        # ISSUE 3 satellite: a drifting slab shape raises a clear ValueError
        # naming the slab range, not a cryptic XLA shape error mid-step
        codes = np.arange(100, dtype=np.int32)
        data = np.arange(100.0)

        def bad(s, e):
            return np.zeros(7) if s == 40 else data[s:e]

        with pytest.raises(ValueError, match=r"loader contract.*\[40:60\)"):
            for _ in stream_slabs(
                bad, codes, n=100, batch_len=20, lead_shape=(), prefetch=depth,
            ):
                pass

    def test_loader_contract_dtype_violation(self):
        codes = np.arange(100, dtype=np.int32)
        data = np.arange(100.0)

        def bad(s, e):
            sl = data[s:e]
            return sl.astype(np.float32) if s >= 60 else sl

        with pytest.raises(ValueError, match=r"\[60:80\).*float32.*float64"):
            for _ in stream_slabs(
                bad, codes, n=100, batch_len=20, lead_shape=(), prefetch=0,
            ):
                pass


def test_dispatch_throttle_reads_option_and_syncs():
    import flox_tpu

    with flox_tpu.set_options(stream_dispatch_depth=3):
        th = DispatchThrottle()
    assert th.depth == 3
    import jax.numpy as jnp

    x = jnp.ones(4)
    for _ in range(7):
        th.tick(x)  # must not raise; 0/None carries are ignored
    DispatchThrottle(depth=0).tick(x)
    DispatchThrottle(depth=2).tick(None)


def test_donation_probe_memoized_and_cleared():
    import flox_tpu.cache
    from flox_tpu import pipeline

    flox_tpu.cache.clear_all()
    assert pipeline._DONATION_OK == {}
    pipeline.donation_supported()
    assert len(pipeline._DONATION_OK) == 1  # probed once, memoized
    flox_tpu.cache.clear_all()
    assert pipeline._DONATION_OK == {}
    # forced modes bypass the probe
    import flox_tpu as ft

    with ft.set_options(stream_donate="off"):
        assert pipeline.donation_supported() is False
    with ft.set_options(stream_donate="on"):
        assert pipeline.donation_supported() is True


def test_stream_option_validation():
    import flox_tpu

    with pytest.raises(ValueError):
        flox_tpu.set_options(stream_prefetch=-1)
    with pytest.raises(ValueError):
        flox_tpu.set_options(stream_dispatch_depth=-2)
    with pytest.raises(ValueError):
        flox_tpu.set_options(stream_donate="maybe")
    # resilience knobs validate at set time too (the full invalid-value
    # matrix lives in tests/test_resilience.py::TestOptionValidation)
    with pytest.raises(ValueError):
        flox_tpu.set_options(stream_retries=-1)
    with pytest.raises(ValueError):
        flox_tpu.set_options(stream_backoff=-0.5)
    with pytest.raises(ValueError):
        flox_tpu.set_options(stream_checkpoint_every=-1)
    with flox_tpu.set_options(stream_prefetch=0, stream_dispatch_depth=0,
                              stream_donate="off", stream_retries=0,
                              stream_backoff=0.0, stream_slab_timeout=0.0,
                              stream_checkpoint_every=0,
                              stream_checkpoint_path=None):
        pass


def test_clear_all_empties_every_named_cache():
    """Regression (ISSUE 2 satellite): ``clear_all`` must empty every
    module-level cache it names — introspected from its own source, so a
    new cache import without the matching ``.clear()`` fails here."""
    import flox_tpu.cache as cache

    src = textwrap.dedent(inspect.getsource(cache.clear_all))
    tree = ast.parse(src)
    named = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = importlib.import_module(
                ("." * node.level) + (node.module or ""), package="flox_tpu"
            )
            for alias in node.names:
                # alias.name is the attribute actually bound (an asname
                # only renames it in clear_all's scope); a submodule
                # import may not have set the parent attribute yet
                named.append((mod, alias.name))
    assert len(named) >= 7, "clear_all no longer names the known caches?"

    def _resolve(mod, name):
        try:
            return getattr(mod, name)
        except AttributeError:
            return importlib.import_module(f"{mod.__name__}.{name}")

    from flox_tpu.cache import LRUCache

    def _module_tables(m):
        # a subsystem module delegated to via its own clear() (resident
        # dataset registry, durable store table): its state lives in
        # module-level _UPPER_SNAKE dict tables (the FLX008 shape)
        return [v for k, v in vars(m).items()
                if isinstance(v, dict) and k.isupper()]

    # populate what can be populated artificially, then clear
    for mod, name in named:
        obj = _resolve(mod, name)
        if inspect.ismodule(obj):
            assert callable(getattr(obj, "clear", None)), (
                f"clear_all imports module {obj.__name__} without a clear()"
            )
            for tbl in _module_tables(obj):
                tbl[("__clear_all_probe__", name)] = object()
        elif isinstance(obj, (dict, LRUCache)):
            obj[("__clear_all_probe__", name)] = object()
        elif isinstance(obj, list):
            for i in range(len(obj)):
                obj[i] = 1234
        elif hasattr(obj, "inc") and hasattr(obj, "snapshot"):
            # telemetry.MetricsRegistry (ISSUE 4): bypasses the enabled()
            # gate on purpose — we are testing the reset, not the gate
            obj.inc("__clear_all_probe__")
        elif hasattr(obj, "records") and hasattr(obj, "append"):
            # telemetry._FlightRecorder (ISSUE 8): the bounded ring
            obj.append({"type": "event", "name": "__clear_all_probe__"})
    cache.clear_all()

    checked = 0
    for mod, name in named:
        obj = _resolve(mod, name)
        if inspect.ismodule(obj):
            for tbl in _module_tables(obj):
                assert tbl == {}, (
                    f"a table in {obj.__name__} not emptied by clear_all"
                )
            checked += 1
        elif isinstance(obj, dict):
            assert obj == {}, f"{mod.__name__}.{name} not emptied by clear_all"
            checked += 1
        elif isinstance(obj, LRUCache):  # the compiled-program LRUs (ISSUE 7)
            assert len(obj) == 0, f"{mod.__name__}.{name} not emptied by clear_all"
            checked += 1
        elif isinstance(obj, list):
            assert all(v == 0 for v in obj), f"{mod.__name__}.{name} not reset"
            checked += 1
        elif hasattr(obj, "cache_info"):  # functools.lru_cache wrapper
            assert obj.cache_info().currsize == 0, f"{mod.__name__}.{name} not cleared"
            checked += 1
        elif hasattr(obj, "inc") and hasattr(obj, "snapshot"):
            assert obj.snapshot() == {}, f"{mod.__name__}.{name} not reset"
            checked += 1
        elif hasattr(obj, "records") and hasattr(obj, "append"):
            assert len(obj) == 0, f"{mod.__name__}.{name} not emptied by clear_all"
            checked += 1
        else:
            raise AssertionError(
                f"clear_all names {mod.__name__}.{name} of type {type(obj)!r} "
                "— teach this test how to verify it empties"
            )
    assert checked == len(named)
