"""Sparse (BCOO) grouped reductions vs the dense path (reference:
aggregate_sparse semantics, tests via dense equivalence)."""

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from flox_tpu import groupby_reduce

RNG = np.random.default_rng(21)

FUNCS = ["sum", "nansum", "min", "max", "nanmin", "nanmax", "mean", "nanmean", "count"]


@pytest.fixture(params=["1d", "2d", "with-nan", "nan-labels"])
def case(request):
    n, size = 60, 4
    codes = RNG.integers(0, size, n).astype(np.int64)
    dense = np.round(RNG.normal(size=(3, n)), 1)
    dense[RNG.random((3, n)) < 0.6] = 0.0  # sparsity
    if request.param == "1d":
        dense = dense[0]
    elif request.param == "with-nan":
        dense[..., RNG.random(n) < 0.1] = np.nan
    elif request.param == "nan-labels":
        codes[RNG.random(n) < 0.2] = -1
    return dense, codes, size


@pytest.mark.parametrize("func", FUNCS)
def test_sparse_matches_dense(case, func):
    dense, codes, size = case
    mat = jsparse.BCOO.fromdense(jnp.asarray(dense))
    got, groups = groupby_reduce(mat, codes, func=func)
    expected, groups2 = groupby_reduce(dense, codes, func=func, engine="jax")
    np.testing.assert_array_equal(np.asarray(groups), np.asarray(groups2))
    np.testing.assert_allclose(
        np.asarray(got).astype(float), np.asarray(expected).astype(float),
        rtol=1e-10, atol=1e-12, equal_nan=True,
    )


def test_sparse_expected_groups():
    dense = np.array([1.0, 0.0, 2.0, 0.0])
    codes = np.array([0, 0, 2, 2])
    mat = jsparse.BCOO.fromdense(jnp.asarray(dense))
    got, groups = groupby_reduce(mat, codes, func="sum", expected_groups=np.array([0, 1, 2]))
    np.testing.assert_allclose(np.asarray(got), [1.0, 0.0, 2.0])
    np.testing.assert_array_equal(groups, [0, 1, 2])


def test_sparse_unsupported_func():
    mat = jsparse.BCOO.fromdense(jnp.ones((4,)))
    with pytest.raises(NotImplementedError, match="sparse grouped"):
        groupby_reduce(mat, np.array([0, 0, 1, 1]), func="var")


def test_sparse_int_minmax_empty_group_promotes():
    # empty group with default NaN fill must promote, not write garbage ints
    dense = np.array([3, 0, 5, 0], dtype=np.int32)
    mat = jsparse.BCOO.fromdense(jnp.asarray(dense))
    got, _ = groupby_reduce(mat, np.array([0, 0, 2, 2]), func="min",
                            expected_groups=np.array([0, 1, 2]))
    got = np.asarray(got)
    assert got.dtype.kind == "f" and np.isnan(got[1])
    np.testing.assert_allclose(got[[0, 2]], [0.0, 0.0])  # implicit zeros win the min


def test_sparse_sum_fill_value():
    dense = np.array([1.0, 0.0, 2.0, 0.0])
    mat = jsparse.BCOO.fromdense(jnp.asarray(dense))
    got, _ = groupby_reduce(mat, np.array([0, 0, 2, 2]), func="sum",
                            expected_groups=np.array([0, 1, 2]), fill_value=-999.0)
    np.testing.assert_allclose(np.asarray(got), [1.0, -999.0, 2.0])


def test_sparse_rejects_unsupported_kwargs():
    mat = jsparse.BCOO.fromdense(jnp.ones((4,)))
    with pytest.raises(NotImplementedError, match="min_count"):
        groupby_reduce(mat, np.array([0, 0, 1, 1]), func="nansum", min_count=2)


def test_sparse_int_sum_fill():
    # integer data: NaN-injection must not be constructed for int dtypes
    mat = jsparse.BCOO.fromdense(jnp.asarray(np.array([3, 0, 5, 0], dtype=np.int32)))
    got, _ = groupby_reduce(mat, np.array([0, 0, 2, 2]), func="sum",
                            expected_groups=np.arange(3), fill_value=-999)
    np.testing.assert_array_equal(np.asarray(got), [3, -999, 5])


class TestSparseReindex:
    """Sparse-COO reindex for huge group spaces (reference reindex.py:106-157;
    VERDICT missing #6). Zero fills produce a device-ready jax BCOO; non-zero
    fills a host COO."""

    def test_bcoo_zero_fill(self):
        from flox_tpu.reindex import ReindexArrayType, reindex_

        found = pd.Index([3, 10, 250000])
        target = pd.RangeIndex(1_000_000)
        vals = np.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])
        out = reindex_(vals, found, target, fill_value=0.0,
                       array_type=ReindexArrayType.SPARSE_COO)
        from jax.experimental import sparse as jsparse

        assert isinstance(out, jsparse.BCOO)
        assert out.shape == (2, 1_000_000)
        dense_cols = np.asarray(out.todense()[:, [3, 10, 250000]])
        np.testing.assert_allclose(dense_cols, vals)
        assert float(np.asarray(out.todense()[:, :3]).sum()) == 0.0

    def test_host_coo_nan_fill(self):
        from flox_tpu.reindex import HostCOO, reindex_sparse_coo

        found = pd.Index([0, 5])
        target = pd.RangeIndex(100)
        vals = np.array([1.0, 2.0])
        out = reindex_sparse_coo(vals, found, target, fill_value=np.nan)
        assert isinstance(out, HostCOO)
        dense = out.todense()
        assert dense.shape == (100,)
        assert dense[0] == 1.0 and dense[5] == 2.0
        assert np.isnan(dense[1]) and out.nnz == 2

    def test_missing_fill_required(self):
        from flox_tpu.reindex import reindex_sparse_coo

        with pytest.raises(ValueError, match="fill_value"):
            reindex_sparse_coo(np.ones(2), pd.Index([0, 1]), pd.RangeIndex(5),
                               fill_value=None)

    def test_reorder_only_no_fill_needed(self):
        from flox_tpu.reindex import reindex_sparse_coo

        out = reindex_sparse_coo(np.array([1.0, 2.0, 3.0]), pd.Index([2, 0, 1]),
                                 pd.Index([0, 1, 2]), fill_value=None)
        np.testing.assert_allclose(np.asarray(out.todense()), [2.0, 3.0, 1.0])

    def test_strategy_accepts_sparse(self):
        from flox_tpu.reindex import ReindexArrayType, ReindexStrategy

        s = ReindexStrategy(blockwise=False, array_type=ReindexArrayType.SPARSE_COO)
        assert s.array_type is ReindexArrayType.SPARSE_COO


def test_sparse_reindex_int_na_promotes():
    # review regression: NA fill on int data must promote to float, not
    # cast NaN into INT64_MIN garbage
    from flox_tpu import dtypes
    from flox_tpu.reindex import reindex_sparse_coo

    out = reindex_sparse_coo(np.array([1, 2]), pd.Index([0, 5]), pd.RangeIndex(8),
                             fill_value=dtypes.NA)
    dense = out.todense()
    assert dense.dtype.kind == "f"
    assert dense[0] == 1.0 and dense[5] == 2.0 and np.isnan(dense[1])
