"""Sparse (BCOO) grouped reductions vs the dense path (reference:
aggregate_sparse semantics, tests via dense equivalence)."""

import numpy as np
import pytest

import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from flox_tpu import groupby_reduce

RNG = np.random.default_rng(21)

FUNCS = ["sum", "nansum", "min", "max", "nanmin", "nanmax", "mean", "nanmean", "count"]


@pytest.fixture(params=["1d", "2d", "with-nan", "nan-labels"])
def case(request):
    n, size = 60, 4
    codes = RNG.integers(0, size, n).astype(np.int64)
    dense = np.round(RNG.normal(size=(3, n)), 1)
    dense[RNG.random((3, n)) < 0.6] = 0.0  # sparsity
    if request.param == "1d":
        dense = dense[0]
    elif request.param == "with-nan":
        dense[..., RNG.random(n) < 0.1] = np.nan
    elif request.param == "nan-labels":
        codes[RNG.random(n) < 0.2] = -1
    return dense, codes, size


@pytest.mark.parametrize("func", FUNCS)
def test_sparse_matches_dense(case, func):
    dense, codes, size = case
    mat = jsparse.BCOO.fromdense(jnp.asarray(dense))
    got, groups = groupby_reduce(mat, codes, func=func)
    expected, groups2 = groupby_reduce(dense, codes, func=func, engine="jax")
    np.testing.assert_array_equal(np.asarray(groups), np.asarray(groups2))
    np.testing.assert_allclose(
        np.asarray(got).astype(float), np.asarray(expected).astype(float),
        rtol=1e-10, atol=1e-12, equal_nan=True,
    )


def test_sparse_expected_groups():
    dense = np.array([1.0, 0.0, 2.0, 0.0])
    codes = np.array([0, 0, 2, 2])
    mat = jsparse.BCOO.fromdense(jnp.asarray(dense))
    got, groups = groupby_reduce(mat, codes, func="sum", expected_groups=np.array([0, 1, 2]))
    np.testing.assert_allclose(np.asarray(got), [1.0, 0.0, 2.0])
    np.testing.assert_array_equal(groups, [0, 1, 2])


def test_sparse_unsupported_func():
    mat = jsparse.BCOO.fromdense(jnp.ones((4,)))
    with pytest.raises(NotImplementedError, match="sparse grouped"):
        groupby_reduce(mat, np.array([0, 0, 1, 1]), func="var")


def test_sparse_int_minmax_empty_group_promotes():
    # empty group with default NaN fill must promote, not write garbage ints
    dense = np.array([3, 0, 5, 0], dtype=np.int32)
    mat = jsparse.BCOO.fromdense(jnp.asarray(dense))
    got, _ = groupby_reduce(mat, np.array([0, 0, 2, 2]), func="min",
                            expected_groups=np.array([0, 1, 2]))
    got = np.asarray(got)
    assert got.dtype.kind == "f" and np.isnan(got[1])
    np.testing.assert_allclose(got[[0, 2]], [0.0, 0.0])  # implicit zeros win the min


def test_sparse_sum_fill_value():
    dense = np.array([1.0, 0.0, 2.0, 0.0])
    mat = jsparse.BCOO.fromdense(jnp.asarray(dense))
    got, _ = groupby_reduce(mat, np.array([0, 0, 2, 2]), func="sum",
                            expected_groups=np.array([0, 1, 2]), fill_value=-999.0)
    np.testing.assert_allclose(np.asarray(got), [1.0, -999.0, 2.0])


def test_sparse_rejects_unsupported_kwargs():
    mat = jsparse.BCOO.fromdense(jnp.ones((4,)))
    with pytest.raises(NotImplementedError, match="min_count"):
        groupby_reduce(mat, np.array([0, 0, 1, 1]), func="nansum", min_count=2)


def test_sparse_int_sum_fill():
    # integer data: NaN-injection must not be constructed for int dtypes
    mat = jsparse.BCOO.fromdense(jnp.asarray(np.array([3, 0, 5, 0], dtype=np.int32)))
    got, _ = groupby_reduce(mat, np.array([0, 0, 2, 2]), func="sum",
                            expected_groups=np.arange(3), fill_value=-999)
    np.testing.assert_array_equal(np.asarray(got), [3, -999, 5])
